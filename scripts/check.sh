#!/usr/bin/env bash
# One-command pre-merge gate: lint + incremental mstcheck self-scan +
# the static-analysis fixture corpus and runtime leak-ledger tests.
#
#   scripts/check.sh            # everything (warm mstcheck run is ~10ms)
#   scripts/check.sh --quick    # sub-minute tier: lint + warm --changed
#                               # scan + fixture gate + chaos smoke
#   scripts/check.sh --no-cache # force a full (cold) self-scan
set -euo pipefail
cd "$(dirname "$0")/.."

QUICK=0
MSTCHECK_ARGS=()
for arg in "$@"; do
    if [ "$arg" = "--quick" ]; then
        QUICK=1
    else
        MSTCHECK_ARGS+=("$arg")
    fi
done

# 1. ruff — optional: the container image does not ship it, and the gate
#    must not require anything pip-installed.
if command -v ruff >/dev/null 2>&1; then
    echo "== ruff =="
    ruff check mlx_sharding_tpu/ tests/
else
    echo "== ruff == (not installed; skipping lint)"
fi

# 2. incremental self-scan: per-file results cached by content hash in
#    .mstcheck-cache.json, invalidated wholesale when the checker changes.
#    --quick narrows the parse to stale files only; global passes still
#    see the whole tree through cached facts.
echo "== mstcheck (incremental self-scan) =="
if [ "$QUICK" = 1 ]; then
    MSTCHECK_ARGS+=(--changed)
fi
python -m mlx_sharding_tpu.analysis mlx_sharding_tpu/ "${MSTCHECK_ARGS[@]+"${MSTCHECK_ARGS[@]}"}"

# 3. fixture gate + leak ledger: every rule fires on its known-bad
#    fixture, and the composed stack leaves zero live handles.
echo "== fixture corpus + resource ledger =="
env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_static_analysis.py tests/test_resource_ledger.py -q

# 4. sim smoke: a tiny seeded chaos campaign (3 hosts, storm + host
#    kill) through the REAL fleet stack in virtual time — all
#    invariants must hold. ~10s, zero wall-clock sleeps.
echo "== chaos campaign smoke =="
env JAX_PLATFORMS=cpu python -m mlx_sharding_tpu.sim.chaos --smoke

if [ "$QUICK" = 1 ]; then
    echo "check.sh: quick gates passed (<60s tier)"
else
    echo "check.sh: all gates passed"
fi
