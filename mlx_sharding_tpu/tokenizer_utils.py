"""Tokenizer runtime: incremental detokenization + stop-sequence machinery.

The reference borrows both from mlx_lm (TokenizerWrapper detokenizer,
SURVEY §2.2) and implements stop handling itself
(stopping_criteria ref: shard/openai_api.py:30-43; streaming partial-stop
buffering ref: shard/openai_api.py:436-505). Here both are first-party.

Works with any object exposing ``decode(list[int]) -> str`` (HF tokenizers
do); no network access is assumed.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence


class StreamingDetokenizer:
    """Incremental detokenizer emitting only *stable* UTF-8 text.

    Decodes a tail window starting at the last safe boundary; withholds
    segments that end in U+FFFD (a token split mid-codepoint — the byte-level
    BPE edge case called out in SURVEY §7 hard-parts (e))."""

    def __init__(self, tokenizer):
        self._tokenizer = tokenizer
        self.reset()

    # Region restart cap: decoding is O(region length) per token, so without
    # restarts a long newline-free output costs O(n²) total. Restarts keep
    # the last token as a decode prefix: tokenizers that strip leading
    # whitespace at sequence start (SentencePiece-family "▁word") strip it
    # from the prefix-only decode and the prefix+next decode equally, so the
    # emitted *difference* stays correct.
    MAX_REGION_TOKENS = 64
    # A region that never decodes cleanly (adversarial lone continuation
    # bytes) is force-dropped at this bound so per-token cost stays bounded.
    MAX_DIRTY_REGION_TOKENS = 256

    def reset(self):
        self.tokens: list[int] = []
        self._region_start = 0  # first token of the un-flushed decode region
        self._emitted = ""  # text already emitted from the current region
        self.text = ""  # all emitted text
        self.last_segment = ""

    def _restart_region(self):
        """Start a new region keeping the last token as decode prefix."""
        self._region_start = len(self.tokens) - 1
        self._emitted = self._tokenizer.decode(self.tokens[self._region_start :])

    def add_token(self, token: int):
        self.tokens.append(token)
        region = self.tokens[self._region_start :]
        decoded = self._tokenizer.decode(region)
        if decoded.endswith("�"):
            # Mid-codepoint; wait for more tokens — but never unboundedly.
            self.last_segment = ""
            if len(region) >= self.MAX_DIRTY_REGION_TOKENS:
                # drop the undecodable tail entirely
                self._region_start = len(self.tokens)
                self._emitted = ""
            return
        segment = decoded[len(self._emitted) :]
        self.last_segment = segment
        self.text += segment
        if decoded.endswith("\n") or len(region) >= self.MAX_REGION_TOKENS:
            self._restart_region()
        else:
            self._emitted = decoded

    def finalize(self):
        """Flush anything withheld (e.g. trailing U+FFFD bytes are dropped)."""
        region = self.tokens[self._region_start :]
        decoded = self._tokenizer.decode(region).rstrip("�")
        segment = decoded[len(self._emitted) :]
        self.last_segment = segment
        self.text += segment
        self._emitted = decoded


@dataclass
class StopCondition:
    stop_met: bool
    trim_length: int  # tokens to cut from the tail when stop was token-based


def stopping_criteria(
    tokens: Sequence[int],
    stop_id_sequences: Sequence[Sequence[int]],
    eos_token_id: int | None,
) -> StopCondition:
    """Token-level stop check, same contract as ref shard/openai_api.py:30-43:
    EOS stops with no trim; a matched stop sequence stops and trims itself."""
    if tokens and eos_token_id is not None and tokens[-1] == eos_token_id:
        return StopCondition(stop_met=True, trim_length=0)
    for stop_ids in stop_id_sequences:
        n = len(stop_ids)
        if n and len(tokens) >= n and list(tokens[-n:]) == list(stop_ids):
            return StopCondition(stop_met=True, trim_length=n)
    return StopCondition(stop_met=False, trim_length=0)


def sequence_overlap(s1: Sequence, s2: Sequence) -> bool:
    """True if some suffix of ``s1`` is a prefix of ``s2`` — used to buffer
    streamed text that might be the start of a stop sequence, so partial stop
    words are never emitted (ref: shard/openai_api.py:486-505 behavior)."""
    max_overlap = min(len(s1), len(s2))
    return any(s1[-i:] == s2[:i] for i in range(1, max_overlap + 1))
