"""Serving-resilience error types and deadline bookkeeping.

The reference implementation has no fault-tolerance story at all (SURVEY:
"no tests, no benchmarks, no fault tolerance"): a wedged engine hangs its
HTTP handler forever, an unbounded submit queue grows without limit under
overload, and a dead replica keeps receiving traffic. This module holds the
*shared vocabulary* of the resilience layer — structured, catchable error
types the scheduler/replica dispatcher raise and the HTTP layer maps to
status codes — kept dependency-free (no jax import) so every layer can use
it without cost:

- :class:`RequestTimeoutError` — a per-request deadline (TTFT, total
  generation, or inter-token stall watchdog) expired; HTTP 504.
- :class:`QueueFullError` — admission control rejected the request because
  the submit queue is at ``--max-queue``; HTTP 429 + ``Retry-After``.
- :class:`ReplicasUnavailableError` — every replica is circuit-broken;
  HTTP 503.
- :class:`ReplicaDrainingError` — the replica is being retired and is not
  accepting new requests; a ``QueueFullError`` subtype so the dispatcher
  retries elsewhere and a lone replica maps to HTTP 429 + ``Retry-After``.
- :class:`RequestMigratedError` — a draining replica ended this stream so
  it can continue elsewhere; carries a :class:`ResumeState` the dispatcher
  re-places on a healthy replica. Never reaches a client unless there is
  no migration target.

Deadline semantics (enforced by ``ContinuousBatcher``):

- ``ttft_timeout`` bounds submit → first token (queue wait + prefill +
  first compile). Requests still *queued* past this budget are shed by the
  scheduler before any prefill work is spent on them.
- ``request_timeout`` bounds submit → last token (total generation).
- ``stall_timeout`` is the inter-token watchdog: the longest the consumer
  will wait between consecutive token deliveries once the stream has
  started. It defaults to ``ttft_timeout`` when unset — if the budget was
  generous enough for queue+prefill+compile, it is generous enough for a
  decode block.

Expiry cancels the request through the existing ``cancelled`` path, so the
scheduler reclaims its slot/KV pages on its next tick; the waiting thread
is released immediately with the structured error rather than blocking on
a wedged engine.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Optional


class RequestTimeoutError(RuntimeError):
    """A per-request deadline expired. ``kind`` says which budget:

    - ``"ttft"``   — no first token within ``ttft_timeout`` of submission
    - ``"total"``  — generation exceeded ``request_timeout``
    - ``"stall"``  — the inter-token watchdog tripped mid-stream
    - ``"queue"``  — shed by the scheduler while still queued: its wait
      already exceeded the TTFT budget, so prefill would be wasted work
    """

    def __init__(self, kind: str, elapsed_s: float, budget_s: float):
        self.kind = kind
        self.elapsed_s = elapsed_s
        self.budget_s = budget_s
        super().__init__(
            f"request deadline expired ({kind}): {elapsed_s:.2f}s elapsed "
            f"against a {budget_s:.2f}s budget"
        )


class QueueFullError(RuntimeError):
    """Admission control rejected the request: the submit queue is at its
    ``--max-queue`` bound. Maps to HTTP 429 with ``Retry-After``."""

    def __init__(self, depth: int, max_queue: int, retry_after_s: float = 1.0):
        self.depth = depth
        self.max_queue = max_queue
        self.retry_after_s = retry_after_s
        super().__init__(
            f"server overloaded: {depth} requests already queued "
            f"(--max-queue {max_queue}); retry after {retry_after_s:.0f}s"
        )


class ReplicasUnavailableError(RuntimeError):
    """Every replica is circuit-broken (or excluded by failed retries) —
    there is nowhere to route the request. Maps to HTTP 503; when the
    dispatcher knows the earliest half-open retry ETA (the soonest any
    breaker re-admits a probe), ``retry_after_s`` carries it so the server
    can emit ``Retry-After`` instead of leaving the client to guess."""

    def __init__(self, message: str = "no replica available",
                 retry_after_s: Optional[float] = None):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class ReplicaDrainingError(QueueFullError):
    """The replica is draining (``ReplicaSet.drain`` / ``migrate_out``) and
    rejects new work. Subtype of :class:`QueueFullError` so the dispatcher's
    saturation handling applies unchanged: retry on another replica, no
    breaker strike, 429 + ``Retry-After`` if nothing else is available."""

    def __init__(self, retry_after_s: float = 1.0):
        self.depth = 0
        self.max_queue = 0
        self.retry_after_s = retry_after_s
        RuntimeError.__init__(
            self, "replica is draining and not accepting new requests"
        )


@dataclass
class ResumeState:
    """Everything needed to continue a partially-generated request on a
    different engine, captured when its stream is migrated off a replica.

    Kept dependency-free: ``prompt`` and the sampler fields hold whatever
    array-likes the producing engine recorded (numpy on the host side);
    ``block`` is an optional host-materialized ``kv_transfer.KVPageBlock``
    whose pages the target can import instead of re-prefilling. When
    ``block`` is ``None`` the target folds ``history`` back into the prompt
    and re-prefills — slower, but token-exact (``resume_keys`` /
    ``resume_recent`` carry the sampler PRNG chain and repetition window
    across the fold when the source captured them)."""

    prompt: object                 # original prompt token ids (pre-fold)
    history: list                  # tokens emitted since the last fold
    produced: int = 0              # tokens already delivered to the client
    block: object = None           # optional KVPageBlock (host-resident)
    resume_keys: object = None     # per-request sampler PRNG key row
    resume_recent: object = None   # repetition-penalty recent-token window


class RequestMigratedError(RuntimeError):
    """A replica ended this stream mid-flight so it can resume elsewhere
    (graceful drain). Carries the :class:`ResumeState`; the dispatcher
    re-places it and the client never observes the hop."""

    def __init__(self, state: ResumeState, reason: str = "replica draining"):
        self.state = state
        super().__init__(f"request migrated: {reason}")


class HandoffReadyError(RuntimeError):
    """A prefill-only stream completed its phase: the first token was
    delivered and the request's :class:`ResumeState` (KV page block +
    sampler rows) is ready to move to a decode replica. NOT a failure —
    the disaggregation coordinator catches it to run the handoff, and the
    dispatcher treats it as a successful prefill-replica exit (no breaker
    strike, no in-pool re-placement)."""

    def __init__(self, state: ResumeState):
        self.state = state
        super().__init__("prefill complete: ready for decode handoff")


@dataclass
class Deadlines:
    """Absolute-monotonic per-request deadlines, computed once at submit.

    ``None`` fields mean "unbounded" — the default, preserving the seed
    behavior when no flags/overrides are set."""

    submitted_at: float
    ttft_deadline: Optional[float] = None   # absolute: submit + ttft_timeout
    total_deadline: Optional[float] = None  # absolute: submit + request_timeout
    stall_timeout: Optional[float] = None   # relative: per-token watchdog

    @classmethod
    def start(
        cls,
        *,
        ttft_timeout: Optional[float] = None,
        request_timeout: Optional[float] = None,
        stall_timeout: Optional[float] = None,
        clock: Optional[Callable[[], float]] = None,
    ) -> "Deadlines":
        for name, v in (
            ("ttft_timeout", ttft_timeout),
            ("request_timeout", request_timeout),
            ("stall_timeout", stall_timeout),
        ):
            if v is not None and (
                isinstance(v, bool)  # bool is an int; a JSON `true` is not
                or not isinstance(v, (int, float))
                or v <= 0
            ):
                raise ValueError(f"{name} must be a positive number of seconds")
        # the absolute stamps must come from the SAME clock the consumer
        # loop compares them against (scheduler._consume's injected one) —
        # pass that clock here when it isn't the process monotonic source
        now = time.monotonic() if clock is None else clock()
        if stall_timeout is None:
            stall_timeout = ttft_timeout  # see module docstring
        return cls(
            submitted_at=now,
            ttft_deadline=None if ttft_timeout is None else now + ttft_timeout,
            total_deadline=(
                None if request_timeout is None else now + request_timeout
            ),
            stall_timeout=stall_timeout,
        )
