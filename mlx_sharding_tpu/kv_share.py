"""Layer-wise KV sharing maps (KVSharer, arXiv:2410.18517).

KVSharer's finding is counterintuitive: sharing the KV cache between the
*most dissimilar* layer pairs — not the most similar — preserves output
quality while cutting pool bytes roughly in proportion to the layers
merged. This module is the pure bookkeeping half of that idea:

- :class:`KVShareMap` — a canonical, hashable layer→group assignment.
  Pools allocate one physical (k, v) buffer per *group*; every layer
  reads/writes through the group indirection. The identity map (every
  layer its own group) is bit-exact with the unshared layout and hashes
  to ``None`` so legacy exported blocks stay importable.
- :func:`calibrate_share_map` — offline ranking of layer pairs by KV
  dissimilarity over a calibration batch, emitting the share map the
  ``cli/kv_share_calibrate.py`` tool writes to disk.

The map's ``share_hash`` joins the ``KVPageBlock`` integrity fingerprint
(kv_transfer.py): a block exported under one layout can never scatter
into a pool with a different one — the import fails closed with a
remediation hint instead of producing silently-wrong attention.

Sharing semantics (documented deviation from the paper's weight-level
trick): every layer still computes its own k/v *projection* for the
current tick, but non-owner layers attend over the owner's historical
KV plus their own current-tick row; only the owner layer's rows persist
into the pool. Greedy outputs under a calibrated map therefore differ
from unshared within a tolerance measured at calibration time — the
identity map is exact.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field
from typing import Optional, Sequence

FORMAT = "mst-kv-share-map-v1"


class ShareMapError(ValueError):
    """A share map failed validation or doesn't fit the engine geometry."""


def _canonical_groups(group_of: Sequence[int]) -> tuple[int, ...]:
    """Renumber group ids to first-appearance order so two maps with the
    same partition always compare (and hash) equal."""
    remap: dict[int, int] = {}
    out = []
    for g in group_of:
        if g not in remap:
            remap[g] = len(remap)
        out.append(remap[g])
    return tuple(out)


@dataclass(frozen=True)
class KVShareMap:
    """Layer→share-group assignment over one engine's local layer stack.

    ``group_of[layer] == group`` with group ids canonicalized to
    first-appearance order; the *owner* of a group is its lowest layer
    index (the layer whose rows physically persist)."""

    num_layers: int
    group_of: tuple[int, ...]
    meta: dict = field(default_factory=dict, compare=False, hash=False)

    def __post_init__(self):
        object.__setattr__(
            self, "group_of", _canonical_groups(tuple(self.group_of))
        )
        if self.num_layers < 1:
            raise ShareMapError("share map needs num_layers >= 1")
        if len(self.group_of) != self.num_layers:
            raise ShareMapError(
                f"share map lists {len(self.group_of)} layers but "
                f"num_layers={self.num_layers}"
            )

    # ------------------------------------------------------------ derived
    @property
    def num_groups(self) -> int:
        return max(self.group_of) + 1

    @property
    def is_identity(self) -> bool:
        return self.num_groups == self.num_layers

    @property
    def owner_layers(self) -> tuple[int, ...]:
        """Per group: the lowest layer index assigned to it (canonical
        ordering makes this exactly the first layer that names it)."""
        owners = [-1] * self.num_groups
        for layer, g in enumerate(self.group_of):
            if owners[g] < 0:
                owners[g] = layer
        return tuple(owners)

    @property
    def owner_mask(self) -> tuple[bool, ...]:
        """Per layer: does this layer's KV physically persist?"""
        owners = set(self.owner_layers)
        return tuple(layer in owners for layer in range(self.num_layers))

    @property
    def share_hash(self) -> Optional[str]:
        """Layout identity for export/import integrity checks.

        ``None`` for the identity map — the layout is byte-identical to
        the unshared pool, so legacy blocks (and blocks from unshared
        peers) compose without a flag-day."""
        if self.is_identity:
            return None
        payload = f"{FORMAT}:{self.num_layers}:{','.join(map(str, self.group_of))}"
        return hashlib.blake2b(payload.encode(), digest_size=16).hexdigest()

    def bytes_saved_fraction(self) -> float:
        """Fraction of KV pool bytes the map removes vs unshared."""
        return 1.0 - self.num_groups / self.num_layers

    # --------------------------------------------------------- validation
    def validate_for(self, num_layers: int) -> None:
        """Engine-geometry fit check with a remediation hint."""
        if num_layers != self.num_layers:
            raise ShareMapError(
                f"share map was calibrated for {self.num_layers} layers but "
                f"this engine stages {num_layers} local layers — recalibrate "
                f"with cli/kv_share_calibrate.py against this checkpoint/"
                f"stage split, or drop --kv-share-map"
            )

    # -------------------------------------------------------- constructors
    @classmethod
    def identity(cls, num_layers: int) -> "KVShareMap":
        return cls(num_layers=num_layers,
                   group_of=tuple(range(num_layers)))

    @classmethod
    def from_pairs(cls, num_layers: int,
                   pairs: Sequence[tuple[int, int]],
                   meta: Optional[dict] = None) -> "KVShareMap":
        """Build a map by merging ``pairs`` of layers into shared groups
        (union-find, so chained pairs coalesce)."""
        parent = list(range(num_layers))

        def find(x):
            while parent[x] != x:
                parent[x] = parent[parent[x]]
                x = parent[x]
            return x

        for a, b in pairs:
            if not (0 <= a < num_layers and 0 <= b < num_layers):
                raise ShareMapError(
                    f"share pair ({a}, {b}) out of range for "
                    f"{num_layers} layers"
                )
            ra, rb = find(a), find(b)
            if ra != rb:
                parent[max(ra, rb)] = min(ra, rb)
        return cls(num_layers=num_layers,
                   group_of=tuple(find(i) for i in range(num_layers)),
                   meta=dict(meta or {}))

    # --------------------------------------------------------------- disk
    def to_json(self) -> dict:
        return {
            "format": FORMAT,
            "num_layers": self.num_layers,
            "group_of": list(self.group_of),
            "share_hash": self.share_hash,
            "meta": self.meta,
        }

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_json(), f, indent=2, sort_keys=True)
            f.write("\n")

    @classmethod
    def load(cls, path: str) -> "KVShareMap":
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            raise ShareMapError(
                f"--kv-share-map {path!r} is not readable JSON: {e}"
            ) from e
        if not isinstance(doc, dict) or doc.get("format") != FORMAT:
            raise ShareMapError(
                f"--kv-share-map {path!r} is not a {FORMAT} artifact "
                f"(found format={doc.get('format') if isinstance(doc, dict) else type(doc).__name__!r}) "
                f"— emit one with cli/kv_share_calibrate.py"
            )
        try:
            m = cls(num_layers=int(doc["num_layers"]),
                    group_of=tuple(int(g) for g in doc["group_of"]),
                    meta=dict(doc.get("meta") or {}))
        except (KeyError, TypeError, ValueError) as e:
            raise ShareMapError(
                f"--kv-share-map {path!r} is malformed: {e}"
            ) from e
        stamped = doc.get("share_hash")
        if stamped is not None and stamped != m.share_hash:
            raise ShareMapError(
                f"--kv-share-map {path!r} stamped share_hash {stamped!r} "
                f"disagrees with its own group assignment (hash "
                f"{m.share_hash!r}) — the artifact was hand-edited; "
                f"recalibrate instead of patching the JSON"
            )
        return m


# ------------------------------------------------------------- calibration
def layer_kv_signatures(k, v):
    """Per-layer KV signature vectors from a dense calibration cache.

    ``k``/``v`` are the dense stacked-layer buffers ``(L, B, S, H, D)``
    (cache.py layout) after a calibration prefill. The signature is the
    per-layer mean KV direction — cheap, and enough to rank pairwise
    dissimilarity the way KVSharer's Euclidean ranking does."""
    import numpy as np

    k = np.asarray(k, dtype=np.float32)
    v = np.asarray(v, dtype=np.float32)
    L = k.shape[0]
    sigs = []
    for layer in range(L):
        kv = np.concatenate(
            [k[layer].reshape(-1), v[layer].reshape(-1)]
        )
        sigs.append(kv)
    return np.stack(sigs)


def rank_layer_pairs(k, v, valid_tokens: Optional[int] = None):
    """All layer pairs ranked MOST-dissimilar first.

    Returns ``[((a, b), dissimilarity), ...]`` with ``a < b`` and
    dissimilarity = 1 − cosine(sig_a, sig_b). KVSharer's core observation
    is that the *dissimilar* pairs are the safe ones to share."""
    import numpy as np

    k = np.asarray(k, dtype=np.float32)
    v = np.asarray(v, dtype=np.float32)
    if valid_tokens is not None:
        k = k[:, :, :valid_tokens]
        v = v[:, :, :valid_tokens]
    sigs = layer_kv_signatures(k, v)
    norms = np.linalg.norm(sigs, axis=1)
    norms = np.maximum(norms, 1e-12)
    unit = sigs / norms[:, None]
    cos = unit @ unit.T
    L = sigs.shape[0]
    ranked = [
        ((a, b), float(1.0 - cos[a, b]))
        for a in range(L) for b in range(a + 1, L)
    ]
    ranked.sort(key=lambda t: (-t[1], t[0]))
    return ranked


def calibrate_share_map(
    k,
    v,
    *,
    num_share: int,
    max_group: int = 2,
    valid_tokens: Optional[int] = None,
    meta: Optional[dict] = None,
) -> KVShareMap:
    """Greedy KVSharer calibration: merge the ``num_share`` most
    dissimilar layer pairs into shared groups, capping group size at
    ``max_group`` (the paper shares pairs; >2 compounds quality loss).

    ``k``/``v`` are dense ``(L, B, S, H, D)`` calibration buffers;
    ``valid_tokens`` trims right-padding before ranking."""
    import numpy as np  # noqa: F401 — keeps the dep surface explicit

    L = int(k.shape[0] if hasattr(k, "shape") else len(k))
    if num_share < 0 or num_share > L - 1:
        raise ShareMapError(
            f"num_share must be in [0, {L - 1}] for {L} layers"
        )
    if max_group < 2:
        raise ShareMapError("max_group must be >= 2")
    ranked = rank_layer_pairs(k, v, valid_tokens=valid_tokens)
    group: dict[int, int] = {i: i for i in range(L)}
    size = {i: 1 for i in range(L)}
    chosen: list[tuple[int, int]] = []
    scores: list[float] = []
    for (a, b), score in ranked:
        if len(chosen) >= num_share:
            break
        ga, gb = group[a], group[b]
        if ga == gb or size[ga] + size[gb] > max_group:
            continue
        lo, hi = min(ga, gb), max(ga, gb)
        for layer, g in group.items():
            if g == hi:
                group[layer] = lo
        size[lo] += size.pop(hi)
        chosen.append((a, b))
        scores.append(score)
    info = dict(meta or {})
    info.setdefault("calibration", {})
    info["calibration"].update({
        "num_share_requested": num_share,
        "pairs": [list(p) for p in chosen],
        "dissimilarity": scores,
        "max_group": max_group,
    })
    return KVShareMap.from_pairs(L, chosen, meta=info)


def load_share_map(path: Optional[str],
                   num_layers: Optional[int] = None) -> Optional[KVShareMap]:
    """Engine-facing loader: ``None`` path → no sharing; otherwise load
    and validate against the engine's local layer count when given.
    Identity maps come back as maps (``share_hash is None``) — the engine
    keeps its unshared fast paths selected for them."""
    if not path:
        return None
    m = KVShareMap.load(path)
    if num_layers is not None:
        m.validate_for(num_layers)
    return m
