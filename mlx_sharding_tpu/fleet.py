"""Elastic fleet control: autoscaler loop + overload brownout ladder.

``replicas.py`` provides the mechanisms — score-based routing,
``add_replica()``, ``drain()`` with zero dropped streams — and this module
provides the POLICY that drives them, organized around graceful
degradation: every failure path lands on a state at least as good as the
static fleet the operator configured.

Two controllers:

**BrownoutController** — a degradation ladder between "healthy" and the
429 shed. Pressure is a scalar where 1.0 ≈ the fleet exactly saturated
(``(active + queued) / slots``, plus a shed-rate kicker). Levels:

====== ==========================================================
level  degradation (cumulative)
====== ==========================================================
0      healthy — no intervention
1      cap ``max_tokens`` (long generations are the cheapest ballast),
       and pause prefix-store INSERTION (demotion exports are deferrable
       churn; serving hits stays on — hits SHED load, they don't add it)
2      … and shed speculation (draft compute goes to real tokens).
       Schedulers with an AcceptanceTracker shed per-slot, lowest
       acceptance first — streams where drafting demonstrably pays keep
       their windows; legacy fixed-K engine mode pauses globally
3      … and tighten admission to half the queue bound (shed earlier,
       shallower queues, bounded queue-wait)
====== ==========================================================

Escalation is immediate (overload must be answered now); de-escalation
steps down ONE level per ``dwell_s`` below the exit threshold, so a noisy
load signal can't make serving quality oscillate.

**FleetAutoscaler** — a background loop (or a fake-clock-driven ``tick()``
in tests) that watches the fleet's queue/shed signals and:

- spawns a replica through the pluggable ``factory`` (any zero-arg
  callable returning a replica — a ``ReplicaFactory``) after pressure has
  stayed above ``scale_up_pressure`` for ``scale_up_sustain_s``;
- drains the least-loaded replica after pressure has stayed below
  ``scale_down_pressure`` for ``scale_down_sustain_s``;
- respects ``min_replicas``/``max_replicas`` bounds and a shared
  ``cooldown_s`` between scaling actions (hysteresis: the sustain windows
  reset whenever pressure crosses back).

Failure semantics (the robustness contract): an injected or real failure
at ``replica.spawn``, ``replica.drain``, or ``autoscaler.tick`` records an
autoscale event, quarantines scaling behind the cooldown, and leaves the
CURRENT fleet serving — never a dropped stream, never a wedged loop.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Optional

from mlx_sharding_tpu.analysis.runtime import make_lock
from mlx_sharding_tpu.testing.faults import inject
from mlx_sharding_tpu.utils.clock import MONOTONIC, Clock

logger = logging.getLogger(__name__)


def pool_pressure(slots: int, active: int, queued: int,
                  shed_delta: int) -> float:
    """Load pressure of ONE replica pool: utilization
    ``(active + queued) / slots`` plus a shed kicker (each admission shed
    since the last sample counts 0.25, saturating at +1 — a shedding pool
    is over pressure 1.0 by definition, whatever the instantaneous queue
    looks like).

    Module-level so every consumer prices load identically — and so the
    disaggregated coordinator can run one autoscaler per ROLE pool over
    that pool's own signals. Folding prefill-bound and decode-bound queues
    into one fleet-wide scalar was the bug: a prefill storm inflated the
    shared pressure and spawned decode replicas that then idled (and vice
    versa). Each pool's pressure must see only its own slots/queue/sheds."""
    return (active + queued) / max(1, slots) + min(1.0, 0.25 * shed_delta)


def aggregate_pressure(host_infos: list) -> float:
    """Pod-wide pressure: the slot-weighted mean of per-host pool
    pressures. Each entry is a host heartbeat's fleet block
    (``{"pressure": float, "slots": int, ...}``); hosts with no slots
    (draining out, just died) contribute nothing. Slot weighting matters:
    a saturated 2-slot host must not read as urgent as a saturated
    32-slot host — the pod autoscaler prices capacity, not host count."""
    num = den = 0.0
    for info in host_infos:
        slots = max(0, int(info.get("slots", 0) or 0))
        if slots <= 0:
            continue
        num += float(info.get("pressure", 0.0) or 0.0) * slots
        den += slots
    return num / den if den else 0.0


class BrownoutController:
    """Degradation ladder (see module docstring). ``observe(pressure)`` is
    the only input; the outputs are ``state()`` / the level predicates the
    server and scheduler consult per request."""

    LEVELS = 3

    def __init__(self, *, enter=(0.85, 1.25, 2.0), exit=(0.5, 0.9, 1.5),
                 caps=(512, 256, 96), dwell_s: float = 5.0,
                 clock: Clock = MONOTONIC):
        if len(enter) != self.LEVELS or len(exit) != self.LEVELS:
            raise ValueError(f"enter/exit need {self.LEVELS} thresholds")
        if len(caps) != self.LEVELS:
            raise ValueError(f"caps needs {self.LEVELS} entries")
        if any(x >= e for x, e in zip(exit, enter)):
            raise ValueError("each exit threshold must be below its enter")
        if list(enter) != sorted(enter):
            raise ValueError("enter thresholds must be non-decreasing")
        if dwell_s < 0:
            raise ValueError("dwell_s must be >= 0")
        self.enter = tuple(enter)
        self.exit = tuple(exit)
        self.caps = tuple(caps)
        self.dwell_s = dwell_s
        self.clock = clock
        self._level = 0
        self._below_since: Optional[float] = None
        self._lock = make_lock("BrownoutController._lock")

    def observe(self, pressure: float) -> int:
        """Feed one pressure sample; returns the (possibly new) level."""
        with self._lock:
            target = 0
            for k, thr in enumerate(self.enter):
                if pressure >= thr:
                    target = k + 1
            now = self.clock()
            if target > self._level:
                self._level = target  # escalate immediately
                self._below_since = None
            elif self._level > 0 and pressure <= self.exit[self._level - 1]:
                if self._below_since is None:
                    self._below_since = now
                elif now - self._below_since >= self.dwell_s:
                    self._level -= 1  # one rung per dwell — no oscillation
                    self._below_since = now
            else:
                self._below_since = None
            return self._level

    def level(self) -> int:
        with self._lock:
            return self._level

    def max_tokens_cap(self) -> Optional[int]:
        with self._lock:
            return self.caps[self._level - 1] if self._level > 0 else None

    def state(self) -> dict:
        with self._lock:
            lvl = self._level
            return {
                "level": lvl,
                "max_tokens_cap": self.caps[lvl - 1] if lvl > 0 else None,
                "speculation_disabled": lvl >= 2,
                # HOW level >= 2 sheds is the scheduler's call: per-slot
                # lowest-acceptance-first with an AcceptanceTracker
                # (losing streams drop their windows first), a global
                # pause in legacy fixed-K engine mode. The ladder only
                # publishes the level; this names the contract.
                "speculation_shed": (
                    "lowest-acceptance-first" if 2 <= lvl < 3
                    else "all" if lvl >= 3 else None
                ),
                "admission_tightened": lvl >= 3,
            }


class FleetAutoscaler:
    """Scale/brownout decision loop over a :class:`ReplicaSet`.

    All decision logic lives in :meth:`tick` with an injectable ``clock``,
    so hysteresis/cooldown behavior is testable without sleeping; ``start``
    merely runs ``tick`` every ``interval_s`` on a daemon thread. The
    controller attaches itself to the replica set (``attach_controller``)
    so ``rs.close()`` stops the loop and ``rs.health()`` reports
    ``autoscaler`` + ``brownout`` blocks."""

    def __init__(self, replica_set, factory: Optional[Callable] = None, *,
                 min_replicas: int = 1, max_replicas: Optional[int] = None,
                 interval_s: float = 2.0,
                 scale_up_pressure: float = 0.75,
                 scale_up_sustain_s: float = 5.0,
                 scale_down_pressure: float = 0.25,
                 scale_down_sustain_s: float = 30.0,
                 cooldown_s: float = 15.0,
                 drain_deadline_s: float = 30.0,
                 brownout: Optional[BrownoutController] = None,
                 enable_brownout: bool = True,
                 role: Optional[str] = None,
                 clock: Clock = MONOTONIC):
        if min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if max_replicas is not None and max_replicas < min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        if interval_s <= 0:
            raise ValueError("interval_s must be positive")
        if scale_down_pressure >= scale_up_pressure:
            raise ValueError(
                "scale_down_pressure must be below scale_up_pressure"
            )
        if min(scale_up_sustain_s, scale_down_sustain_s, cooldown_s) < 0:
            raise ValueError("sustain/cooldown windows must be >= 0")
        self.rs = replica_set
        # which pool this controller scales; inherits the replica set's
        # role tag so one autoscaler per role pool reads (and reacts to)
        # only that pool's pressure — see pool_pressure()
        self.role = role if role is not None \
            else getattr(replica_set, "role", None)
        self.factory = factory
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.interval_s = interval_s
        self.scale_up_pressure = scale_up_pressure
        self.scale_up_sustain_s = scale_up_sustain_s
        self.scale_down_pressure = scale_down_pressure
        self.scale_down_sustain_s = scale_down_sustain_s
        self.cooldown_s = cooldown_s
        self.drain_deadline_s = drain_deadline_s
        self.clock = clock
        self.brownout = (
            brownout if brownout is not None
            else (BrownoutController(clock=clock) if enable_brownout else None)
        )
        self._lock = make_lock("FleetAutoscaler._lock")
        self._up_since: Optional[float] = None
        self._down_since: Optional[float] = None
        self._last_scale_at: Optional[float] = None
        self._last_shed = 0
        self._last_level = 0
        self.ticks = 0
        self.tick_errors = 0
        self.spawns = 0
        self.spawn_failures = 0
        self.last_spawn_s = None  # wall time of the last factory() call —
        # the aliased-vs-full-reload A/B number shared weights exist to move
        self.drains = 0
        self.drain_failures = 0
        self.degraded = False  # last scale action failed → static fleet
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        replica_set.attach_controller(self)

    # ------------------------------------------------------------ signals
    def _signals(self) -> tuple:
        """(slots, active, queued, shed_total, live) — everything the
        decision needs, gathered BEFORE our lock (each accessor takes the
        replica set's / batchers' own locks)."""
        slots, active, queued = self.rs.stats()
        res = self.rs.resilience_stats()
        shed = res.get("shed_queue_full", 0) + res.get("shed_deadline", 0)
        fleet = self.rs.fleet_stats()
        return slots, active, queued, shed, fleet["size"]

    def _pick_drain_victim(self) -> Optional[int]:
        """Least-loaded live replica; ties to the HIGHEST index so the
        newest spawn retires first (its cache is the coldest)."""
        per = self.rs.replica_stats()
        cands = [
            p for p in per if not p["retired"] and not p["draining"]
        ]
        if len(cands) <= 1:
            return None
        return min(
            cands, key=lambda p: (p["inflight"] + p["queue_depth"],
                                  -p["replica"])
        )["replica"]

    # ----------------------------------------------------------- decision
    def tick(self) -> dict:
        """One control decision. Returns what it observed and did (tests
        and ``/admin/autoscaler`` read it). Never raises: any failure —
        including an injected ``autoscaler.tick`` fault — degrades to the
        static fleet and is recorded as an autoscale event."""
        now = self.clock()
        try:
            inject("autoscaler.tick")
            slots, active, queued, shed, live = self._signals()
        except Exception:  # noqa: BLE001 — a sick controller must not serve
            logger.exception("autoscaler tick failed; fleet left as-is")
            with self._lock:
                self.tick_errors += 1
            self.rs.record_autoscale_event("tick_error")
            return {"error": True}
        max_reps = self.max_replicas if self.max_replicas is not None else live
        action = None
        with self._lock:
            self.ticks += 1
            shed_delta = max(0, shed - self._last_shed)
            self._last_shed = shed
            # THIS pool's pressure only (see pool_pressure): slots/queue/
            # sheds all come from self.rs, so a storm on the other role's
            # pool can't trigger spawns here
            pressure = pool_pressure(slots, active, queued, shed_delta)
            in_cooldown = (
                self._last_scale_at is not None
                and now - self._last_scale_at < self.cooldown_s
            )
            if (pressure >= self.scale_up_pressure
                    and self.factory is not None and live < max_reps):
                if self._up_since is None:
                    self._up_since = now
                if (now - self._up_since >= self.scale_up_sustain_s
                        and not in_cooldown):
                    action = "spawn"
            else:
                self._up_since = None
            if (action is None and pressure <= self.scale_down_pressure
                    and live > self.min_replicas):
                if self._down_since is None:
                    self._down_since = now
                if (now - self._down_since >= self.scale_down_sustain_s
                        and not in_cooldown):
                    action = "drain"
            elif action is None:
                self._down_since = None
        out = {"pressure": round(pressure, 3), "live": live,
               "action": action, "brownout": 0}
        if self.brownout is not None:
            level = self.brownout.observe(pressure)
            out["brownout"] = level
            with self._lock:
                changed, self._last_level = level != self._last_level, level
            if changed:
                self.rs.set_pressure(level)
                self.rs.record_autoscale_event(f"brownout_level_{level}")
        if action == "spawn":
            out["action"] = self._spawn(now)
        elif action == "drain":
            out["action"] = self._drain(now)
        return out

    def _spawn(self, now: float) -> str:
        try:
            inject("replica.spawn")
            t0 = self.clock()
            rep = self.factory()
            spawn_s = self.clock() - t0
            if rep is None:
                raise RuntimeError("replica factory returned None")
        except Exception:  # noqa: BLE001 — degrade to the static fleet
            logger.exception(
                "replica spawn failed; serving continues on the current "
                "fleet (retry after cooldown)"
            )
            with self._lock:
                self.spawn_failures += 1
                self.degraded = True
                self._last_scale_at = now  # quarantine behind the cooldown
                self._up_since = None
            self.rs.record_autoscale_event("spawn_failed")
            return "spawn_failed"
        idx = self.rs.add_replica(rep)
        with self._lock:
            self.spawns += 1
            self.last_spawn_s = spawn_s
            self.degraded = False
            self._last_scale_at = now
            self._up_since = None
        self.rs.record_autoscale_event("spawn")
        logger.info("autoscaler (%s) spawned replica %d",
                    self.role or "fleet", idx)
        return "spawn"

    def _drain(self, now: float) -> str:
        victim = self._pick_drain_victim()
        if victim is None:
            with self._lock:
                self._down_since = None
            return "drain_skipped"
        try:
            self.rs.drain(victim, deadline=self.drain_deadline_s)
        except Exception:  # noqa: BLE001 — quarantined, streams intact
            logger.exception(
                "autoscaler drain of replica %d failed; replica stays "
                "quarantined (retry after cooldown)", victim,
            )
            with self._lock:
                self.drain_failures += 1
                self.degraded = True
                self._last_scale_at = now
                self._down_since = None
            self.rs.record_autoscale_event("drain_failed")
            return "drain_failed"
        with self._lock:
            self.drains += 1
            self.degraded = False
            self._last_scale_at = now
            self._down_since = None
        self.rs.record_autoscale_event("drain")
        logger.info("autoscaler (%s) drained replica %d",
                    self.role or "fleet", victim)
        return "drain"

    # ------------------------------------------------------- pod surface
    def pressure(self) -> float:
        """Instantaneous pool pressure for the pod heartbeat — the same
        :func:`pool_pressure` the decision loop prices, sampled without
        touching the shed-delta bookkeeping (``tick()`` owns that)."""
        slots, active, queued = self.rs.stats()
        return pool_pressure(slots, active, queued, 0)

    def headroom(self) -> dict:
        """How much THIS host's pool can still grow/shrink — the pod
        autoscaler's per-host entry in the pod-wide free list."""
        live = self.rs.fleet_stats()["size"]
        max_reps = self.max_replicas if self.max_replicas is not None else live
        return {
            "live": live,
            "spawnable": max(0, max_reps - live) if self.factory else 0,
            "drainable": max(0, live - self.min_replicas),
        }

    def spawn_one(self) -> str:
        """Pod-autoscaler nudge: spawn now if bounds allow, with the same
        failure quarantine as an organic scale-up. Returns the action
        string (``spawn`` / ``spawn_failed`` / ``spawn_skipped``)."""
        now = self.clock()
        live = self.rs.fleet_stats()["size"]
        max_reps = self.max_replicas if self.max_replicas is not None else live
        if self.factory is None or live >= max_reps:
            return "spawn_skipped"
        return self._spawn(now)

    def drain_one(self) -> str:
        """Pod-autoscaler nudge: drain the least-loaded replica if bounds
        allow (``drain`` / ``drain_failed`` / ``drain_skipped``)."""
        now = self.clock()
        if self.rs.fleet_stats()["size"] <= self.min_replicas:
            return "drain_skipped"
        return self._drain(now)

    # --------------------------------------------------------- loop/state
    def start(self):
        """Run ``tick()`` every ``interval_s`` on a daemon thread (no-op if
        already running)."""
        with self._lock:
            if self._thread is not None:
                return
            self._stop_evt.clear()
            t = threading.Thread(
                target=self._run, name="mst-autoscaler", daemon=True
            )
            self._thread = t
        t.start()

    def _run(self):
        while not self._stop_evt.wait(self.interval_s):
            self.tick()

    def stop(self):
        with self._lock:
            self._stop_evt.set()
            t, self._thread = self._thread, None
        if t is not None:  # join OUTSIDE the lock: the loop thread's tick()
            t.join(timeout=10.0)  # takes _lock and must be able to finish

    def state(self) -> dict:
        with self._lock:
            return {
                "running": self._thread is not None,
                **({"role": self.role} if self.role is not None else {}),
                "ticks": self.ticks,
                "tick_errors": self.tick_errors,
                "spawns": self.spawns,
                "spawn_failures": self.spawn_failures,
                "last_spawn_s": self.last_spawn_s,
                "drains": self.drains,
                "drain_failures": self.drain_failures,
                "degraded": self.degraded,
                "min_replicas": self.min_replicas,
                "max_replicas": self.max_replicas,
                "cooldown_s": self.cooldown_s,
            }
