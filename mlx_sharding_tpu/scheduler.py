"""Continuous batching scheduler over the fused engine's microbatch axis.

The reference serializes requests entirely — one request owns the whole
pipeline until it finishes (single-threaded stdlib HTTP front end,
ref: shard/openai_api.py:543-563). Round 1 of this repo kept that behavior
(a generation lock). This module replaces it with slot-level continuous
batching, the thing the fused engine's ``M`` axis was designed for:

- every microbatch slot holds an independent request with its own KV-cache
  offset, sampler params, PRNG key and repetition window;
- a single scheduler thread owns the engine and loops: admit pending
  requests into free slots (chunked prefill that leaves other slots'
  state untouched), then run ONE fused decode step advancing every active
  slot by one token;
- tokens stream out through per-request queues; a slot is reclaimed when
  its request hits max_tokens or its consumer disappears (client
  disconnect / stop sequence matched by the server layer).

Determinism: each slot samples with its own PRNG-key chain seeded from the
request's seed, so a request's token stream is identical whether it ran
alone or interleaved with others (tested in tests/test_scheduler.py).
One carve-out: with a draft engine attached, a SAMPLED request's key chain
advances per speculative round (3 splits) vs per plain step (1 split), and
a neighbor that pauses speculation for a tick (want_logprobs, or within K
of max_seq — see _spec_ok) shifts where those rounds fall — so a sampled
stream is replay-stable only among spec-compatible neighbors. Every stream
remains distribution-exact regardless, and GREEDY streams never consume
keys, so their token-exactness holds unconditionally.

Async tick pipelining (``async_sched``): the decode loop can run
double-buffered — dispatch decode block t+1 (a pure device-side state
chain; last_tok/cache/recent/keys/active never round-trip through the
host) BEFORE harvesting block t's tokens, so the blocking ``device_get``
of an already-finished block overlaps the next block's compute and all
host-side work (emit, stop/cancel, admission bookkeeping) runs while the
device is busy. Token streams are bit-identical to sync mode: the same
jitted block programs consume the same device state chain in the same
order, and per-slot PRNG/repetition state is untouched by neighbors. The
cost is a one-tick control lag — a slot that finishes during block t
still participates in the in-flight block t+1 (its lookahead tokens are
dropped host-side, its pages are retired only at t+1's harvest, and its
paged-KV overrun is bounded to one decode block by the doubled
``_grow_ahead``) — and every host-visible state transition (admission
prefill, preemption, pool-pressure growth, shutdown) must quiesce the
in-flight block first.
"""

from __future__ import annotations

import hashlib
import logging
import queue
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from mlx_sharding_tpu import tracing
from mlx_sharding_tpu.analysis import runtime as mst_runtime
from mlx_sharding_tpu.analysis.runtime import (
    make_lock,
    note_acquire,
    note_release,
    note_reset,
)
from mlx_sharding_tpu.cache import (
    KVCache,
    export_pool_pages,
    import_pool_pages,
    rewind_slot_offset,
)
from mlx_sharding_tpu.generate import block_lp_outputs, block_token_logprobs
from mlx_sharding_tpu.kv_transfer import KVSpillTier, export_block, import_block
from mlx_sharding_tpu.resilience import (
    Deadlines,
    HandoffReadyError,
    QueueFullError,
    ReplicaDrainingError,
    RequestMigratedError,
    RequestTimeoutError,
    ResumeState,
)
from mlx_sharding_tpu.testing.faults import inject
from mlx_sharding_tpu.utils.clock import MONOTONIC, WALL_SLEEP, Clock, SleepFn
from mlx_sharding_tpu.utils.observability import (
    Histogram,
    ITL_BUCKETS_S,
    LATENCY_BUCKETS_S,
)
from mlx_sharding_tpu.sample import (
    SamplerParams,
    make_sampler_params,
    sample_token_batched,
    stack_sampler_params,
)
from mlx_sharding_tpu.speculative import (
    AcceptanceTracker,
    NgramDraftProposer,
)


def _note_pages(owner, pages, *, acquired: bool):
    """Leak-ledger shadow of a batch of free-list pops (acquired=True) or
    returns. One global read when the ledger is off — the per-page loop
    only runs under instrument_resources()."""
    led = mst_runtime._RESOURCES
    if led is None:
        return
    oid = id(owner)
    for p in pages:
        if acquired:
            led.note_acquire("scheduler.page", (oid, p))
        else:
            led.note_release("scheduler.page", (oid, p))


@dataclass(eq=False)  # identity semantics: requests key the spill tier
class _Request:
    prompt: np.ndarray  # (T,) int32
    sp: SamplerParams
    seed: int
    max_tokens: int
    rep_context: int
    want_logprobs: bool = False
    out: queue.Queue = field(default_factory=lambda: queue.Queue())
    cancelled: bool = False
    # per-request deadlines (resilience.Deadlines) — None = unbounded, the
    # seed behavior; host-side only, never broadcast to worker mirrors
    deadlines: Optional[Deadlines] = None
    slot: int = -1
    produced: int = 0
    prefill_pos: int = 0  # next prompt index to prefill; admission is chunked
    # draft-engine prefill position (speculative CB): tracked separately —
    # a prefix-cache hit starts the TARGET past the reused pages while the
    # draft, which has no page sharing, prefills the whole prompt from 0
    draft_pos: int = 0
    # target prefill logits stashed while the draft catches up
    _last_logits: Optional[object] = None
    # raw sampler request, kept so multi-host serving can broadcast the
    # request verbatim and workers rebuild an identical SamplerParams
    temperature: float = 0.0
    top_p: float = 1.0
    repetition_penalty: Optional[float] = None
    logit_bias: Optional[dict] = None
    # prefix-cache scratch: rolling page keys (memoized for the request's
    # lifetime) and the chain _fits matched, consumed by _assign_slot in the
    # same admission pass
    _pkeys: Optional[list] = None
    _chain: Optional[list] = None
    # prefix-STORE scratch (fleet-wide content-addressed reuse): the chained
    # chunk digests (memoized like _pkeys), the ("device"|"host", cover)
    # plan _fits resolved, and the held PrefixLease while the slot maps
    # shared store pages — released exactly once on every exit path
    _sdigests: Optional[list] = None
    _splan: Optional[tuple] = None
    _please: Optional[object] = None
    # pod-federated prefix fetch state: None = never consulted, "pending" =
    # a background fetch is in flight (admission holds the request so the
    # prefix isn't redundantly prefilled), "done" = resolved either way.
    # _fits only READS the flag — every federation call runs off the tick
    # path in _pod_fetch_waiting (MST115)
    _podfetch: Optional[str] = None
    # over-commit admission state: order ticket (oldest admitted request is
    # never preempted), tokens emitted since the last (re)admission (folded
    # into the prompt on preemption so resume re-prefills them), and the
    # stashed device-side sampler state for token-exact resume
    admit_seq: Optional[int] = None
    history: list = field(default_factory=list)
    resume_keys: Optional[np.ndarray] = None
    resume_recent: Optional[np.ndarray] = None
    # KV migration state: ``spilled`` marks a KVPageBlock waiting in the
    # batcher's spill tier (preemption), ``_block`` carries a block handed
    # in directly (cross-replica migration via generate_step(_resume=…))
    spilled: bool = False
    _block: Optional[object] = None
    # disaggregated serving: emit the first token, then end the stream
    # with HandoffReadyError(ResumeState) instead of entering decode
    prefill_only: bool = False
    # cold-slot detection scratch: consumed tokens observed at the last
    # recency scan (produced - out.qsize()) and ticks the count has been
    # stagnant with a backlog — the consumer stopped pulling, the engine
    # keeps decoding for nobody
    _consumed_seen: int = 0
    _cold_ticks: int = 0
    # request-lifecycle tracing (tracing.py): the bound RequestTrace (None
    # when tracing is off or the request is unsampled — every hot-path site
    # guards on that), whether THIS batcher began the trace (and so must
    # retire it into the flight recorder at finish), and perf_counter
    # stamps feeding the queue-wait / inter-token histograms
    _trace: Optional[object] = None
    _trace_own: bool = False
    _t_submit: float = 0.0
    _t_last_emit: float = 0.0


@dataclass
class _InflightBlock:
    """A dispatched-but-unharvested decode block: the device-side output
    futures plus the host-side snapshot needed to emit its tokens later."""

    outs: object                     # block output futures (tokens [+ lp])
    live: list                       # [(slot, req)] snapshot at dispatch
    want_lp: bool
    prev_tok: Optional[object] = None  # block's first input (draft replay)


@dataclass
class _InflightSpec:
    """A dispatched-but-unharvested speculative round: the (count, gs)
    output futures plus the host-side plan needed to emit, account and
    train the acceptance tracker at harvest. The async ngram tick keeps at
    most one of these in flight (same double-buffer slot as
    :class:`_InflightBlock`)."""

    outs: object                     # (count (M,), gs (K, M)) futures
    live: list                       # [(slot, req)] snapshot at dispatch
    wins: dict                       # slot → policy window used this round
    wcaps: object                    # np (M,) effective per-slot caps
    K: int                           # round width (max live window)
    # optimistic continuation per slot (the proposals, assumed accepted):
    # while THIS round is in flight, the next async dispatch appends these
    # to the slot's host-visible history so its n-gram lookup sees an
    # up-to-date tail. A wrong guess only costs that round's acceptance —
    # the verify never trusts proposals, so exactness is unaffected.
    guess: dict = field(default_factory=dict)


# Retry-After clamps for 429 sheds: the estimate comes from the OBSERVED
# completion rate (below), not a fixed constant, bounded so a mis-sampled
# rate can neither tell clients "come back now" nor park them for minutes.
RETRY_AFTER_FLOOR_S = 1.0
RETRY_AFTER_CEIL_S = 30.0
RETRY_AFTER_WINDOW_S = 30.0


def estimate_retry_after(
    backlog: int,
    finish_times,
    now: float,
    *,
    window_s: float = RETRY_AFTER_WINDOW_S,
    floor_s: float = RETRY_AFTER_FLOOR_S,
    ceil_s: float = RETRY_AFTER_CEIL_S,
) -> float:
    """When should a shed client retry? ``backlog`` is how many requests
    must finish before the queue has room again; ``finish_times`` are
    monotonic completion stamps (any iterable, typically the batcher's
    bounded deque). The drain rate is completions-in-window / window-span;
    the estimate is ``backlog / rate``, clamped to [floor_s, ceil_s].

    Zero-drain edge: with no completion inside the window the queue is not
    draining at all — the honest answer is the ceiling, not the floor (a
    constant 1s would tell every shed client to hammer a wedged server)."""
    recent = [t for t in finish_times if now - t <= window_s]
    if not recent:
        return ceil_s
    span = max(now - min(recent), 1e-3)
    rate = len(recent) / span
    return min(ceil_s, max(floor_s, backlog / rate))


class ContinuousBatcher:
    """Drives a :class:`PipelineEngine` (built with ``microbatches=M``,
    ``batch=1``) as an M-slot continuous-batching server backend.

    ``generate_step`` has the same contract as ``Generator.generate_step`` /
    ``PipelineEngine.generate_step`` — the API server uses it unchanged, but
    without the global generation lock (``concurrent = True``).
    """

    concurrent = True
    # generate_step accepts request_timeout/ttft_timeout/stall_timeout and
    # enforces them scheduler-side; the server checks this attr before
    # forwarding deadline kwargs (plain Generator/PipelineEngine lack them)
    supports_deadlines = True
    # generate_step accepts _resume=ResumeState — the dispatcher only
    # re-places migrated/crashed streams onto engines that advertise this
    supports_resume = True
    # generate_step accepts _prefill_only=True (disaggregated serving):
    # the stream delivers the first token, then ends with
    # HandoffReadyError carrying the request's ResumeState
    supports_prefill_only = True
    # generate_step accepts _trace=RequestTrace (tracing.py): the server
    # (or disagg coordinator) binds one span timeline through the whole
    # request path; without one the scheduler self-begins on the process
    # tracer when tracing is enabled
    supports_trace = True

    def __init__(self, engine, *, repetition_window: int = 64, decode_block: int = 8,
                 policy: str = "fifo", prefix_cache: bool = False,
                 overcommit: bool = False, draft_engine=None, spec_k: int = 4,
                 draft: str = "auto", spec_window_max: Optional[int] = None,
                 spec_clock=None,
                 max_queue: Optional[int] = None, async_sched: str = "auto",
                 spill_bytes: Optional[int] = None,
                 spill_cold_after: Optional[int] = None,
                 kv_prefetch: str = "auto",
                 prefix_store=None, clock: Clock = MONOTONIC,
                 sleep: SleepFn = WALL_SLEEP):
        if engine.batch != 1:
            raise ValueError("continuous batching expects engine batch=1")
        if max_queue is not None and (not isinstance(max_queue, int) or max_queue < 1):
            raise ValueError(f"max_queue must be a positive int, got {max_queue!r}")
        if draft not in ("auto", "off", "ngram", "engine"):
            raise ValueError(
                f"draft must be 'auto', 'off', 'ngram' or 'engine', got "
                f"{draft!r}"
            )
        # the draft MODE: 'auto' keeps the legacy contract — engine iff a
        # draft engine was handed in, otherwise no speculation
        spec_mode = draft
        if spec_mode == "auto":
            spec_mode = "engine" if draft_engine is not None else "off"
        if spec_mode == "engine" and draft_engine is None:
            raise ValueError(
                "draft='engine' needs a draft engine (--draft-model)"
            )
        if spec_mode != "engine" and draft_engine is not None:
            raise ValueError(
                f"a draft engine was given but draft={draft!r} — drop the "
                "draft engine or select draft='engine'/'auto'"
            )
        if spec_mode == "ngram":
            if engine.num_stages != 1:
                raise ValueError(
                    "speculative continuous batching needs a pp=1 engine "
                    "(the verify wants the keep_all vectorized body)"
                )
            if jax.process_count() > 1:
                # the worker-mirror protocol (multihost.serve_worker_batched)
                # has no speculative op: a controller-local spec round would
                # desync the mirrored op streams
                raise ValueError(
                    "--draft ngram is not supported in multi-host serving: "
                    "worker mirrors replay plain decode ticks only; run it "
                    "on single-host replicas (e.g. behind --replicas) instead"
                )
        if spec_window_max is not None:
            if isinstance(spec_window_max, bool) \
                    or not isinstance(spec_window_max, int) \
                    or spec_window_max < 2:
                raise ValueError(
                    f"spec_window_max must be an int >= 2, got "
                    f"{spec_window_max!r}"
                )
            if spec_mode == "off":
                raise ValueError(
                    "spec_window_max needs a draft mode — select "
                    "--draft ngram or --draft engine"
                )
        if draft_engine is not None:
            # speculative x continuous batching: the draft engine mirrors the
            # target's slot structure (same M, same chunking) with its own
            # dense KV cache; pp=1 only (the verify needs the keep_all
            # vectorized body)
            if engine.num_stages != 1 or draft_engine.num_stages != 1:
                raise ValueError(
                    "speculative continuous batching needs pp=1 engines"
                )
            tv = getattr(engine.model.config, "vocab_size", None)
            dv = getattr(draft_engine.model.config, "vocab_size", None)
            if tv != dv:
                # a mismatched pair would silently emit clamped-index
                # garbage: draft token ids index the target's embedding and
                # logprob rows (speculative.py:131-139 enforces the same)
                raise ValueError(
                    f"draft vocab ({dv}) must match target vocab ({tv}) — "
                    "speculation exchanges raw token ids between the models"
                )
            if getattr(draft_engine, "paged", False):
                raise ValueError("the draft engine must be dense (no pool_pages)")
            if draft_engine.microbatches != engine.microbatches:
                raise ValueError("draft engine must match the target's slots")
            if draft_engine.prefill_chunk != engine.prefill_chunk:
                raise ValueError("draft engine must match the target's "
                                 "prefill chunk")
            if draft_engine.max_seq < engine.max_seq:
                raise ValueError("draft engine max_seq must cover the target's")
            if spec_k < 1:
                raise ValueError(f"spec_k must be >= 1, got {spec_k}")
        if policy not in ("fifo", "first_fit"):
            raise ValueError(f"unknown admission policy {policy!r}")
        if prefix_cache and not getattr(engine, "paged", False):
            raise ValueError(
                "prefix_cache requires a paged engine (pool_pages): sharing "
                "is page-granular"
            )
        if prefix_store is not None:
            if not getattr(engine, "paged", False):
                raise ValueError(
                    "the prefix store requires a paged engine (pool_pages): "
                    "prefix reuse is page-granular"
                )
            if prefix_cache:
                raise ValueError(
                    "prefix_cache and prefix_store are mutually exclusive — "
                    "the fleet-wide store subsumes the slot-local prefix "
                    "cache (--prompt-cache); drop --prompt-cache"
                )
            if draft_engine is not None:
                raise ValueError(
                    "the prefix store is incompatible with a draft engine: "
                    "the draft's dense KV has no shareable pages, so a "
                    "store hit would leave it attending to unprefilled state"
                )
            if jax.process_count() > 1:
                # same class of problem as overcommit: lookup/lease/import
                # are host-side page-table decisions outside the op stream
                # worker ranks mirror
                raise ValueError(
                    "the prefix store is not supported in multi-host "
                    "serving: store hits rewrite page tables host-side, "
                    "outside the mirrored op stream; run it on single-host "
                    "replicas (e.g. behind --replicas) instead"
                )
        if overcommit and not getattr(engine, "paged", False):
            raise ValueError(
                "overcommit admission requires a paged engine (pool_pages)"
            )
        if overcommit and jax.process_count() > 1:
            # The sampler-state stash itself is no longer the blocker (it
            # rides a KVPageBlock now, a pure device-side gather every rank
            # could mirror). What remains genuinely unsupported: preemption
            # and block re-import are HOST-side scheduling decisions that
            # rewrite page-table/active rows and pop the rank-local free
            # list outside the mirrored multihost op stream — worker ranks
            # can't observe the controller's choice of victim/pages, so
            # their mirrored jitted programs would consume diverged inputs
            # and desync into a collective hang.
            raise ValueError(
                "overcommit admission is not supported in multi-host "
                "serving: preemption/resume rewrites page tables and free "
                "lists host-side, outside the op stream worker ranks "
                "mirror; run overcommit on single-host replicas (e.g. "
                "behind --replicas) instead"
            )
        if spill_bytes is not None:
            if isinstance(spill_bytes, bool) or not isinstance(spill_bytes, int) \
                    or spill_bytes <= 0:
                raise ValueError(
                    f"spill_bytes must be a positive byte count, got "
                    f"{spill_bytes!r}"
                )
            if not getattr(engine, "paged", False):
                raise ValueError(
                    "KV spill (--spill-bytes) requires a paged engine "
                    "(pool_pages): spilling moves pool pages"
                )
            if draft_engine is not None:
                # the draft's dense KV has no page chain to export; a spilled
                # target block would resume against a stale draft cache
                raise ValueError(
                    "KV spill is incompatible with a draft engine — "
                    "speculative slots re-prefill on preemption"
                )
        if spill_cold_after is not None:
            if isinstance(spill_cold_after, bool) \
                    or not isinstance(spill_cold_after, int) \
                    or spill_cold_after < 1:
                raise ValueError(
                    f"spill_cold_after must be an int >= 1 (ticks), got "
                    f"{spill_cold_after!r}"
                )
            if spill_bytes is None:
                raise ValueError(
                    "spill_cold_after needs a spill tier to spill into — "
                    "set spill_bytes (--spill-bytes)"
                )
            if jax.process_count() > 1:
                # same host-side page-table rewrite problem as overcommit:
                # a rank-local cold spill would desync mirrored op streams
                raise ValueError(
                    "cold-slot spill is not supported in multi-host serving"
                )
        if kv_prefetch not in ("on", "off", "auto"):
            raise ValueError(
                f"kv_prefetch must be 'on', 'off' or 'auto', got "
                f"{kv_prefetch!r}"
            )
        if kv_prefetch == "on" and spill_bytes is None:
            raise ValueError(
                "kv_prefetch='on' needs a spill tier to prefetch from — "
                "set spill_bytes (--spill-bytes)"
            )
        if async_sched not in ("on", "off", "auto"):
            raise ValueError(
                f"async_sched must be 'on', 'off' or 'auto', got {async_sched!r}"
            )
        if async_sched == "on" and draft_engine is not None:
            # speculative rounds already harvest per-round accept counts —
            # the next round's proposals depend on them, so there is no
            # device-side chain to run ahead on
            raise ValueError(
                "async_sched='on' is incompatible with a draft engine; use "
                "'auto' (resolves to sync when speculating)"
            )
        if async_sched == "on" and jax.process_count() > 1:
            # worker mirrors replay the op stream per broadcast tick; a
            # rank-local lookahead block would desync the mirrored streams
            raise ValueError(
                "async_sched='on' is not supported in multi-host serving"
            )
        self.engine = engine
        self.M = engine.microbatches
        self.W = repetition_window
        # injectable time source + wait primitive (utils/clock.py): every
        # deadline/TTFT/retry-after computation below reads this clock, so
        # tests and the fleet simulator can drive admission, timeout expiry
        # and migrate_out unwinding in virtual time. spec_clock defaults to
        # the same source (it predates the general slot; kept for callers
        # that pin the speculative controller to its own clock).
        self._clock = clock
        self._sleep = sleep
        if spec_clock is None:
            spec_clock = clock
        # Admission: "fifo" is strict arrival order (a request that doesn't
        # fit blocks everything behind it — predictable, starvation-free);
        # "first_fit" lets later requests that DO fit (free slot + enough
        # pages) jump a blocked head. Only meaningful with a paged pool.
        self.policy = policy
        self._waiting: list[_Request] = []
        # decode steps fused per scheduler tick: the host pulls tokens once
        # per block (the per-pull round trip otherwise gates every slot —
        # see generate.Generator). Tradeoff: admission/cancel latency grows
        # to a block boundary, so the serving default (8) stays below the
        # Generator's 16.
        self.decode_block = max(1, decode_block)
        self._decode_block_progs: dict = {}  # want_lp → jitted block
        self._submit: queue.Queue = queue.Queue()
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self._start_lock = make_lock("ContinuousBatcher._start_lock")
        # Admission control: generate_step rejects (QueueFullError → HTTP
        # 429) when queued requests reach max_queue, instead of letting the
        # unbounded submit queue grow without limit under overload. The lock
        # makes check-then-enqueue atomic across HTTP handler threads (and
        # the shed counter exact). The scheduler thread moves requests from
        # _submit to _waiting outside this lock, so a request mid-drain can
        # be momentarily invisible to the depth read — the bound is exact
        # across submitters and soft by at most that one in-flight drain.
        self.max_queue = max_queue
        self._admission_lock = make_lock("ContinuousBatcher._admission_lock")
        # resilience counters (read by /metrics via resilience_stats)
        self.timeouts = 0        # consumer-side deadline expiries
        self.shed_queue_full = 0  # rejected at admission (429)
        self.shed_deadline = 0   # shed while queued: TTFT budget already gone
        # monotonic completion stamps (bounded) feeding the drain-rate
        # Retry-After estimate on 429s; appended under _admission_lock
        self._finish_times: deque = deque(maxlen=256)
        # brownout ladder level from the fleet controller (fleet.py), set
        # via set_pressure(): >=2 pauses speculation, >=3 halves the
        # effective admission bound. Hot-path reads are racy by design
        # (gauge-grade) — the level changes at autoscaler-tick cadence.
        self._pressure = 0
        # close() flips this when the scheduler thread fails to join —
        # /health reports degraded and the thread-live gauge drops to 0
        self.thread_wedged = False

        # Multi-controller discipline (multi-host serving mirrors this
        # scheduler on every rank): host-built inputs must be committed as
        # REPLICATED global arrays before entering a jitted program over the
        # global mesh, and state transitions must run inside jit — eager ops
        # on process-spanning arrays are not executable. Single-host, _put is
        # the identity and the jitted setters behave exactly like the eager
        # .at[].set they replace.
        self._multi = jax.process_count() > 1
        if self._multi:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from mlx_sharding_tpu.parallel.pipeline import put_global

            # every rank mirrors the same op stream, so the host value being
            # committed is identical by construction — put_global skips
            # device_put's cross-host assert broadcast
            rep = NamedSharding(engine.mesh, P())
            self._put = lambda x: put_global(x, rep)
        else:
            self._put = lambda x: x
        self._row_set = jax.jit(lambda arr, slot, val: arr.at[slot].set(val))
        self._sp_set = jax.jit(
            lambda batched, one, slot: jax.tree.map(
                lambda full, x: full.at[slot].set(x), batched, one
            )
        )
        self._set_last = jax.jit(lambda lt, slot, tok: lt.at[slot, 0].set(tok))
        self._zeros_like = jax.jit(jnp.zeros_like)
        self._rewind_offset = jax.jit(rewind_slot_offset)

        # device-side per-slot state. Paged engines share a page pool across
        # slots — packing mixed-length requests into far less HBM than M
        # dense max_seq allocations; the admission accounting mode below
        # decides how much of a request's need is claimed up front.
        self.paged = getattr(engine, "paged", False)
        self.prefix_cache = bool(prefix_cache)
        # Fleet-wide content-addressed prefix KV store (prefix_store.py):
        # admission LPM-matches the prompt's chained chunk digests against
        # device entries (zero-copy COW page share) and the host tier
        # (block import), and completed prefills register their prefix
        # back. One store is shared by every batcher in the process — the
        # subsystem the slot-local _prefix_index cannot grow into.
        # the engine's KV share-map layout hash (kv_share.py; None ==
        # unshared/identity) — stamped into every exported block and
        # demanded of every imported one, so a layout mismatch fails
        # closed at the edge instead of scattering wrong-geometry KV
        self._share_hash = getattr(engine, "kv_share_hash", None)
        # the engine's compressed-latent codec + layout hash
        # (kv_compress.py; None == raw transport) — every export carries
        # the codec so host-boundary flushes compress, every import
        # reconstructs under the matching layout or fails closed
        self._kv_codec = getattr(engine, "kv_codec", None)
        self._compress_hash = getattr(engine, "kv_compress_hash", None)
        self.prefix_store = prefix_store
        if prefix_store is not None:
            prefix_store.bind_page_size(engine.page_size)
            prefix_store.bind_share_hash(self._share_hash)
            prefix_store.bind_compress_hash(self._compress_hash)
        # Admission accounting mode. "reserve" (default) claims a request's
        # whole page need (prompt + max_tokens) up front: deadlock-free by
        # construction, but a request that asks for max_tokens=4096 and emits
        # 20 holds ~64x its real need. Over-commit admits on CURRENT need
        # (prompt + one decode block), grows per block, and on pool
        # exhaustion preempts the newest-admitted slot back to the waiting
        # line (its emitted tokens fold into its prompt; device sampler
        # state is stashed, so resume is token-exact). The oldest admitted
        # request is never preempted, so progress is guaranteed: worst case
        # the pool drains to one request, which the absolute capacity check
        # in generate_step proves fits alone.
        self.overcommit = bool(overcommit)
        self.preemptions = 0
        self._admit_counter = 0
        # KV migration (kv_transfer.py): spill-don't-discard preemption and
        # request migration. The tier holds preempted requests' page blocks
        # in host DRAM under an LRU budget; export is a dispatched device
        # gather (the blocking device→host copy runs on the tier's flusher
        # thread, never the tick path — MST106), import is one page scatter
        # instead of a re-prefill. All counters below are written under
        # _admission_lock (racy reads are gauge-grade, like preemptions).
        self.spill_bytes = spill_bytes
        self.spill = KVSpillTier(spill_bytes) if spill_bytes else None
        self.spills = 0            # blocks exported to the tier at preempt
        self.spill_hits = 0        # resumes served by a block import
        self.spill_fallbacks = 0   # export/import/budget failures → re-prefill
        self.migrations_out = 0    # requests exported by migrate_out (drain)
        self.migrations_in = 0     # resumed requests accepted via _resume
        self.handoffs_out = 0      # prefill-only requests handed to decode
        self.reprefill_tokens = 0  # tokens re-prefilled after discard paths
        # Proactive KV residency (cold-slot spill + PRESERVE-style
        # prefetch). A slot whose consumer stopped pulling tokens for
        # spill_cold_after ticks (backlog stagnant — the engine keeps
        # decoding, nobody reads) is suspended: its block spills to the
        # tier, its pool pages free up for admission, and the request
        # parks off the waiting line until the consumer catches up. Wake
        # re-queues it at the head; with prefetch on, the host→device
        # stage is dispatched while it waits its turn, so the re-import
        # scatter consumes device-resident pages instead of demand-paging
        # host numpy on the resume tick (the stall MST109 polices).
        self.spill_cold_after = spill_cold_after
        self.kv_prefetch = kv_prefetch
        self._prefetch_on = kv_prefetch == "on" or (
            kv_prefetch == "auto" and self.spill is not None
        )
        self._parked: list[_Request] = []  # cold-spilled, off the waiting line
        self.cold_spills = 0      # slots suspended by the cold policy
        self.cold_wakes = 0       # parked requests re-queued on consumer pull
        self.prefetches = 0       # host→device stages dispatched
        self.prefetch_hits = 0    # imports that consumed a staged block
        self.demand_imports = 0   # imports that marshaled host numpy (fallback)
        self.prefetch_faults = 0  # cache.prefetch faults absorbed → demand path
        # prefill-only requests whose first token was emitted this tick;
        # _handoff_out exports them before the tick's decode dispatch
        self._handoff_ready: list = []
        self._export_pages = jax.jit(export_pool_pages) if self.paged else None
        self._import_pages = jax.jit(import_pool_pages) if self.paged else None
        # drain flag: migrate_out() sets it (under _start_lock, like _stop);
        # the scheduler thread notices at the next tick, quiesces, and ends
        # every stream with a RequestMigratedError carrying its ResumeState
        self._migrate_requested = False
        # speculative decoding across slots: per tick, the draft proposes K
        # tokens for every active slot and the target verifies all of them
        # in one T=K forward; each slot emits its accepted prefix + one
        # correction/resample token. Greedy slots stay token-exact vs plain
        # decode; sampled slots are distribution-exact (the PRNG is consumed
        # differently than non-speculative decode, as in speculative.py).
        self.draft = draft_engine
        self.spec_k = spec_k
        self._spec_mode = spec_mode  # "off" | "ngram" | "engine"
        # async tick pipelining: resolved mode. "auto" turns it on for any
        # tick whose in-flight work is a pure device-side chain — plain
        # single-host decode AND n-gram speculation (host-built drafts, no
        # draft KV); a draft ENGINE forces sync (the round harvests accept
        # counts the next proposals depend on), multi-host forces sync
        # (worker mirrors replay per broadcast tick). The reason is kept on
        # the instance and logged so `--async-sched auto` says WHY.
        self.async_sched = async_sched
        if async_sched == "on":
            self._async = True
            reason = "async ticks: async_sched='on'"
        elif async_sched == "off":
            self._async = False
            reason = "sync ticks: async_sched='off'"
        elif draft_engine is not None:
            self._async = False
            reason = (
                "sync ticks: auto resolved to sync — the draft engine's "
                "speculative rounds harvest per-round accept counts that "
                "the next round's proposals depend on, so there is no "
                "device-side chain to run ahead on"
            )
        elif jax.process_count() > 1:
            self._async = False
            reason = (
                "sync ticks: auto resolved to sync — multi-host worker "
                "mirrors replay the op stream per broadcast tick; a "
                "rank-local lookahead block would desync them"
            )
        elif spec_mode == "ngram":
            self._async = True
            reason = (
                "async ticks: auto resolved to async — n-gram drafts are "
                "host-built (no draft engine, no draft KV), so the "
                "speculative round chains pure device-side like a plain "
                "decode block"
            )
        else:
            self._async = True
            reason = (
                "async ticks: auto resolved to async — plain single-host "
                "decode is a pure device-side chain"
            )
        self.async_reason = reason
        logging.getLogger(__name__).info("%s", reason)
        # the work in flight (dispatched, not harvested): a plain decode
        # block or, in async ngram mode, a speculative round. Owned by the
        # scheduler thread, always None in sync mode outside _decode_once
        self._inflight: Optional[object] = None  # _InflightBlock | _InflightSpec
        # per-tick timing (racy gauges by design, like kv_bytes_read_*):
        # device_blocked measures the harvest device_get; host is the rest
        # of the tick's wall time — the work the async path overlaps
        self.tick_host_ms_last = 0.0
        self.tick_device_blocked_ms_last = 0.0
        self._tick_host_s_total = 0.0
        self._tick_blocked_s_total = 0.0
        self._tick_count = 0  # ticks that harvested a block
        # always-on latency histograms (/metrics): inter-token latency at
        # the emit path, admission queue wait at slot assignment. These are
        # the metric itself (a lock + bisect per observation, same grade as
        # the tick-timing counters), distinct from per-request tracing —
        # which stays behind the `if tr is not None` no-op guard (MST112)
        self._h_itl = Histogram(ITL_BUCKETS_S, "ContinuousBatcher._h_itl")
        self._h_queue_wait = Histogram(
            LATENCY_BUCKETS_S, "ContinuousBatcher._h_queue_wait"
        )
        # --trace-profile resolved once at construction (serving configures
        # tracing before building engines): True wraps each dispatched
        # decode block in jax.profiler.TraceAnnotation so host spans line
        # up with the XLA timeline in an on-chip profile capture
        self._trace_profile = tracing.profile_enabled()
        # time the tick spent inside import_block (device blocked on the
        # resume path): ~0 when prefetch staged the pages, the full
        # host→device marshal on a demand import — the number that makes
        # resume stalls visible next to the async-sched gauges
        self.tick_kv_import_ms_last = 0.0
        self._tick_kv_import_s_total = 0.0
        # adaptive window control: an AcceptanceTracker drives per-slot
        # windows for ngram mode always, and for engine mode when the
        # operator opts in with spec_window_max (without it the engine path
        # keeps the legacy fixed-K contract: every round is exactly spec_k
        # wide). The tracker's clock is injectable for deterministic tests.
        if spec_mode == "ngram" or (
            spec_mode == "engine" and spec_window_max is not None
        ):
            self.spec_tracker: Optional[AcceptanceTracker] = AcceptanceTracker(
                self.M, w_max=spec_window_max or 8, clock=spec_clock
            )
            self._w_max = self.spec_tracker.rungs[-1]
        else:
            self.spec_tracker = None
            self._w_max = spec_k if spec_mode == "engine" else 0
        self._ngram = NgramDraftProposer() if spec_mode == "ngram" else None
        # over-commit page growth must cover whichever step writes furthest
        # ahead: a decode block (1 write/step), a T=K speculative verify,
        # and DOUBLE that when the pipeline runs a block/round ahead of the
        # host's emitted counts (at dispatch of t+1 the host has harvested
        # only through t-1)
        reach = self.decode_block
        if spec_mode == "engine":
            reach = max(reach, spec_k, self._w_max)
        elif spec_mode == "ngram":
            reach = max(reach, self._w_max)
        self._grow_ahead = (2 if self._async else 1) * reach
        if spec_mode != "off":
            self.rounds = 0          # spec telemetry: verify rounds x slots
            self.accepted_tokens = 0  # tokens EMITTED by speculating slots
            self.draft_tokens = 0    # proposal tokens offered to verifies
            # ticks that fell back to plain decode (spec paused) and the
            # tokens replayed through the draft to keep its KV in sync
            self.fallback_ticks = 0
            self.replayed_tokens = 0
            # spec.draft faults absorbed → that tick ran plain decode
            self.spec_draft_faults = 0
        if draft_engine is not None:
            self.dcache = draft_engine.init_cache()
            self._split3 = jax.jit(
                lambda ks: jax.vmap(lambda k: jax.random.split(k, 3))(ks)
            )
            # draft consumed [t0, d1..d_{K-1}] = K rows; keep the verified
            # prefix (the accepted tokens ARE the draft's inputs there).
            # k is the ROUND's width — adaptive rounds can run narrower
            # than spec_k
            self._drewind = jax.jit(
                lambda off, count, act, k: off + jnp.where(act, count - k, 0)
            )
        elif spec_mode == "ngram":
            # sampled ngram rounds split each slot's key once for the
            # verify (no draft-side key, unlike the engine path's 3-way)
            self._split2 = jax.jit(
                lambda ks: jax.vmap(lambda k: jax.random.split(k, 2))(ks)
            )
        if self.paged:
            self.cache, self.table = engine.init_cache_paged()
            # analytic per-tick KV-read accounting (the HBM story behind the
            # ragged-vs-gather paths): bytes of K+V per token position,
            # summed over every layer stack — leaf shape is
            # (S, L, pool+1, B, page, H, D), so S*L*H*D*itemsize per row
            self.kv_path = getattr(engine, "paged_attention", "gather")
            self.kv_bytes_read_last_tick = 0
            self.kv_bytes_read_total = 0
            # per-decoded-token HBM traffic, split by side (weights vs KV):
            # the headline numbers of the quantized memory hierarchy
            self.weight_bytes_per_token_last = 0.0
            self.kv_bytes_per_token_last = 0.0
            self._kv_row_bytes = sum(
                leaf.shape[0] * leaf.shape[1] * leaf.shape[-2]
                * leaf.shape[-1] * leaf.dtype.itemsize
                for leaf in (
                    jax.tree.leaves(self.cache.k) + jax.tree.leaves(self.cache.v)
                )
            )
            self._free_pages = list(range(engine.pool_pages - 1, -1, -1))
            self._pages_of: dict[int, list[int]] = {}  # slot → mapped pages
            self.pages_high_water = 0
            # Prompt-prefix sharing (vLLM-style content-addressed pages):
            # a FULL page of prompt KV is registered under the hash of the
            # whole token prefix it closes; a later request whose prompt
            # matches a chain of registered pages maps them read-only and
            # prefills only the suffix (its slot offset starts past them).
            # Refcount = #slots mapping the page + 1 if the index holds it;
            # index-only pages are "cached": not free, evictable LRU when
            # admission runs short. The reference resets remote caches per
            # request (ref: shard/utils.py:122-124) — this is the beaten
            # semantics; Generator._pc is the single-stream analogue.
            self._page_ref: dict[int, int] = {}
            self._prefix_index: "OrderedDict[bytes, int]" = OrderedDict()
            self.prefix_queries = 0
            self.prefix_hits = 0
            self.prefix_tokens_reused = 0
            self.prefix_evictions = 0
        else:
            self.cache = engine.init_cache()
            # dummy for the step arg
            self.table = self._put(jnp.zeros((1, 1), jnp.int32))
        self.recent = self._put(jnp.full((self.M, self.W), -1, jnp.int32))
        self.keys = self._put(jnp.stack([jax.random.PRNGKey(0)] * self.M))
        # bias width 512 covers OpenAI's documented logit_bias cap (300);
        # larger requests are rejected on the submitting thread
        self.sp = jax.tree.map(
            self._put,
            stack_sampler_params(
                [make_sampler_params(min_bias_slots=512) for _ in range(self.M)]
            ),
        )
        self.rep_sizes = self._put(jnp.full((self.M,), self.W, jnp.int32))
        self.active = self._put(jnp.zeros((self.M,), bool))
        self.last_tok = self._put(jnp.zeros((self.M, 1), jnp.int32))

        # host-side slot table
        self._slots: list[Optional[_Request]] = [None] * self.M
        self._prefill_rr = 0  # round-robin cursor for admission fairness

        self._first_sample = jax.jit(self._first_sample_fn)

    # ------------------------------------------------------------- public
    def generate_step(
        self,
        prompt_tokens,
        *,
        temperature: float = 0.0,
        top_p: float = 1.0,
        repetition_penalty: Optional[float] = None,
        repetition_context_size: int = 20,
        logit_bias: Optional[dict[int, float]] = None,
        seed: Optional[int] = None,
        max_tokens: int = 256,
        want_logprobs: bool = False,  # yields TokenLogprobs summaries
        request_timeout: Optional[float] = None,  # submit → last token budget
        ttft_timeout: Optional[float] = None,     # submit → first token budget
        stall_timeout: Optional[float] = None,    # inter-token watchdog
        _resume: Optional[ResumeState] = None,    # dispatcher-internal
        _prefill_only: bool = False,              # disagg-coordinator-internal
        _trace=None,                              # tracing.RequestTrace or None
    ):
        # Eager validation/admission, lazy consumption: every rejection
        # (bad params, queue full) raises on the CALLING thread before any
        # request state exists — the server can answer 400/429 before it has
        # committed to a streaming response. Only the token loop is deferred.
        with self._start_lock:
            draining = self._migrate_requested
        if draining:
            # draining/retired: reject up front so the dispatcher re-places
            # on a healthy replica (QueueFullError subtype → retry, no strike)
            raise ReplicaDrainingError()
        prompt = np.asarray(prompt_tokens, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        # Re-placement of a partially generated stream (replica drain /
        # crash failover): continue from the migrated state instead of
        # starting over. Preferred path imports the shipped KVPageBlock;
        # without one (or when this engine can't host it) the emitted
        # history folds into the prompt and re-prefills — slower but
        # token-exact, since the sampler PRNG row and repetition window
        # travel in the state when the source captured them.
        produced0 = 0
        hist: list = []
        block = None
        resume_keys = resume_recent = None
        if _resume is not None:
            produced0 = int(_resume.produced)
            if produced0 >= max_tokens:
                raise ValueError(
                    f"resumed request already produced {produced0} of "
                    f"{max_tokens} tokens"
                )
            hist = [int(t) for t in (_resume.history or [])]
            if len(hist) > produced0:
                # history is "tokens emitted since the last fold" — always a
                # suffix of what the client saw, so it can be SHORTER than
                # produced (the rest already folded into the prompt) but
                # never longer: that would re-emit tokens the accounting
                # says were never delivered
                raise ValueError(
                    f"resume state inconsistent: produced={produced0} but "
                    f"history carries {len(hist)} tokens"
                )
            block = _resume.block
            if block is not None and (not self.paged or self.draft is not None):
                block = None  # no pool to import into; fall back to fold
            # Capture the stashed sampler rows even when a block rides along:
            # if its import fails on this engine the admission path degrades
            # to fold + re-prefill, and the re-seeded PRNG chain must be the
            # exported one — a fresh PRNGKey(seed) would replay the stream
            # from token zero and double-emit what the client already saw.
            resume_keys = _resume.resume_keys
            resume_recent = _resume.resume_recent
            if block is None and hist:
                prompt = np.concatenate([prompt, np.asarray(hist, np.int32)])
                hist = []
        budget = max_tokens - produced0
        total = (block.n_tokens if block is not None else prompt.size) + budget
        if total > self.engine.max_seq:
            raise ValueError(
                f"prompt ({prompt.size}) + max_tokens ({max_tokens}) exceeds "
                f"KV capacity {self.engine.max_seq}"
            )
        if self.paged and -(-total // self.engine.page_size) > self.engine.pool_pages:
            raise ValueError(
                f"request needs {-(-total // self.engine.page_size)} "
                f"pages, pool has {self.engine.pool_pages} — it could never "
                "be admitted"
            )
        sp = make_sampler_params(temperature, top_p, repetition_penalty, logit_bias)
        if sp.bias_indices.shape[0] > self.sp.bias_indices.shape[1]:
            raise ValueError(
                f"logit_bias with {len(logit_bias)} entries exceeds the "
                f"scheduler's per-slot bias width "
                f"{self.sp.bias_indices.shape[1]}"
            )
        if repetition_penalty is not None and repetition_context_size > self.W:
            # silently shrinking the window would make --concurrent output
            # diverge from the serial path for the same request
            raise ValueError(
                f"repetition_context_size {repetition_context_size} exceeds "
                f"the scheduler's window {self.W}"
            )
        deadlines = (
            Deadlines.start(
                ttft_timeout=ttft_timeout,
                request_timeout=request_timeout,
                stall_timeout=stall_timeout,
            )
            if any(v is not None
                   for v in (ttft_timeout, request_timeout, stall_timeout))
            else None
        )
        req = _Request(
            prompt=prompt,
            sp=sp,
            seed=int(time.time_ns()) & 0x7FFFFFFF if seed is None else seed,
            max_tokens=max_tokens,
            rep_context=min(repetition_context_size, self.W),
            want_logprobs=want_logprobs,
            deadlines=deadlines,
            temperature=temperature,
            top_p=top_p,
            repetition_penalty=repetition_penalty,
            logit_bias=logit_bias,
            prefill_only=bool(_prefill_only),
        )
        if _resume is not None:
            req.produced = produced0
            req.history = hist
            req._block = block
            if resume_keys is not None:
                req.resume_keys = np.asarray(resume_keys)
            if resume_recent is not None:
                req.resume_recent = np.asarray(resume_recent)
            with self._admission_lock:
                self.migrations_in += 1
        # Bind (or self-begin) the request's span timeline. The server and
        # disagg coordinator pass _trace so one timeline spans the whole
        # path; direct scheduler users (bench, tests) get a trace from the
        # process tracer when one is configured — begin() returns None when
        # tracing is off or this request falls outside the sample.
        tr = _trace
        if tr is None:
            tr = tracing.begin()
            req._trace_own = tr is not None
        req._trace = tr
        req._t_submit = time.perf_counter()
        if tr is not None:
            tr.note(
                prompt_tokens=int(prompt.size), max_tokens=int(max_tokens),
                prefill_only=bool(_prefill_only), resumed=_resume is not None,
            )
            tr.point("submit")
        self._ensure_running()
        if self.max_queue is not None:
            with self._admission_lock:
                depth = self._submit.qsize() + len(self._waiting)
                bound = self.max_queue
                if self._pressure >= 3:
                    # brownout level 3: tightened admission — shed at half
                    # the configured bound so queue-wait stays bounded
                    # while the fleet is saturated
                    bound = max(1, bound // 2)
                if depth >= bound:
                    self.shed_queue_full += 1
                    if tr is not None:
                        # the shed is the request's whole story: stamp it
                        # and retire a self-begun trace so it can't leak
                        # in the recorder's live table
                        tr.point("shed", depth=depth, bound=bound)
                        if req._trace_own:
                            tracing.finish(tr)
                    raise QueueFullError(
                        depth, bound,
                        retry_after_s=estimate_retry_after(
                            max(1, depth - bound + 1),
                            self._finish_times, self._clock(),
                        ),
                    )
                self._submit.put(req)
        else:
            # mst: allow(MST201): no admission bound to keep atomic with
            self._submit.put(req)
        return self._consume(req)

    def _consume(self, req: _Request):
        """Token stream for a submitted request. Waits are bounded by the
        request's deadlines: TTFT before the first token, the inter-token
        watchdog after it, and the total budget throughout — whichever
        expires first. Expiry flips ``cancelled`` (the scheduler reclaims
        the slot/pages on its next tick, even a wedged one once it revives)
        and raises the structured error immediately, so a consumer never
        blocks forever on a dead engine."""
        dl = req.deadlines
        first = True
        try:
            while True:
                kind, timeout = None, None
                if dl is not None:
                    now = self._clock()
                    cands = []
                    if first and dl.ttft_deadline is not None:
                        cands.append(("ttft", dl.ttft_deadline - now))
                    if dl.total_deadline is not None:
                        cands.append(("total", dl.total_deadline - now))
                    if dl.stall_timeout is not None and (
                        not first or dl.ttft_deadline is None
                    ):
                        # inter-token watchdog; with no TTFT budget it also
                        # bounds the FIRST token, so a caller who set only
                        # stall_timeout still can't block forever on a
                        # wedged engine
                        cands.append(("stall", dl.stall_timeout))
                    if cands:
                        kind, timeout = min(cands, key=lambda t: t[1])
                        timeout = max(0.0, timeout)
                try:
                    item = (
                        req.out.get(timeout=timeout)
                        if timeout is not None
                        else req.out.get()
                    )
                except queue.Empty:
                    req.cancelled = True
                    with self._admission_lock:  # exact under concurrency
                        self.timeouts += 1
                    now = self._clock()
                    budget = (
                        dl.stall_timeout if kind == "stall"
                        else (dl.ttft_deadline if kind == "ttft"
                              else dl.total_deadline) - dl.submitted_at
                    )
                    raise RequestTimeoutError(
                        kind, now - dl.submitted_at, budget
                    ) from None
                if item is None:
                    return
                if isinstance(item, BaseException):
                    raise item
                first = False
                yield item
        finally:
            req.cancelled = True  # scheduler reclaims the slot next tick

    @property
    def weights_shared(self) -> bool:
        """True when this batcher's engine aliases a WeightStore-resident
        tree instead of owning a private upload — the per-replica
        ``mst_replica_weights_shared`` gauge reads this through the
        ReplicaSet."""
        return bool(getattr(self.engine, "weights_shared", False))

    def stats(self) -> tuple[int, int, int]:
        """(total slots, active slots, queued requests) — the /metrics
        contract, kept here so scheduler internals can change freely."""
        with self._admission_lock:
            queued = self._submit.qsize() + len(self._waiting)
        # _slots is owned by the scheduler thread; this is a racy snapshot
        # by design (a metric, not a decision input)
        return (self.M, sum(1 for r in self._slots if r is not None), queued)

    def _live_locked(self) -> bool:
        """scheduler_thread_live body; caller holds ``_start_lock``."""
        if self.thread_wedged:
            return False
        t = self._thread
        return t is None or t.is_alive() or self._stop

    def scheduler_thread_live(self) -> bool:
        """True while the scheduler thread is healthy: running, cleanly
        stopped, or not yet started. False only after close() observed a
        join timeout (a tick wedged mid-device-op)."""
        with self._start_lock:
            return self._live_locked()

    def set_pressure(self, level: int):
        """Brownout ladder input from the fleet controller (fleet.py):
        level >= 1 pauses prefix-store INSERTION (serving hits stays on —
        reuse sheds prefill work exactly when the fleet needs it), level
        >= 2 sheds speculation — globally in legacy fixed-K mode, per-slot
        lowest-acceptance-first with an AcceptanceTracker — and level >= 3
        halves the effective admission bound (and sheds speculation
        everywhere). Idempotent; levels outside [0, 3] are clamped."""
        lvl = max(0, min(3, int(level)))
        with self._admission_lock:
            self._pressure = lvl
        store = self.prefix_store
        if store is not None:
            store.pause_inserts(lvl >= 1)

    def resilience_stats(self) -> dict:
        """Deadline/shedding counters + queue bound for /metrics."""
        live = self.scheduler_thread_live()  # own lock; taken before ours
        with self._admission_lock:
            return {
                "timeouts": self.timeouts,
                "brownout_level": self._pressure,
                "shed_queue_full": self.shed_queue_full,
                "shed_deadline": self.shed_deadline,
                "max_queue": self.max_queue,
                "scheduler_thread_live": live,
                "preemptions": self.preemptions,
                "spills": self.spills,
                "spill_hits": self.spill_hits,
                "spill_fallbacks": self.spill_fallbacks,
                "migrations_out": self.migrations_out,
                "migrations_in": self.migrations_in,
                "handoffs_out": self.handoffs_out,
            }

    def spec_stats(self) -> Optional[dict]:
        """Speculation telemetry for /metrics (``mst_spec_*``); None when
        the batcher never speculates, so a non-speculating host's exposition
        stays label-free. Racy counter snapshot by design — gauges, not
        decision inputs."""
        if self._spec_mode == "off":
            return None
        out = {
            "mode": self._spec_mode,
            "window_max": self._w_max,
            "rounds": self.rounds,
            "draft_tokens": self.draft_tokens,
            "accepted_tokens": self.accepted_tokens,
            "accept_rate": self.accepted_tokens / max(1, self.draft_tokens),
            "fallback_ticks": self.fallback_ticks,
            "replayed_tokens": self.replayed_tokens,
            "draft_faults": self.spec_draft_faults,
        }
        if self.spec_tracker is not None:
            out.update(self.spec_tracker.stats())
        return out

    def spill_stats(self) -> Optional[dict]:
        """KV spill/migration counters + tier occupancy for /metrics
        (``mst_kv_spill_*`` / ``mst_kv_migration_*``); None on dense
        engines, which have no page pool to export blocks from. The tier's
        own stats are read before taking the admission lock so the two
        locks never nest."""
        if not self.paged:
            return None
        spill = self.spill
        tier = spill.stats() if spill is not None else {}
        with self._admission_lock:
            out = {
                "enabled": spill is not None,
                "spills": self.spills,
                "spill_hits": self.spill_hits,
                "spill_fallbacks": self.spill_fallbacks,
                "migrations_out": self.migrations_out,
                "migrations_in": self.migrations_in,
                "reprefill_tokens": self.reprefill_tokens,
                "preemptions": self.preemptions,
                # proactive residency: cold policy + prefetch counters
                "cold_spills": self.cold_spills,
                "cold_wakes": self.cold_wakes,
                "parked": len(self._parked),
                "prefetch_enabled": self._prefetch_on,
                "prefetches": self.prefetches,
                "prefetch_hits": self.prefetch_hits,
                "demand_imports": self.demand_imports,
                "prefetch_faults": self.prefetch_faults,
            }
        out["budget_bytes"] = tier.get("budget_bytes", 0)
        out["bytes_in_use"] = tier.get("bytes_in_use", 0)
        out["blocks"] = tier.get("blocks", 0)
        out["blocks_host"] = tier.get("blocks_host", 0)
        out["evictions"] = tier.get("evictions", 0)
        out["rejects"] = tier.get("rejects", 0)
        out["rejects_oversize"] = tier.get("rejects_oversize", 0)
        out["rejects_closed"] = tier.get("rejects_closed", 0)
        out["tier_hits"] = tier.get("hits", 0)
        out["tier_misses"] = tier.get("misses", 0)
        out["hit_rate"] = tier.get("hit_rate", 0.0)
        return out

    def health(self) -> dict:
        """Serving health for the /health endpoint: ``status`` in
        ok/degraded/draining, ``serving`` decides 200 vs 503."""
        with self._start_lock:
            live = self._live_locked()
            draining = self._stop or self._migrate_requested
        if not live:
            # a wedged thread (even one noticed during close) beats draining:
            # the operator needs to see the leak, not a polite shutdown
            return {"status": "degraded", "serving": False,
                    "scheduler_thread_live": False}
        if draining:
            return {"status": "draining", "serving": False,
                    "scheduler_thread_live": live}
        return {"status": "ok", "serving": True,
                "scheduler_thread_live": live}

    def page_stats(self) -> Optional[tuple[int, int, int]]:
        """(pool pages, pages in use, high-water mark) for /metrics — the
        KV-HBM story of a paged pool; None on dense engines."""
        if not self.paged:
            return None
        total = self.engine.pool_pages
        return (total, total - len(self._free_pages), self.pages_high_water)

    def _pages_needed(self, n_prompt: int, max_tokens: int) -> int:
        page = self.engine.page_size
        return -(-(n_prompt + max_tokens) // page)

    def kv_read_stats(self) -> Optional[tuple[str, int, int]]:
        """(attention path, KV bytes read last tick, total) for /metrics;
        None on dense engines. Analytic, not measured: ragged counts the
        page-rounded rows each live slot actually occupies, gather counts
        the full slot_pages-wide contiguous view `_paged_read` materializes
        per slot per step — the gap between the two numbers is the traffic
        the ragged kernel deletes."""
        if not self.paged:
            return None
        return (
            self.kv_path, self.kv_bytes_read_last_tick,
            self.kv_bytes_read_total,
        )

    def tick_timing_stats(self) -> dict:
        """Per-tick host/device-blocked timing for /metrics and the bench:
        ``device_blocked_ms`` is the harvest ``device_get`` wait (what the
        async pipeline shrinks by overlapping it with the next block's
        compute), ``host_ms`` is the rest of the tick's wall time. Racy
        snapshot by design — a gauge, not a decision input."""
        n = max(1, self._tick_count)
        return {
            "path": "async" if self._async else "sync",
            "host_ms_last": self.tick_host_ms_last,
            "device_blocked_ms_last": self.tick_device_blocked_ms_last,
            "host_ms_avg": 1000.0 * self._tick_host_s_total / n,
            "device_blocked_ms_avg": 1000.0 * self._tick_blocked_s_total / n,
            "ticks": self._tick_count,
            # resume-path import stall (kv_import): ~0 when prefetch staged
            # the pages, the full host→device marshal on a demand import
            "kv_import_ms_last": self.tick_kv_import_ms_last,
            "kv_import_s_total": self._tick_kv_import_s_total,
        }

    def latency_stats(self) -> dict:
        """Bucketed latency snapshots for /metrics: inter-token latency
        (observed at the emit path) and admission queue wait (submit →
        slot assignment), as :meth:`Histogram.to_dict` snapshots — the
        mergeable currency ReplicaSet/DisaggCoordinator aggregate across
        replicas with :meth:`Histogram.merge_dicts`."""
        return {
            "itl": self._h_itl.to_dict(),
            "queue_wait": self._h_queue_wait.to_dict(),
        }

    def reset_tick_timing(self):
        """Zero the tick-timing accumulators. The first ticks after
        construction pay jit compilation (dispatch-side, so it lands in
        host_ms) — benchmarks reset after their warmup request so the
        averages reflect steady state only."""
        # mst: allow(MST501): advisory reset racing a tick skews one sample
        self.tick_host_ms_last = 0.0
        self.tick_device_blocked_ms_last = 0.0
        # mst: allow(MST501): advisory reset racing a tick skews one sample
        self._tick_host_s_total = 0.0
        self._tick_blocked_s_total = 0.0
        self._tick_count = 0
        self.tick_kv_import_ms_last = 0.0
        self._tick_kv_import_s_total = 0.0

    def _account_kv_read(self, live, steps: int, path: Optional[str] = None):
        if not self.paged or not live:
            return
        page = self.engine.page_size
        if (path or self.kv_path) == "ragged":
            rows = 0
            for _, req in live:
                length = req.prompt.size + max(0, req.produced - 1) + 1
                rows += -(-length // page) * page
        else:
            rows = len(live) * self.engine.slot_pages * page
        b = rows * self._kv_row_bytes * steps
        self.kv_bytes_read_last_tick = b
        self.kv_bytes_read_total += b
        # weights stream once per step regardless of slot count, so per
        # token they amortize over the live slots; KV does not amortize
        tokens = len(live) * steps
        self.kv_bytes_per_token_last = b / tokens
        self.weight_bytes_per_token_last = (
            getattr(self.engine, "weight_stream_bytes", 0) / len(live)
        )

    def hbm_bytes_per_token_stats(self) -> Optional[dict]:
        """{"weights": bytes, "kv": bytes} streamed per decoded token on the
        last decode tick (analytic, from the same model as kv_read_stats);
        None on dense engines. Exported as
        ``mst_decode_hbm_bytes_per_token{kind=}``."""
        if not self.paged:
            return None
        return {
            "weights": self.weight_bytes_per_token_last,
            "kv": self.kv_bytes_per_token_last,
        }

    def prefix_stats(self) -> Optional[tuple[int, int, int, int, int]]:
        """(queries, hits, tokens reused, evictions, cached pages) for
        /metrics; None unless the prefix cache is on."""
        if not (self.paged and self.prefix_cache):
            return None
        return (
            self.prefix_queries, self.prefix_hits, self.prefix_tokens_reused,
            self.prefix_evictions, len(self._prefix_index),
        )

    def _prefix_keys(self, req: _Request) -> list[bytes]:
        """Rolling content-addressed key per FULL prompt page (the vLLM
        block-hash scheme): key_i = blake2b over pages 0..i, chained so the
        whole prompt is hashed once — O(prompt) total, 16 bytes retained per
        page. Memoized on the request (recomputing per _fits poll would make
        a blocked fifo head quadratic)."""
        if req._pkeys is None:
            page = self.engine.page_size
            h = hashlib.blake2b(digest_size=16)
            keys = []
            for i in range(int(req.prompt.size) // page):
                h.update(req.prompt[i * page : (i + 1) * page].tobytes())
                keys.append(h.digest())
            req._pkeys = keys
        return req._pkeys

    def _prefix_lookup(self, req: _Request) -> list[tuple[bytes, int]]:
        """Longest chain of registered pages covering a page-aligned prefix
        of the request's prompt. Capped one token short of the full prompt:
        the last prompt token must go through prefill to produce the logits
        the first sample needs."""
        if not self.prefix_cache:
            return []
        page = self.engine.page_size
        keys = self._prefix_keys(req)
        chain: list[tuple[bytes, int]] = []
        for i in range((int(req.prompt.size) - 1) // page):
            p = self._prefix_index.get(keys[i])
            if p is None:
                break
            chain.append((keys[i], p))
        return chain

    def _evictable_pages(self, exclude: tuple = ()) -> int:
        ex = set(exclude)
        return sum(
            1 for p in self._prefix_index.values()
            if self._page_ref.get(p) == 1 and p not in ex
        )

    def _evict_for(self, n_needed: int):
        """Drop LRU index entries whose page no live slot maps until the
        free list can cover ``n_needed`` pages."""
        while len(self._free_pages) < n_needed:
            victim = next(
                (k for k, p in self._prefix_index.items()
                 if self._page_ref.get(p) == 1),
                None,
            )
            if victim is None:
                return
            p = self._prefix_index.pop(victim)
            self._page_ref.pop(p, None)
            self._free_pages.append(p)
            self.prefix_evictions += 1
            _note_pages(self, (p,), acquired=False)

    def _write_table_row(self, slot: int, pages: list):
        """Publish a slot's page mapping to the device table and bump the
        pool high-water mark. Unmapped tail entries stay at the scratch
        page (index pool_pages): overshoot writes past the mapping land
        there harmlessly."""
        row = np.full((self.engine.slot_pages,), self.engine.pool_pages,
                      np.int32)
        row[: len(pages)] = pages
        self.table = self._row_set(
            self.table, self._put(jnp.asarray(slot, jnp.int32)),
            self._put(jnp.asarray(row)),
        )
        in_use = self.engine.pool_pages - len(self._free_pages)
        self.pages_high_water = max(self.pages_high_water, in_use)

    def _unref_pages(self, pages):
        for p in pages:
            r = self._page_ref.get(p, 1) - 1
            if r <= 0:
                self._page_ref.pop(p, None)
                self._free_pages.append(p)
                _note_pages(self, (p,), acquired=False)
            else:
                self._page_ref[p] = r

    def _release_pages(self, slot: int):
        self._unref_pages(self._pages_of.pop(slot, []))

    # ------------------------------------------ prefix store (fleet-wide)
    def _store_digests(self, req: _Request) -> list:
        """The request's chained chunk digests for store keying, memoized
        like ``_pkeys`` (recomputing per _fits poll would make a blocked
        fifo head quadratic). Cleared whenever the prompt changes (fold)."""
        if req._sdigests is None:
            req._sdigests = self.prefix_store.digests_for(req.prompt)
        return req._sdigests

    def _store_lookup(self, req: _Request) -> Optional[tuple]:
        """Poll-safe store LPM for ``req``; absorbs the
        ``cache.prefix_lookup`` fault site into a counted no-hit — the
        stream degrades to plain prefill, never drops."""
        digests = self._store_digests(req)
        if not digests:
            return None
        try:
            # bind the request's trace for the store's self-instrumented
            # prefix_lookup span (tracing.current() inside the store)
            with tracing.bind(req._trace):
                return self.prefix_store.lookup(self, digests)
        except Exception as e:
            self.prefix_store.count_lookup_fault()
            logging.getLogger(__name__).debug(
                "prefix-store lookup failed (plain prefill): %s", e
            )
            return None

    def _store_admit(self, req: _Request, plan: tuple,
                     n: int) -> Optional[tuple]:
        """Admission-side half of a store hit: returns ``(pages,
        reused_tokens)`` for the slot, or None to fall back to plain
        prefill admission (the plan went stale between _fits and here).

        Device plan: lease the entry's shared pages copy-on-write — the
        slot maps them read-only (its own +1 per page on top of the
        entry's claim) and allocates only the uncovered tail; decode and
        tail-prefill write past ``reused_tokens``, so a fork never touches
        the shared prefix. Host plan: allocate the full need fresh and
        scatter the tier block into the prefix pages (prefetch-staged when
        the waiting-line pass got to it, counted demand import otherwise),
        then re-register the imported pages as a device entry so the next
        same-pool admission shares them zero-copy. An import failure keeps
        the already-mapped pages and prefills from token 0 — token-exact
        either way."""
        store = self.prefix_store
        kind, cover = plan
        digests = self._store_digests(req)
        if len(digests) < cover:
            return None  # prompt changed since the plan was computed
        if kind == "device":
            lease = store.acquire(self, digests, cover)
            if lease is None:
                store.count_lookup("miss", digests)
                return None  # entry demoted since _fits; plain prefill
            store.count_lookup("device")
            for p in lease.pages:
                # the slot's own claim on each shared page, released by
                # _release_pages like any mapped page; the entry's claim
                # (+1 at registration) outlives the slot
                self._page_ref[p] += 1
            tail: list[int] = []
            try:
                self._evict_for(n - cover)
                for _ in range(n - cover):
                    tail.append(self._free_pages.pop())
            except BaseException:
                # overcommit race: the headroom _fits saw evaporated
                # before the tail allocation — give back the partial
                # pops, the slot's claims and the COW lease, or the
                # entry can never demote
                self._free_pages.extend(tail)
                for p in lease.pages:
                    self._page_ref[p] -= 1
                lease.release()
                raise
            _note_pages(self, tail, acquired=True)
            pages = list(lease.pages) + tail
            for p in tail:
                self._page_ref[p] = 1
            req._please = lease
            return pages, lease.n_tokens
        block = store.host_block(digests[cover - 1])
        if block is None:
            store.count_lookup("miss", digests)
            return None  # evicted since _fits; plain prefill
        store.count_lookup("host")
        self._evict_for(n)
        pages = [self._free_pages.pop() for _ in range(n)]
        _note_pages(self, pages, acquired=True)
        for p in pages:
            self._page_ref[p] = 1
        page = self.engine.page_size
        try:
            was_staged = block.is_prefetched
            t0 = time.perf_counter()
            with tracing.bind(req._trace):
                self.cache = import_block(
                    self.cache, block, pages[:cover],
                    share_hash=self._share_hash, codec=self._kv_codec,
                    scatter=self._import_pages, put=self._put,
                )
            dt = time.perf_counter() - t0
            self.tick_kv_import_ms_last = dt * 1e3
            self._tick_kv_import_s_total += dt
            tr = req._trace
            if tr is not None:
                tr.add("handoff_import", t0, t0 + dt, kind="prefix_store",
                       pages=cover, staged=was_staged)
            store.count_import(staged=was_staged, n_tokens=cover * page)
            with self._admission_lock:
                if was_staged:
                    self.prefetch_hits += 1
                else:
                    self.demand_imports += 1
        except Exception as e:
            # the pages are already this slot's — keep them and prefill
            # the whole prompt into them; nothing reached the consumer,
            # so the stream stays token-exact
            store.count_import_fault()
            logging.getLogger(__name__).debug(
                "prefix-store block import failed (re-prefill): %s", e
            )
            return pages, 0
        block.drop_prefetch()  # staged copies served their one import
        lease = store.register(
            self, digests[:cover], pages[:cover],
            req.prompt[: cover * page], cover * page * self._kv_row_bytes,
            force=True,
        )
        if lease is not None:
            for p in lease.pages:
                self._page_ref[p] += 1  # the promoted entry's own claim
            req._please = lease
        return pages, cover * page

    def _store_insert(self, req: _Request):
        """Register a freshly prefilled prompt's page-aligned prefix in the
        store, under its insertion policy. Bookkeeping only — dict entries
        and refcounts, no device work — which is what keeps this legal in
        the tick-hot prefill-completion path (MST111 polices the opposite:
        store traffic that marshals host bytes in tick-hot code). The
        request itself holds the entry's first lease; pages it registered
        become shared the moment a same-prefix admission leases them."""
        store = self.prefix_store
        digests = self._store_digests(req)
        if not digests:
            return
        k = len(digests)
        pages = self._pages_of.get(req.slot, [])[:k]
        if len(pages) < k:
            return
        page = self.engine.page_size
        lease = store.register(
            self, digests, pages, req.prompt[: k * page],
            k * page * self._kv_row_bytes,
        )
        if lease is None:
            return
        for p in lease.pages:
            self._page_ref[p] += 1  # the entry's own claim on each page
        req._please = lease

    def _drop_prefix_lease(self, req: _Request):
        """Release ``req``'s prefix lease exactly once (idempotent via the
        None swap; a true double release raises inside the store). On the
        LAST release the entry comes back for demotion: its pages leave
        the device for the host tier and return to the free list."""
        lease, req._please = req._please, None
        if lease is None:
            return
        entry = lease.release()
        if entry is not None:
            self._demote_prefix_entry(entry)

    def _demote_prefix_entry(self, entry):
        """Last-release demotion: export the entry's pages as a pure-prefix
        ``KVPageBlock`` (dispatch-only gather; the device→host copy runs on
        the host tier's flusher) keyed by the full-chain digest, then
        return the pages to the pool. Skips the export when the host tier
        already holds the digest (a re-imported prefix demoting again);
        any failure — injected ``cache.export``, tier budget reject —
        just drops the prefix (re-prefilled on next use), never an error
        the stream can see."""
        store = self.prefix_store
        digest = entry.digests[-1]
        try:
            if not store.host_contains(digest):
                block = export_block(
                    self.cache, entry.pages,
                    page_size=self.engine.page_size,
                    n_tokens=len(entry.pages) * self.engine.page_size,
                    prompt=entry.tokens, history=[], produced=0,
                    resume_keys=None, resume_recent=None,
                    share_hash=self._share_hash, codec=self._kv_codec,
                    gather=self._export_pages, put=self._put,
                )
                store.host_put(digest, block)
        except Exception as e:
            store.count_demote_drop()
            logging.getLogger(__name__).debug(
                "prefix demotion export failed (prefix dropped): %s", e
            )
        self._unref_pages(entry.pages)

    def _pod_fetch_waiting(self):
        """Consult the pod view for head-of-line waiting requests whose
        prefix missed the LOCAL store (pod.PodPrefixFederation): when a
        live peer's gossiped inventory advertises the digest, a background
        worker pulls the owner's exported block into the local host tier
        — pod-wide, the prefix prefills ONCE — while ``_fits`` holds the
        request on the ``_podfetch`` flag. Every failure (fault, stale
        inventory, owner death, timeout, integrity) resolves the flag and
        the request prefills plain: degraded, never dropped. All
        federation traffic lives here and in the worker thread, off the
        tick-hot functions (MST115)."""
        store = self.prefix_store
        fed = getattr(store, "federation", None) if store is not None \
            else None
        if fed is None or not self._waiting:
            return
        for req in self._waiting[:4]:
            if req.cancelled or req.spilled or req._block is not None \
                    or req._podfetch is not None:
                continue
            digests = self._store_digests(req)
            if not digests or self._store_lookup(req) is not None:
                req._podfetch = "done"  # nothing to federate / local hit
                continue
            req._podfetch = "pending"
            threading.Thread(
                target=self._pod_fetch_one, args=(req, digests[-1]),
                name="mst-pod-prefix-fetch", daemon=True,
            ).start()

    def _pod_fetch_one(self, req: _Request, digest: bytes):
        """Background federation fetch for one waiting request. The
        federation counts every outcome by kind; this worker only flips
        the admission gate — on success the next ``_store_lookup`` poll
        sees the host-tier hit and admission imports it via the ordinary
        staged-prefetch/demand path."""
        try:
            self.prefix_store.federation.fetch(digest)
        except Exception as e:  # noqa: BLE001 — degrade to plain prefill
            logging.getLogger(__name__).debug(
                "pod prefix fetch failed (plain prefill): %s", e
            )
        req._podfetch = "done"

    def _prefetch_store_waiting(self):
        """Stage host-tier prefix blocks for head-of-line waiting requests
        (the same PRESERVE-style overlap as the spill prefetch): a
        dispatch-only ``device_put`` here means the admission scatter a few
        ticks later consumes device-resident arrays instead of
        demand-marshaling host numpy. Bounded like _prefetch_waiting so a
        deep queue can't turn the pass into a copy storm."""
        store = self.prefix_store
        if store is None or not self._waiting:
            return
        budget = 2
        for req in self._waiting[:4]:
            if budget == 0:
                break
            if req.cancelled or req.spilled or req._block is not None:
                continue
            plan = self._store_lookup(req)
            if plan is None or plan[0] != "host":
                continue
            digests = self._store_digests(req)
            block = store.host_block(digests[plan[1] - 1])
            if block is None or not block.is_host or block.is_prefetched:
                continue
            budget -= 1
            try:
                block.prefetch(put=self._put, codec=self._kv_codec)
                with self._admission_lock:
                    self.prefetches += 1
            except Exception as e:
                with self._admission_lock:
                    self.prefetch_faults += 1
                logging.getLogger(__name__).debug(
                    "prefix block prefetch failed (demand import): %s", e
                )

    def stage_resume(self, state) -> bool:
        """Dispatch-only host→device staging of an incoming resume block —
        the pod receiving host calls this BEFORE submitting the shipped
        request (``generate_step(..., _resume=state)``), so the block's
        host→device DMA rides alongside the decode block already in
        flight and the admission scatter consumes device-resident arrays
        (the same PRESERVE-style overlap as the spill/store prefetch
        passes). Returns True when a stage was dispatched; any failure is
        absorbed into the counted demand-import path."""
        block = getattr(state, "block", None)
        if block is None or not getattr(block, "is_host", False) \
                or block.is_prefetched:
            return False
        try:
            block.prefetch(put=self._put, codec=self._kv_codec)
            with self._admission_lock:
                self.prefetches += 1
            return True
        except Exception as e:  # noqa: BLE001 — degrade to demand import
            with self._admission_lock:
                self.prefetch_faults += 1
            logging.getLogger(__name__).debug(
                "resume block prefetch failed (demand import): %s", e
            )
            return False

    def close(self, timeout: float = 10.0):
        with self._start_lock:
            self._stop = True
            t = self._thread
        if t is not None:
            if t.is_alive():
                # a sentinel for a dead thread would sit in _submit forever,
                # inflating the queued gauge (and the pod-gossiped pressure)
                # by one per repeated close
                # mst: allow(MST201): wake sentinel; Queue locks internally
                self._submit.put(None)  # wake the idle wait
            t.join(timeout=timeout)
            if t.is_alive():
                # a tick is wedged (stuck device op / injected fault): the
                # daemon thread can't be reclaimed, so record the leak —
                # /health flips to degraded and mst_scheduler_thread_live
                # drops to 0 instead of pretending the close succeeded
                with self._start_lock:
                    self.thread_wedged = True
                # post-mortem: freeze the flight recorder so the wedged
                # tick's victims keep their timelines after the ring cycles
                tracing.auto_snapshot("wedge:scheduler")
                logging.getLogger(__name__).error(
                    "scheduler thread failed to exit within %.0fs — a tick "
                    "is wedged; the thread is abandoned (daemon) and /health "
                    "now reports degraded", timeout,
                )
        spill = self.spill
        if spill is not None:
            spill.close()
        store = self.prefix_store
        if store is not None:
            # drop this engine's device entries from the fleet store: the
            # pool backing those pages is going away with the engine, so
            # any index entry pointing at them would be a use-after-free
            # for the next admission. Host-tier blocks survive (they're
            # self-contained numpy) and keep serving other replicas.
            store.drop_owner(self)
        # the page pool dies with the engine: index-resident prefix pages
        # (legitimately out of the free list while the batcher lives) are
        # discarded wholesale, so retire them from the leak ledger too
        oid = id(self)
        note_reset("scheduler.page", lambda k: k[0] == oid)
        # release engine-held resources (a shared-weight store lease drops
        # its ref here — drain/retire/hot-swap all funnel through close())
        eng_close = getattr(self.engine, "close", None)
        if eng_close is not None:
            eng_close()
        draft = self.draft
        if draft is not None and hasattr(draft, "close"):
            draft.close()

    # ------------------------------------------------------------ internals
    def _ensure_running(self):
        with self._start_lock:
            if self._thread is None or not self._thread.is_alive():
                self._stop = False
                self._thread = threading.Thread(
                    target=self._loop, name="continuous-batcher", daemon=True
                )
                self._thread.start()

    def _first_sample_fn(self, logits, keys, sp, recent, rep_sizes, slot):
        """Sample the first token of the request in ``slot`` from its prefill
        logits, using the same split-then-sample key chain as the decode
        step, leaving other slots' keys untouched. ``logits`` is the (1, V)
        prefill output; the returned logprobs keep that shape (indexing a
        global array must stay inside this jit)."""
        split = jax.random.split(keys[slot])
        key_new, sub = split[0], split[1]
        row = jnp.arange(self.W) >= self.W - rep_sizes[slot]
        masked = jnp.where(row, recent[slot], -1)
        tok, logprobs = sample_token_batched(
            sub[None],
            logits.reshape(1, -1),
            jax.tree.map(lambda x: x[slot][None], sp),
            masked[None],
        )
        keys = keys.at[slot].set(key_new)
        recent = recent.at[slot].set(
            jnp.concatenate([recent[slot, 1:], tok.astype(jnp.int32)])
        )
        return tok[0], logprobs, keys, recent

    def _assign_slot(self, req: _Request, slot: int):
        """Claim ``slot`` for ``req`` and reset its device-side state: offset
        0, repetition window seeded from the prompt tail (same as
        init_recent_tokens in the serial path), the request's sampler params
        and PRNG key. Prefill happens incrementally in the loop — one chunk
        per scheduler tick — so active slots keep decoding during admission."""
        prompt = req.prompt
        slot_arr = self._put(jnp.asarray(slot, jnp.int32))
        # queue wait ends here: submit (or re-queue after preempt/wake) →
        # slot assignment. Histogram always; span only when traced.
        now = time.perf_counter()
        if req._t_submit:
            self._h_queue_wait.observe(max(0.0, now - req._t_submit))
        tr = req._trace
        if tr is not None:
            tr.add("queue_wait", req._t_submit or now, now, slot=slot)
        reused_tokens = 0
        req.admit_seq = self._admit_counter
        self._admit_counter += 1
        block = self._take_block(req)
        if block is not None and self._import_block(req, slot, slot_arr, block):
            return
        if self.paged:
            n = self._need_pages(req)
            if self.prefix_store is not None:
                # one admitted request == one token of insert budget (the
                # deterministic damping clock — no wall time on this path)
                self.prefix_store.note_admission()
                splan, req._splan = req._splan, None
                got = self._store_admit(req, splan, n) if splan else None
                if got is None and splan is None:
                    self.prefix_store.count_lookup(
                        "miss", self._store_digests(req) or None
                    )
                if got is not None:
                    pages, reused_tokens = got
                    self._pages_of[slot] = pages
                    self._write_table_row(slot, pages)
                    self.cache = self.cache._replace(
                        offset=self._row_set(
                            self.cache.offset, slot_arr,
                            self._put(jnp.asarray(reused_tokens, jnp.int32)),
                        )
                    )
                    self._write_sampler_row(req, slot_arr)
                    self._slots[slot] = req
                    note_acquire("scheduler.slot", (id(self), slot))
                    req.slot = slot
                    # prefill only the uncovered tail; the shared (or
                    # imported) prefix KV is already mapped to this slot
                    req.prefill_pos = reused_tokens
                    return
            chain = req._chain if req._chain is not None else self._prefix_lookup(req)
            req._chain = None
            if self.prefix_cache:
                self.prefix_queries += 1
                if chain:
                    self.prefix_hits += 1
                    reused_tokens = len(chain) * self.engine.page_size
                    self.prefix_tokens_reused += reused_tokens
                for key, _ in chain:
                    self._prefix_index.move_to_end(key)
            shared = [p for _, p in chain]
            # claim the chain BEFORE evicting: at ref 2 its pages are
            # invisible to _evict_for, which must only reclaim OTHER
            # index-only pages (matching the _fits exclude accounting)
            for p in shared:
                self._page_ref[p] += 1
            self._evict_for(n - len(shared))
            pages = shared + [
                self._free_pages.pop() for _ in range(n - len(shared))
            ]
            _note_pages(self, pages[len(shared):], acquired=True)
            for p in pages[len(shared):]:
                self._page_ref[p] = 1
            self._pages_of[slot] = pages
            self._write_table_row(slot, pages)
        self.cache = self.cache._replace(
            offset=self._row_set(
                self.cache.offset, slot_arr,
                self._put(jnp.asarray(reused_tokens, jnp.int32)),
            )
        )
        self._write_sampler_row(req, slot_arr)
        if self.draft is not None:
            # the draft mirrors the slot from position 0 (no page sharing)
            self.dcache = self.dcache._replace(
                offset=self._row_set(
                    self.dcache.offset, slot_arr,
                    self._put(jnp.asarray(0, jnp.int32)),
                )
            )
        if self.spec_tracker is not None:
            # new stream in the slot: window back to the probe rung, no
            # carried-over acceptance history from the previous occupant
            self.spec_tracker.reset(slot)
        self._slots[slot] = req
        note_acquire("scheduler.slot", (id(self), slot))
        req.slot = slot
        # prefill starts past the reused prefix — its KV is already mapped
        req.prefill_pos = reused_tokens

    def _write_sampler_row(self, req: _Request, slot_arr):
        # pad the request's sampler params to the batched width host-side,
        # then write its row inside jit (set_sampler_slot is eager)
        width = self.sp.bias_indices.shape[1]
        one = req.sp
        n_bias = one.bias_indices.shape[0]
        if n_bias < width:
            one = one._replace(
                bias_indices=jnp.pad(one.bias_indices, (0, width - n_bias)),
                bias_values=jnp.pad(one.bias_values, (0, width - n_bias)),
            )
        self.sp = self._sp_set(self.sp, jax.tree.map(self._put, one), slot_arr)
        self.rep_sizes = self._row_set(
            self.rep_sizes, slot_arr,
            self._put(jnp.asarray(req.rep_context, jnp.int32)),
        )

    def _take_block(self, req: _Request) -> Optional[object]:
        """Resolve the request's pending KVPageBlock, if any: one handed in
        by the dispatcher (cross-replica migration) or one parked in the
        spill tier at preemption. A tier entry that was LRU-evicted since
        the preemption degrades here to the discard path — fold and
        re-prefill, still token-exact via the stashed sampler rows."""
        if req._block is not None:
            block, req._block = req._block, None
            return block
        if not req.spilled:
            return None
        req.spilled = False
        block = self.spill.take(req) if self.spill is not None else None
        if block is None:
            self._fold_history(req)
            with self._admission_lock:
                self.spill_fallbacks += 1
        return block

    def _import_block(self, req: _Request, slot: int, slot_arr, block) -> bool:
        """Admission via page import: allocate the request's pages and
        scatter the block's payload into them instead of re-prefilling,
        then restore the sampler state the block carries — offset, PRNG
        row, repetition window, and the pending last token — so the next
        decode step emits exactly what the uninterrupted run would have.
        Any failure (fault-injected ``cache.import``, corrupt block, pool
        exhausted mid-import, geometry mismatch) releases what was claimed
        and returns False: the caller falls back to normal re-prefill
        admission, which can never double-emit because nothing was queued
        to the consumer here."""
        if not self.paged or self.draft is not None:
            self._fold_history(req)
            return False
        page = self.engine.page_size
        pages: list = []
        try:
            if block.page_size != page:
                raise ValueError(
                    f"block page_size {block.page_size} != pool page {page}"
                )
            data_pages = block.n_pages
            need = max(self._need_pages(req, block=block), data_pages)
            self._evict_for(need)
            if len(self._free_pages) < need:
                raise RuntimeError(
                    f"target pool exhausted mid-import: need {need} pages, "
                    f"{len(self._free_pages)} free"
                )
            pages = [self._free_pages.pop() for _ in range(need)]
            _note_pages(self, pages, acquired=True)
            for p in pages:
                self._page_ref[p] = 1
            # residency accounting, read BEFORE the import consumes the
            # stage: a host block with device-staged pages is the overlapped
            # path (prefetch hit); host without a stage is the demand import
            # this PR demotes to a counted fallback; a still-device block
            # (flusher hasn't run) is neither
            was_host = block.is_host
            was_staged = block.is_prefetched
            t0 = time.perf_counter()
            with tracing.bind(req._trace):
                self.cache = import_block(
                    self.cache, block, pages[:data_pages],
                    share_hash=self._share_hash, codec=self._kv_codec,
                    scatter=self._import_pages, put=self._put,
                )
            dt = time.perf_counter() - t0
            self.tick_kv_import_ms_last = dt * 1e3
            self._tick_kv_import_s_total += dt
            tr = req._trace
            if tr is not None:
                tr.add("handoff_import", t0, t0 + dt, pages=data_pages,
                       staged=was_staged)
            if was_host:
                with self._admission_lock:
                    if was_staged:
                        self.prefetch_hits += 1
                    else:
                        self.demand_imports += 1
        except Exception as e:
            logging.getLogger(__name__).debug(
                "KV block import failed (falling back to re-prefill): %s", e
            )
            if pages:
                self._pages_of[slot] = pages
                self._release_pages(slot)
            self._fold_history(req)
            with self._admission_lock:
                self.spill_fallbacks += 1
            return False
        self._pages_of[slot] = pages
        self._write_table_row(slot, pages)
        # offset = valid KV rows; the next decode step writes row n_tokens
        self.cache = self.cache._replace(
            offset=self._row_set(
                self.cache.offset, slot_arr,
                self._put(jnp.asarray(block.n_tokens, jnp.int32)),
            )
        )
        self._write_sampler_row(req, slot_arr)
        self.recent = self._row_set(
            self.recent, slot_arr, self._put(jnp.asarray(block.resume_recent))
        )
        self.keys = self._row_set(
            self.keys, slot_arr, self._put(jnp.asarray(block.resume_keys))
        )
        self.last_tok = self._set_last(
            self.last_tok, slot_arr,
            self._put(jnp.asarray(block.last_tok, jnp.int32)),
        )
        self.active = self._row_set(
            self.active, slot_arr, self._put(jnp.asarray(True))
        )
        req.resume_keys = None
        req.resume_recent = None
        req.history = [int(t) for t in block.history]
        self._slots[slot] = req
        note_acquire("scheduler.slot", (id(self), slot))
        req.slot = slot
        req.prefill_pos = req.prompt.size
        req.draft_pos = req.prompt.size
        with self._admission_lock:
            self.spill_hits += 1
        return True

    @staticmethod
    def _chunk_at(prompt: np.ndarray, pos: int, c: int):
        """Slice one right-padded prefill chunk at ``pos``; returns
        (chunk (c,), n_valid) — shared by the target and draft branches so
        their padding semantics can never diverge."""
        chunk = prompt[pos : pos + c]
        n_valid = chunk.size
        if n_valid < c:
            chunk = np.pad(chunk, (0, c - n_valid))
        return chunk, n_valid

    def _prefill_done(self, req: _Request) -> bool:
        """Admission prefill complete on EVERY engine: the target (which may
        start past a reused prefix) and, when speculating, the draft (which
        always prefills from 0)."""
        return req.prefill_pos >= req.prompt.size and (
            self.draft is None or req.draft_pos >= req.prompt.size
        )

    def _prefill_one_chunk(self, req: _Request):
        """Run ONE prefill chunk for a mid-admission request — on the target
        and, when speculating, the draft, each at its own position (a prefix
        hit advances only the target's start). On the last chunk of BOTH,
        sample the first token and activate the slot for decode; the
        target's final-chunk logits are stashed while the draft catches up."""
        eng = self.engine
        c = eng.prefill_chunk
        slot_arr = self._put(jnp.asarray(req.slot, jnp.int32))
        tr = req._trace
        t0 = time.perf_counter() if tr is not None else 0.0
        if req.prefill_pos < req.prompt.size:
            chunk, n_valid = self._chunk_at(req.prompt, req.prefill_pos, c)
            logits, self.cache = eng.prefill_slot()(
                eng.layer_params, eng.layer_masks, eng.vocab_parts,
                eng.shared_params, self._put(jnp.asarray(chunk[None])),
                slot_arr, self.cache,
                self._put(jnp.asarray(n_valid, jnp.int32)),
                self.table if self.paged else None,
            )
            req.prefill_pos += n_valid
            if req.prefill_pos >= req.prompt.size:
                req._last_logits = logits
        if self.draft is not None and req.draft_pos < req.prompt.size:
            d = self.draft
            chunk, n_valid = self._chunk_at(req.prompt, req.draft_pos, c)
            _, self.dcache = d.prefill_slot()(
                d.layer_params, d.layer_masks, d.vocab_parts, d.shared_params,
                self._put(jnp.asarray(chunk[None])), slot_arr, self.dcache,
                self._put(jnp.asarray(n_valid, jnp.int32)), None,
            )
            req.draft_pos += n_valid
        if tr is not None:
            tr.add("prefill", t0, time.perf_counter(), slot=req.slot,
                   pos=req.prefill_pos, chunk=c)
        if not self._prefill_done(req):
            return
        logits = req._last_logits
        req._last_logits = None

        if self.prefix_cache:
            # Register every FULL prompt page under its whole-prefix content
            # key. Decode writes start at prompt.size, past all of them, so a
            # registered page is immutable for its pool lifetime. Pages a
            # concurrent identical prompt registered first just get touched.
            pages = self._pages_of.get(req.slot, [])
            for i, key in enumerate(self._prefix_keys(req)):
                if key in self._prefix_index:
                    self._prefix_index.move_to_end(key)
                    continue
                self._prefix_index[key] = pages[i]
                self._page_ref[pages[i]] = self._page_ref.get(pages[i], 0) + 1
        elif self.prefix_store is not None and req._please is None:
            # fleet-store insertion (bookkeeping only — refcounts and dict
            # entries, no device work on this hot path): the freshly
            # prefilled full prompt pages become a shareable device entry,
            # subject to the store's min-hits / burst / brownout damping.
            # A slot that ADMITTED via the store (req._please set) already
            # holds its lease — re-registering would double-claim pages.
            self._store_insert(req)

        # Seed the PRNG key and repetition window only NOW: decode ticks for
        # other slots ran between this request's chunks and they split/shift
        # ALL M rows — setting these at assignment would leave the slot with
        # mangled state by prefill completion and break the deterministic
        # serial-parity guarantee for multi-chunk prompts.
        W = self.W
        if req.resume_keys is not None:
            # resuming a preempted request: restore the stashed sampler state
            # so the sample below continues the request's exact PRNG chain
            # and repetition window — the token it emits is the one the
            # uninterrupted run would have produced next
            self.recent = self._row_set(
                self.recent, slot_arr, self._put(jnp.asarray(req.resume_recent))
            )
            self.keys = self._row_set(
                self.keys, slot_arr, self._put(jnp.asarray(req.resume_keys))
            )
            req.resume_keys = None
            req.resume_recent = None
        else:
            row = np.full((W,), -1, np.int32)
            tail = (
                req.prompt[-req.rep_context:] if req.rep_context
                else req.prompt[:0]
            )
            if tail.size:
                row[W - tail.size:] = tail
            self.recent = self._row_set(
                self.recent, slot_arr, self._put(jnp.asarray(row))
            )
            self.keys = self._row_set(
                self.keys, slot_arr, self._put(jax.random.PRNGKey(req.seed))
            )

        tok, logprobs, self.keys, self.recent = self._first_sample(
            logits, self.keys, self.sp, self.recent, self.rep_sizes, slot_arr
        )
        self.last_tok = self._set_last(self.last_tok, slot_arr, tok)
        self.active = self._row_set(
            self.active, slot_arr, self._put(jnp.asarray(True))
        )
        self._emit(req, int(tok), logprobs)
        if req.prefill_only and req.slot >= 0:
            # disaggregated handoff: the first token is the prefill
            # replica's whole deliverable — park the request; the tick
            # exports its block (off this hot path) before dispatching
            # decode, so the slot never enters a decode block here
            self._handoff_ready.append(req)

    def _emit(self, req: _Request, token: int, logprobs):
        now = time.perf_counter()
        if req.produced == 0:
            # first token leaves the scheduler: the TTFT stamp on a traced
            # timeline (the TTFT histogram itself is recorded server-side,
            # where the client-visible first write happens)
            tr = req._trace
            if tr is not None:
                tr.point("first_token", slot=req.slot)
        elif req._t_last_emit:
            # inter-token latency: the gap between consecutive emits of one
            # stream — always-on metric, same grade as the tick counters
            self._h_itl.observe(now - req._t_last_emit)
        req._t_last_emit = now
        req.produced += 1
        # history is the tokens emitted since the last prompt fold — the
        # overcommit preempt/resume bookkeeping, and (always, since drain
        # can migrate any request) the payload a ResumeState ships so the
        # target replica can continue this exact stream
        req.history.append(int(token))
        # decode blocks emit TokenLogprobs summaries (or None); the first
        # token of a request still carries a lazy (1, V) device row from its
        # prefill sample — the server handles both forms
        req.out.put((token, logprobs))
        if req.produced >= req.max_tokens:
            self._finish(req)

    def _finish(self, req: _Request):
        if req.slot >= 0:
            self.active = self._row_set(
                self.active, self._put(jnp.asarray(req.slot, jnp.int32)),
                self._put(jnp.asarray(False)),
            )
            if self.paged:
                # The slot is inactive from the next DISPATCH on (garbage
                # ticks route to the scratch table row), so its pages go
                # back to the pool immediately — even when an async
                # lookahead block is still writing them. Safe because the
                # only later writers of a recycled page (growth for another
                # slot's NEXT dispatch; admission prefill, which quiesces
                # first) are blocks the in-flight one strictly precedes on
                # the device stream, and both attention paths mask rows past
                # each owner's frontier — the same property that makes
                # dirty-page recycling sound in sync mode. Decode-region
                # garbage can never reach an index-registered prompt page
                # (registration covers only full PROMPT pages; decode
                # writes start past them). Index-registered pages survive
                # as cache entries until LRU eviction needs them back.
                self._release_pages(req.slot)
                # the slot's claim on any store-shared prefix pages is gone
                # with _release_pages; the lease is the ENTRY's lifetime —
                # last release demotes the prefix to the host tier
                # (dispatch-only export; the flusher does the host copy)
                self._drop_prefix_lease(req)
                if self._inflight is not None:
                    # the in-flight block's frozen active mask advances this
                    # dead slot's offset one block past its true end; queue
                    # a rewind CHAINED AFTER it (self.cache is its output
                    # future) so the reclaimed slot's offset never points
                    # past the pages just returned — no host sync involved.
                    # A speculative round advances by its data-dependent
                    # accepted count, not decode_block: rewind by the same
                    # device-side value (still future-chained, still async)
                    if isinstance(self._inflight, _InflightSpec):
                        amount = self._inflight.outs[0][req.slot]
                    else:
                        amount = self._put(
                            jnp.asarray(self.decode_block, jnp.int32)
                        )
                    self.cache = self._rewind_offset(
                        self.cache,
                        self._put(jnp.asarray(req.slot, jnp.int32)),
                        amount,
                    )
            self._slots[req.slot] = None
            note_release("scheduler.slot", (id(self), req.slot))
            req.slot = -1
        # completion stamp for the drain-rate Retry-After estimate; cancelled
        # reaps count too — they free queue capacity all the same
        with self._admission_lock:
            self._finish_times.append(self._clock())
        tr = req._trace
        if tr is not None:
            tr.point("finish", produced=req.produced)
            if req._trace_own:
                # a self-begun trace retires here; a server-owned one is
                # finished by the server after its last SSE write
                tracing.finish(tr)
        req.out.put(None)

    def _reap_cancelled(self):
        for req in list(self._slots):
            if req is not None and req.cancelled:
                self._finish(req)

    def _decode_block_prog(self, want_lp: bool):
        """``decode_block`` continuous-batching steps scanned into one
        program; the active mask is frozen for the block (a slot finishing
        mid-block keeps computing — its extra tokens are clamp-written into
        its own cache region and discarded host-side, so other slots'
        streams are unaffected and serial parity holds)."""
        if want_lp not in self._decode_block_progs:
            eng = self.engine
            step, M = eng.decode_cb(), self.M

            def block(layer_params, masks, vparts, shared, tok, cache, active,
                      recent, keys, sp, rep_sizes, table):
                def body(carry, _):
                    tok, cache, recent, keys = carry
                    tok, logprobs, cache, recent, keys = step(
                        layer_params, masks, vparts, shared, tok, cache,
                        active, recent, keys, sp, rep_sizes, table,
                    )
                    if want_lp:
                        out = (tok, *block_lp_outputs(tok.reshape(M), logprobs))
                    else:
                        out = (tok,)
                    return (tok, cache, recent, keys), out

                (tok, cache, recent, keys), outs = jax.lax.scan(
                    body, (tok, cache, recent, keys), None,
                    length=self.decode_block,
                )
                return outs, tok, cache, recent, keys

            # The CPU client executes donated computations inline at
            # dispatch (no async stream to alias on), which would serialize
            # the async pipeline: block t+1's dispatch would block for its
            # own execution. Donation only pays on accelerator backends —
            # there it aliases the cache buffers without blocking; on CPU
            # skip it so dispatch stays async and the overlap is real.
            donate = () if jax.default_backend() == "cpu" else (5, 7, 8)
            self._decode_block_progs[want_lp] = jax.jit(
                block, donate_argnums=donate
            )
        return self._decode_block_progs[want_lp]

    def _fold_history(self, req: _Request):
        """Legacy discard-preemption bookkeeping: fold the emitted tokens
        into the prompt so resume re-prefills them (the recompute strategy —
        the KV is gone). Clears any stale migration state; counts the
        re-prefill work for the spill-vs-discard bench story."""
        req.spilled = False
        req._block = None
        if req.history:
            with self._admission_lock:
                self.reprefill_tokens += req.prompt.size + len(req.history)
            req.prompt = np.concatenate(
                [req.prompt, np.asarray(req.history, np.int32)]
            )
            req.history = []
            req._pkeys = None  # prompt changed: content keys are stale
            req._sdigests = None  # and so are the store digests
        req._splan = None  # any admission plan predates the fold

    def _spill_block(self, req: _Request) -> bool:
        """Export ``req``'s KV page chain into the spill tier. Device-side
        this only DISPATCHES a page gather (the jitted export program); the
        blocking device→host copy happens on the tier's flusher thread, so
        the tick never stalls on the transfer (MST106). Returns False —
        caller falls back to discard — on any failure: tier disabled, over
        budget, accounting drift, or an injected ``cache.export`` fault."""
        if self.spill is None or not req.history:
            return False
        slot = req.slot
        page = self.engine.page_size
        # valid KV rows: the last emitted token's KV is unwritten (its id
        # is last_tok / history[-1], fed as the next decode input)
        n_tokens = req.prompt.size + max(0, len(req.history) - 1)
        n_pages = -(-max(1, n_tokens) // page)
        pages = self._pages_of.get(slot, [])[:n_pages]
        ok = False
        if len(pages) == n_pages:
            try:
                block = export_block(
                    self.cache, pages, page_size=page, n_tokens=n_tokens,
                    prompt=req.prompt, history=req.history,
                    produced=req.produced, resume_keys=req.resume_keys,
                    resume_recent=req.resume_recent,
                    share_hash=self._share_hash, codec=self._kv_codec,
                    gather=self._export_pages, put=self._put,
                )
                ok = self.spill.put(req, block)
            except Exception as e:
                logging.getLogger(__name__).debug(
                    "KV spill export failed for slot %d: %s", slot, e
                )
        req.spilled = ok
        with self._admission_lock:
            if ok:
                self.spills += 1
            else:
                self.spill_fallbacks += 1
        return ok

    def _suspend_slot(self, req: _Request):
        """Vacate ``req``'s slot, preserving everything a token-exact
        resume needs. Mid-decode, its page chain is exported to the spill
        tier when one is configured (resume re-imports it — one page
        scatter instead of a re-prefill); otherwise, or on export failure,
        its emitted tokens fold into its prompt and resume re-prefills
        them. Either way the device-side sampler state is stashed so the
        next sampled token continues the exact PRNG/repetition chain.
        Mid-prefill there is nothing to stash; the prefill restarts.
        Shared by overcommit preemption and cold-slot spill; the caller
        decides where the request goes (waiting line vs parked list)."""
        slot = req.slot
        tr = req._trace
        t0 = time.perf_counter() if tr is not None else 0.0
        if self._prefill_done(req):
            # one transfer for both sampler rows; runs only quiesced (no
            # in-flight block) in async mode, so this sync is off the
            # steady-state decode path
            keys_h, recent_h = jax.device_get((self.keys, self.recent))
            req.resume_keys = np.asarray(keys_h[slot])
            req.resume_recent = np.asarray(recent_h[slot])
            with tracing.bind(tr):  # kv_transfer export self-instruments
                if not self._spill_block(req):
                    self._fold_history(req)
        req._chain = None
        req._splan = None
        req._last_logits = None
        req.prefill_pos = 0
        req.draft_pos = 0
        self.active = self._row_set(
            self.active, self._put(jnp.asarray(slot, jnp.int32)),
            self._put(jnp.asarray(False)),
        )
        self._release_pages(slot)
        # suspend runs quiesced, so a last-release demotion's export
        # dispatch is safe here; re-admission re-plans against the store
        self._drop_prefix_lease(req)
        self._slots[slot] = None
        note_release("scheduler.slot", (id(self), slot))
        req.slot = -1
        if tr is not None:
            tr.add("spill", t0, time.perf_counter(), slot=slot,
                   spilled=req.spilled)

    def _preempt(self, req: _Request):
        """Evict an admitted request back to the head of the waiting line,
        releasing its pages (over-commit pool exhaustion)."""
        with self._admission_lock:
            self.preemptions += 1
        tr = req._trace
        if tr is not None:
            tr.point("preempt", slot=req.slot)
        self._suspend_slot(req)
        # back on the line: the queue-wait clock restarts for re-admission
        req._t_submit = time.perf_counter()
        # head of the waiting line: preemption goes newest-first, so
        # repeated inserts at 0 restore admission order among the victims
        self._waiting.insert(0, req)

    # -------------------------------------------- proactive KV residency
    def _cold_candidates(self) -> list:
        """Recency scan: admitted decode slots whose consumer stopped
        pulling. ``produced - out.qsize()`` is the consumed-token count; a
        slot with a standing backlog whose count has not moved for
        ``spill_cold_after`` consecutive scans is cold — the engine is
        decoding tokens nobody reads, holding pool pages hotter streams
        (or the waiting line) could use. Cheap host-only bookkeeping; runs
        every tick from the (non-hot) policy helpers."""
        if self.spill_cold_after is None or self.spill is None:
            return []
        cold = []
        for req in self._slots:
            if req is None or req.cancelled or req.prefill_only:
                continue
            if not self._prefill_done(req) or not req.history:
                continue  # mid-prefill slots have nothing to spill
            backlog = req.out.qsize()
            consumed = req.produced - backlog
            if backlog > 0 and consumed == req._consumed_seen:
                req._cold_ticks += 1
            else:
                req._cold_ticks = 0
            req._consumed_seen = consumed
            if req._cold_ticks >= self.spill_cold_after:
                cold.append(req)
        return cold

    def _spill_cold(self, cold: list):
        """Suspend cold slots and park them off the waiting line. Parked
        requests hold no pool pages and don't count against admission —
        their spilled bytes are reclaimed capacity until the consumer
        catches up and :meth:`_wake_parked` re-queues them. Callers on the
        async path quiesce first: suspension device_gets sampler rows and
        rewrites page tables, which must not race an in-flight block."""
        for req in cold:
            if req.slot < 0:
                # the async caller's quiesce drains the in-flight block
                # AFTER the candidate scan, and that harvest can finish a
                # cold slot (max_tokens landed). Suspending it then would
                # release slot -1 — i.e. clobber self._slots[-1], dropping
                # whichever live stream holds the last slot — and park a
                # finished request for _wake_parked to re-admit.
                continue
            with self._admission_lock:
                self.cold_spills += 1
            tr = req._trace
            if tr is not None:
                tr.point("cold_spill", slot=req.slot)
            self._suspend_slot(req)
            req._cold_ticks = 0
            self._parked.append(req)

    def _wake_parked(self):
        """Re-queue parked requests whose consumer caught up (backlog
        drained). Woken requests go to the HEAD of the waiting line — their
        TTFT is long past, making them the oldest claim on capacity — and,
        with prefetch on, their host→device stage is dispatched here so
        the copy overlaps the decode blocks that run while they wait for a
        slot. Cancelled parked requests are reaped in place."""
        if not self._parked:
            return
        keep, woken = [], []
        for req in self._parked:
            if req.cancelled:
                self._drop_spill(req)
                req.out.put(None)
                continue
            if req.out.qsize() == 0:
                woken.append(req)
            else:
                keep.append(req)
        self._parked = keep
        if not woken:
            return
        now = time.perf_counter()
        for req in woken:
            req._cold_ticks = 0
            # re-queued at the head: the queue-wait clock restarts, and a
            # traced timeline gets its wake point
            req._t_submit = now
            tr = req._trace
            if tr is not None:
                tr.point("wake")
            self._prefetch_block(req)
            with self._admission_lock:
                self.cold_wakes += 1
        self._waiting[:0] = woken

    def _prefetch_block(self, req: _Request):
        """Dispatch the host→device stage for ``req``'s spilled block (the
        PRESERVE-style overlap): ``KVPageBlock.prefetch`` device_puts the
        page arrays without blocking on them, so by the time admission
        imports the block the scatter consumes device-resident pages. A
        still-device block (flusher hasn't copied it out) needs no stage.
        Faults on ``cache.prefetch`` are absorbed here — the block stays
        host-resident and import falls back to the counted demand path."""
        if not self._prefetch_on or self.spill is None or not req.spilled:
            return
        block = self.spill.peek(req)
        if block is None:
            return
        self.spill.touch(req)  # about to re-import: don't LRU-evict it
        if not block.is_host or block.is_prefetched:
            return
        try:
            tr = req._trace
            t0 = time.perf_counter() if tr is not None else 0.0
            block.prefetch(put=self._put, codec=self._kv_codec)
            if tr is not None:
                tr.add("prefetch", t0, time.perf_counter(),
                       pages=block.n_pages)
            with self._admission_lock:
                self.prefetches += 1
        except Exception as e:
            with self._admission_lock:
                self.prefetch_faults += 1
            logging.getLogger(__name__).debug(
                "KV prefetch failed (degrading to demand import): %s", e
            )

    def _prefetch_waiting(self):
        """Stage blocks for spilled requests near the head of the waiting
        line (preemption victims about to be re-admitted), bounded so a
        deep queue can't turn the policy pass into a copy storm. The
        prefix-store pass rides the same policy slot: host-tier prefix
        blocks for soon-to-be-admitted prompts get their stage started
        here so admission's import scatters device-resident arrays."""
        if self._prefetch_on and self.spill is not None:
            budget = 2
            for req in self._waiting[:4]:
                if budget == 0:
                    break
                if req.spilled and not req.cancelled:
                    self._prefetch_block(req)
                    budget -= 1
        self._pod_fetch_waiting()
        self._prefetch_store_waiting()

    def migrate_out(self, deadline: float = 30.0) -> int:
        """Gracefully evacuate every request (replica drain): the scheduler
        thread quiesces at its next tick and ends each stream with a
        ``RequestMigratedError`` carrying a :class:`ResumeState` — a
        host-materialized ``KVPageBlock`` when the page export succeeds,
        otherwise prompt+history for a token-exact re-prefill elsewhere.
        New submissions are rejected with ``ReplicaDrainingError`` from the
        moment this is called; the flag is permanent (retirement), so the
        caller should ``close()`` afterwards. Returns the number of
        requests migrated before ``deadline`` expired; stragglers (e.g. a
        wedged tick) keep migrating if the thread ever revives."""
        with self._admission_lock:
            base = self.migrations_out
        with self._start_lock:
            self._migrate_requested = True
            t = self._thread
        if t is None or not t.is_alive():
            # never started (no requests yet) or already stopped: nothing
            # admitted to migrate; the flag alone retires the batcher
            return 0
        # mst: allow(MST201): wake sentinel; Queue locks internally
        self._submit.put(None)  # wake the idle wait
        t0 = self._clock()
        while self._clock() - t0 < deadline:
            if not t.is_alive():
                break
            with self._admission_lock:
                queued = self._submit.qsize() + len(self._waiting)
            if queued == 0 and not any(r is not None for r in self._slots):
                break
            self._sleep(0.01)
        with self._admission_lock:
            return self.migrations_out - base

    def _migrate_all_out(self):
        """Scheduler-thread half of :meth:`migrate_out`. Runs quiesced (no
        in-flight block), so the one sampler-state ``device_get`` and the
        per-slot block exports are off the steady-state decode path — this
        is a teardown, not a tick, which is why the host copies here are
        synchronous rather than routed through the spill tier's flusher."""
        admitted = [
            (slot, req) for slot, req in enumerate(self._slots)
            if req is not None
        ]
        keys_h = recent_h = None
        if any(self._prefill_done(r) for _, r in admitted):
            # one transfer for every slot's sampler rows (PRNG chain +
            # repetition window) — what makes the resumed stream exact
            keys_h, recent_h = jax.device_get((self.keys, self.recent))
        for slot, req in admitted:
            self._slots[slot] = None
            note_release("scheduler.slot", (id(self), slot))
            req.slot = -1
            if req.cancelled:
                self._release_pages(slot)
                self._drop_prefix_lease(req)
                self._drop_spill(req)
                req.out.put(None)
                continue
            tr = req._trace
            t0 = time.perf_counter() if tr is not None else 0.0
            with tracing.bind(tr):
                state = self._export_resume_state(req, slot, keys_h, recent_h)
            if tr is not None:
                tr.add("migration", t0, time.perf_counter(), slot=slot,
                       block=state.block is not None)
            self._release_pages(slot)
            self._drop_prefix_lease(req)
            req.out.put(RequestMigratedError(state))
            with self._admission_lock:
                self.migrations_out += 1
        if admitted:
            self.active = self._zeros_like(self.active)
        self._drain_submissions()
        # parked cold-spilled sessions migrate too: their tier blocks (or
        # fold-history fallback) travel in the ResumeState like any
        # spill-preempted waiter's
        for req in self._waiting + self._parked:
            if req.cancelled:
                self._drop_spill(req)
                req.out.put(None)
                continue
            tr = req._trace
            t0 = time.perf_counter() if tr is not None else 0.0
            with tracing.bind(tr):
                state = self._export_resume_state(req, -1, None, None)
            if tr is not None:
                tr.add("migration", t0, time.perf_counter(), queued=True)
            req.out.put(RequestMigratedError(state))
            with self._admission_lock:
                self.migrations_out += 1
        self._waiting.clear()
        self._parked.clear()

    def _export_resume_state(self, req: _Request, slot: int,
                             keys_h, recent_h, *,
                             host: bool = True) -> ResumeState:
        """Build a request's portable :class:`ResumeState`. Admitted
        mid-decode requests get their page chain exported and host-
        materialized; a waiting request that was spill-preempted hands over
        its tier block. Any export failure (injected ``cache.export``
        fault, accounting drift, integrity error) degrades to a blockless
        state — the target folds history into the prompt and re-prefills,
        token-exact because the sampler rows still travel."""
        if slot >= 0 and self._prefill_done(req) and keys_h is not None:
            req.resume_keys = np.asarray(keys_h[slot])
            req.resume_recent = np.asarray(recent_h[slot])
        block = req._block  # un-imported block from a previous migration
        req._block = None
        if block is None and req.spilled:
            req.spilled = False
            block = self.spill.take(req) if self.spill is not None else None
        if (block is None and slot >= 0 and self.paged
                and self.draft is None and self._prefill_done(req)
                and req.history):
            page = self.engine.page_size
            n_tokens = req.prompt.size + max(0, len(req.history) - 1)
            n_pages = -(-max(1, n_tokens) // page)
            pages = self._pages_of.get(slot, [])[:n_pages]
            if len(pages) == n_pages:
                try:
                    block = export_block(
                        self.cache, pages, page_size=page, n_tokens=n_tokens,
                        prompt=req.prompt, history=req.history,
                        produced=req.produced, resume_keys=req.resume_keys,
                        resume_recent=req.resume_recent,
                        share_hash=self._share_hash, codec=self._kv_codec,
                        gather=self._export_pages, put=self._put,
                    )
                except Exception as e:
                    block = None
                    with self._admission_lock:
                        self.spill_fallbacks += 1
                    logging.getLogger(__name__).debug(
                        "drain export failed for slot %d: %s", slot, e
                    )
        if block is not None and host:
            try:
                # staged prefetch copies pin THIS engine's device buffers;
                # a block leaving the replica must not carry them
                block.drop_prefetch()
                block.to_host()  # the block must outlive this engine
            except Exception as e:
                block = None
                with self._admission_lock:
                    self.spill_fallbacks += 1
                logging.getLogger(__name__).debug(
                    "drain host copy failed for slot %d: %s", slot, e
                )
        return ResumeState(
            prompt=np.asarray(req.prompt, np.int32),
            history=[int(t) for t in req.history],
            produced=req.produced,
            block=block,
            resume_keys=req.resume_keys,
            resume_recent=req.resume_recent,
        )

    def _drop_spill(self, req: _Request):
        req.spilled = False
        if self.spill is not None:
            self.spill.drop(req)

    def _handoff_out(self):
        """Finish this tick's prefill-only requests: export each parked
        request's page block (dispatch-only gather) and end its stream with
        :class:`HandoffReadyError` carrying the ResumeState. Runs from the
        tick right after the prefill section — the pipeline is still
        quiesced from admission, so the one sampler-row ``device_get`` here
        is off the steady-state decode path, and the slot is released
        before the tick's decode dispatch so a handoff request never rides
        a decode block. The block is deliberately NOT host-materialized
        here (``host=False``): the consumer thread — the disagg
        coordinator's handoff step — pulls it with ``to_host()``, so the
        device→host DMA drains while this replica's next prefills and
        decode ticks proceed."""
        ready, self._handoff_ready = self._handoff_ready, []
        live = [r for r in ready if r.slot >= 0]
        keys_h = recent_h = None
        if any(not r.cancelled for r in live):
            # one transfer for every parked request's sampler rows (PRNG
            # chain + repetition window) — what keeps the resumed decode
            # stream token-exact on the target replica
            keys_h, recent_h = jax.device_get((self.keys, self.recent))
        for req in live:
            slot = req.slot
            if req.cancelled:
                self._finish(req)
                continue
            tr = req._trace
            t0 = time.perf_counter() if tr is not None else 0.0
            with tracing.bind(tr):
                state = self._export_resume_state(
                    req, slot, keys_h, recent_h, host=False
                )
            if tr is not None:
                # phase 1 of the disagg handoff (export dispatch on the
                # prefill replica); the coordinator records transfer/import
                tr.add("handoff_export", t0, time.perf_counter(), slot=slot)
            self.active = self._row_set(
                self.active, self._put(jnp.asarray(slot, jnp.int32)),
                self._put(jnp.asarray(False)),
            )
            self._release_pages(slot)
            # a prefill-only request's insertion lease drops HERE: last
            # release demotes the freshly prefilled prefix to the host
            # tier, which is exactly what lets the disagg coordinator skip
            # the prefill pool next time this prefix arrives
            self._drop_prefix_lease(req)
            self._slots[slot] = None
            note_release("scheduler.slot", (id(self), slot))
            req.slot = -1
            req.out.put(HandoffReadyError(state))
            with self._admission_lock:
                self.handoffs_out += 1
                self._finish_times.append(self._clock())

    def _grow_for_decode(self):
        """Over-commit page growth: before a decode block runs, every
        decoding slot must have pages covering the block's KV writes. Grow
        oldest-first from the free list (evicting cached prefix pages as
        needed); on pool exhaustion preempt the newest-admitted request.
        The oldest admitted request is never preempted, and generate_step's
        absolute capacity check proves a lone request's full need fits the
        pool, so it can always grow to completion — progress is guaranteed."""
        page = self.engine.page_size
        K = self._grow_ahead
        decoding = sorted(
            (
                (slot, req)
                for slot, req in enumerate(self._slots)
                if req is not None and self._prefill_done(req)
            ),
            key=lambda t: t[1].admit_seq,
        )
        for slot, req in decoding:
            while self._slots[slot] is req:  # a victim skips its own growth
                have = len(self._pages_of.get(slot, ()))
                emitted = len(req.history)
                # next KV write lands at prompt + emitted - 1 (the first
                # sampled token writes no KV; each block step writes one)
                offset = req.prompt.size + max(0, emitted - 1)
                # total pages this request can ever touch — same quantity
                # generate_step bounded by the pool size at submission
                cap = self._pages_needed(
                    req.prompt.size, emitted + (req.max_tokens - req.produced)
                )
                want = min(-(-(offset + K) // page), cap)
                n_more = want - have
                if n_more <= 0:
                    break
                self._evict_for(n_more)
                if len(self._free_pages) >= n_more:
                    fresh = [self._free_pages.pop() for _ in range(n_more)]
                    _note_pages(self, fresh, acquired=True)
                    for p in fresh:
                        self._page_ref[p] = 1
                    pages = self._pages_of[slot]
                    pages.extend(fresh)
                    self._write_table_row(slot, pages)
                    break
                victims = [r for r in self._slots if r is not None]
                if len(victims) <= 1:
                    # Only this request is left and the pool STILL can't
                    # cover its next block. cap ≤ pool (generate_step's
                    # capacity check) makes this unreachable absent
                    # accounting drift — but silently continuing would
                    # wedge the request against its scratch-page tail and
                    # emit garbage forever. Fail it loudly instead.
                    req.out.put(RuntimeError(
                        f"KV page pool exhausted: slot {slot} needs "
                        f"{n_more} more page(s) for its next decode block "
                        f"but only {len(self._free_pages)} are free and no "
                        "other request remains to preempt"
                    ))
                    self._finish(req)
                    break
                self._preempt(max(victims, key=lambda r: r.admit_seq))

    def _dispatch_block(self) -> Optional[_InflightBlock]:
        """Dispatch one decode block on the device and return its handle
        WITHOUT waiting for it: pure device-side state chain (last_tok /
        cache / recent / keys rebind to output futures), no host reads.
        The paired :meth:`_harvest` pulls the tokens; the async tick runs
        them a block apart so the device never waits on host work."""
        eng = self.engine
        if self.paged and self.overcommit:
            self._grow_for_decode()
        # snapshot of slots active for this block, in slot order
        live = [
            (slot, req) for slot, req in enumerate(self._slots)
            if req is not None and self._prefill_done(req)
        ]
        if not live:
            return None
        want_lp = any(req.want_logprobs for _, req in live)
        # analytic gauge; in async mode the lengths are one block stale
        self._account_kv_read(live, self.decode_block)
        # the block's first input token, kept so a draft engine can replay
        # the exact chain the target consumed (sync/spec fallback only)
        prev_tok = self.last_tok if self.draft is not None else None
        block = self._decode_block_prog(want_lp)
        if self._trace_profile:
            # --trace-profile: annotate the dispatched block so the host
            # span lines up with the XLA timeline in a profiler capture
            with tracing.profile_span("mst.decode_block"):
                outs, self.last_tok, self.cache, self.recent, self.keys = block(
                    eng.layer_params, eng.layer_masks, eng.vocab_parts,
                    eng.shared_params, self.last_tok, self.cache, self.active,
                    self.recent, self.keys, self.sp, self.rep_sizes, self.table,
                )
        else:
            outs, self.last_tok, self.cache, self.recent, self.keys = block(
                eng.layer_params, eng.layer_masks, eng.vocab_parts,
                eng.shared_params, self.last_tok, self.cache, self.active,
                self.recent, self.keys, self.sp, self.rep_sizes, self.table,
            )
        return _InflightBlock(outs=outs, live=live, want_lp=want_lp,
                              prev_tok=prev_tok)

    def _harvest(self, inf: Optional[_InflightBlock]):
        """Pull a dispatched block's tokens to the host and run all of its
        host-side consequences: emit per slot (lookahead tokens of a slot
        that finished after dispatch are dropped by the ``req.slot != slot``
        skip), draft replay, finish/reclaim. The ONE ``device_get`` here is
        the tick sync — the async loop must never grow a second harvest
        point (MST104)."""
        if inf is None:
            return
        inject("scheduler.harvest")  # fault harness: kill the harvest
        t0 = time.perf_counter()
        # mst: allow(MST102): THE tick sync — tokens must reach the host
        outs, prev = jax.device_get((inf.outs, inf.prev_tok))
        blocked = time.perf_counter() - t0
        self.tick_device_blocked_ms_last = blocked * 1000.0
        self._tick_blocked_s_total += blocked
        self._tick_count += 1
        toks = outs[0]  # (K, M, 1)
        live = inf.live
        # per-tick spans for traced requests, reusing the tick-timing
        # stamps above (t0/blocked) — no extra clock reads on this path
        for _, _req in live:
            _tr = _req._trace
            if _tr is not None:
                _tr.add("decode_tick", t0, t0 + blocked, slot=_req.slot,
                        block=self.decode_block)
        if self.draft is not None and live:
            # This tick fell back to plain decode (spec paused — logprobs
            # wanted, or a slot within K of max_seq): the target just
            # advanced decode_block positions, so the draft must ingest the
            # same token chain or its next proposals attend to stale KV and
            # acceptance silently collapses. Step j of the block consumed
            # toks[j-1] (step 0 consumed prev_tok), so the replay chain is
            # [prev_tok, toks[:-1]]. Deterministic device ops only — every
            # multi-host mirror computes the identical replay in lockstep.
            chain = np.concatenate([prev[None], toks[:-1]], 0)
            self.dcache = self.draft.spec_replay_cb(self.decode_block)(
                self.draft.layer_params, self.draft.layer_masks,
                self.draft.vocab_parts, self.draft.shared_params,
                self._put(jnp.asarray(chain)), self.dcache, self.active,
            )
            self.fallback_ticks += 1
            self.replayed_tokens += self.decode_block * len(live)
        for j in range(toks.shape[0]):
            for slot, req in live:
                if req.slot != slot:  # finished (max_tokens) earlier in block
                    continue
                lp = None
                if inf.want_lp and req.want_logprobs:
                    lp = block_token_logprobs(outs, j, slot)
                self._emit(req, int(toks[j, slot, 0]), lp)

    def _decode_once(self):
        # the sync composition point — MultiHostBatcher overrides THIS to
        # broadcast the tick before the mirrored dispatch+harvest
        self._harvest(self._dispatch_block())

    def _need_pages(self, req: _Request, block=None) -> int:
        """Pages to map at admission. Reserve mode (default) claims the whole
        prompt+max_tokens need up front; over-commit claims only the CURRENT
        need — prompt plus one decode block (capped by what's left to emit) —
        and grows per block in _grow_for_decode. A request resuming via a
        KVPageBlock (``block``, or its entry still parked in the spill tier)
        sizes from the block's KV rows instead of the prompt: at least the
        block's own pages, plus decode headroom in the same mode."""
        remaining = max(1, req.max_tokens - req.produced)
        if req.prefill_only:
            # a prefill-only request emits exactly one token on this
            # replica before its block hands off to the decode pool —
            # reserving its full decode budget here would starve the
            # prefill pool's admission for capacity it never uses
            remaining = 1
        if block is None:
            block = req._block
        if block is None and req.spilled and self.spill is not None:
            block = self.spill.peek(req)
        if block is not None:
            ahead = min(self._grow_ahead, remaining) if self.overcommit \
                else remaining
            return max(
                block.n_pages,
                -(-(block.n_tokens + ahead) // self.engine.page_size),
            )
        if self.overcommit:
            return self._pages_needed(
                req.prompt.size, min(self._grow_ahead, remaining)
            )
        return self._pages_needed(req.prompt.size, remaining)

    def _spec_ok(self) -> bool:
        """A tick can take the speculative round iff no decoding slot wants
        logprob summaries (the verify doesn't compute them) and every
        decoding slot has window-max rows of KV headroom — the verify
        writes up to that many positions speculatively, and past max_seq
        the dynamic-slice clamp would corrupt valid rows. Async ngram ticks
        double the margin: at dispatch of round t+1 the host has harvested
        only through t-1, so the true frontier can be a full round ahead of
        ``history``. Ticks that fail the check run a plain decode block
        (all slots still advance, just unspeculated)."""
        if self._pressure >= 2 and self.spec_tracker is None:
            # legacy fixed-K engine mode: brownout level 2+ pauses
            # speculation globally — draft compute is ballast under
            # overload (racy gauge-grade read; the fallback tick path
            # handles the draft-KV replay). With a tracker the shed is
            # per-slot, lowest-acceptance-first (effective_windows).
            return False
        K = (2 if self._async else 1) * self._w_max
        ms = self.engine.max_seq
        for req in self._slots:
            if req is None or not self._prefill_done(req):
                continue
            if req.want_logprobs:
                return False
            # history counts tokens since the last prompt fold, so
            # prompt + history is the slot's true KV frontier even for a
            # resumed request whose ``produced`` spans an earlier replica
            since = len(req.history)
            if req.prompt.size + max(0, since - 1) + K > ms:
                return False
        return True

    def _spec_draft_ok(self) -> bool:
        """``spec.draft`` fault site, checked before each speculative
        round's proposals: a faulted draft degrades THAT tick to plain
        decode — counted, never a wrong or dropped stream (the fallback
        path replays the block through a draft engine's KV as usual)."""
        try:
            inject("spec.draft", engine=id(self))
        except Exception:
            self.spec_draft_faults += 1
            return False
        return True

    def _spec_plan(self, live):
        """Per-round window plan: ``(K, wins)`` where K is the round width
        (max live window) and wins maps slot → policy window, or None when
        no live slot speculates this round (the tick runs plain decode).
        Without a tracker (legacy fixed-K engine mode) every slot gets
        spec_k. With one, windows come from the per-slot controller after
        brownout shedding (level 2 sheds lowest-acceptance-first, level 3+
        sheds all — see AcceptanceTracker.effective_windows)."""
        if self.spec_tracker is None:
            return self.spec_k, {slot: self.spec_k for slot, _ in live}
        wins = self.spec_tracker.effective_windows(
            [slot for slot, _ in live], self._pressure
        )
        K = max(wins.values(), default=0)
        if K < 2:
            return None
        return K, wins

    def _dispatch_spec(self, prev_guess=None) -> Optional[_InflightSpec]:
        """Dispatch one speculative round for every decoding slot and
        return its handle WITHOUT waiting: proposals (host-built n-gram
        lookups, or K batched draft-engine steps), one T=K target verify
        with per-slot window caps, all device outputs left as futures.
        Slots whose window is 0 (disabled/shed) ride along with wcap=1 —
        they emit exactly the correction token, i.e. a plain decode step.
        ``prev_guess`` is the in-flight round's optimistic continuation per
        slot (async: host history is one round stale at dispatch). Returns
        None when no slot speculates — the caller runs a plain tick."""
        eng = self.engine
        if self.paged and self.overcommit:
            self._grow_for_decode()
        live = [
            (slot, req) for slot, req in enumerate(self._slots)
            if req is not None and self._prefill_done(req)
        ]
        if not live:
            return None
        plan = self._spec_plan(live)
        if plan is None:
            return None
        K, wins = plan
        # the T=K verify always takes the gather path (chunked writes want
        # the contiguous buffer), whatever the decode tick uses
        self._account_kv_read(live, 1, path="gather")
        wcaps = np.ones((self.M,), np.int32)
        guess: dict = {}
        if self._spec_mode == "ngram":
            prev_guess = prev_guess or {}
            drafts_np = np.zeros((K, self.M), np.int32)
            for slot, req in live:
                w = wins.get(slot, 0)
                if w < 2:
                    continue
                toks = np.concatenate(
                    [req.prompt, np.asarray(req.history, np.int32)]
                )
                tail = prev_guess.get(slot)
                if tail is not None and tail.size:
                    toks = np.concatenate([toks, tail])
                d, n_valid = self._ngram.propose(toks, w)
                drafts_np[:w, slot] = d
                wcaps[slot] = min(w, max(1, n_valid))
                guess[slot] = d[: wcaps[slot]]
            keys2 = self._split2(self.keys)
            self.keys, vkeys = keys2[:, 0], keys2[:, 1]
            drafts = self._put(jnp.asarray(drafts_np))
            gs, count, self.last_tok, self.cache, self.recent = \
                eng.spec_verify_ngram_cb(K)(
                    eng.layer_params, eng.layer_masks, eng.vocab_parts,
                    eng.shared_params, self.last_tok, drafts, self.cache,
                    self.active, self.recent, vkeys, self.sp,
                    self.rep_sizes, self._put(jnp.asarray(wcaps)),
                    self.table,
                )
        else:
            d = self.draft
            for slot, _req in live:
                wcaps[slot] = max(1, wins.get(slot, 0))
            keys3 = self._split3(self.keys)
            self.keys, dkeys, vkeys = keys3[:, 0], keys3[:, 1], keys3[:, 2]
            drafts, qlps, self.dcache = d.spec_propose_cb(K)(
                d.layer_params, d.layer_masks, d.vocab_parts, d.shared_params,
                self.last_tok, self.dcache, self.active, self.recent, dkeys,
                self.sp, self.rep_sizes,
            )
            gs, count, self.last_tok, self.cache, self.recent = \
                eng.spec_verify_cb(K)(
                    eng.layer_params, eng.layer_masks, eng.vocab_parts,
                    eng.shared_params, self.last_tok, drafts, qlps,
                    self.cache, self.active, self.recent, vkeys, self.sp,
                    self.rep_sizes, self._put(jnp.asarray(wcaps)),
                    self.table,
                )
            self.dcache = self.dcache._replace(
                offset=self._drewind(
                    self.dcache.offset, count, self.active,
                    jnp.asarray(K, jnp.int32),
                )
            )
        return _InflightSpec(outs=(count, gs), live=live, wins=wins,
                             wcaps=wcaps, K=K, guess=guess)

    def _harvest_spec(self, inf: Optional[_InflightSpec]):
        """Pull a dispatched speculative round's (counts, tokens) to the
        host and run its host-side consequences: per-slot emit of the
        accepted prefix + correction token, acceptance accounting, and the
        tracker update that resizes each slot's next window. The ONE
        ``device_get`` here is the round's tick sync (MST104's single
        harvest point, spec flavor)."""
        if inf is None:
            return
        t0 = time.perf_counter()
        # mst: allow(MST102): the spec round's one consolidated harvest
        counts, gs_h = jax.device_get(inf.outs)
        blocked = time.perf_counter() - t0
        self.tick_device_blocked_ms_last = blocked * 1000.0
        self._tick_blocked_s_total += blocked
        self._tick_count += 1
        self.rounds += len(inf.live)
        for _, _req in inf.live:
            _tr = _req._trace
            if _tr is not None:
                _tr.add("spec_round", t0, t0 + blocked, slot=_req.slot,
                        window=inf.K)
        for slot, req in inf.live:
            emitted = 0
            for j in range(int(counts[slot])):
                if req.slot != slot:
                    break  # finished (max_tokens) earlier in this round
                self._emit(req, int(gs_h[j, slot]), None)
                emitted += 1
            w = inf.wins.get(slot, 0)
            if w >= 2:
                # count what actually reached the consumer: a slot that
                # hits max_tokens mid-round drops the rest of its accepted
                # prefix, and counting those would overstate the acceptance
                # rate. Disabled/shed slots ride along as plain decode
                # (wcap=1) — counting their correction token as "accepted"
                # with no draft spend would push accept_rate past 1.0.
                self.accepted_tokens += emitted
                self.draft_tokens += int(inf.wcaps[slot])
                if self.spec_tracker is not None and req.slot == slot:
                    # train on the verify's verdict (the full accepted
                    # count), not the max_tokens-truncated emission
                    self.spec_tracker.observe(slot, w, int(counts[slot]))

    def _spec_once(self):
        """One synchronous speculative round: dispatch + immediate harvest
        (the sync composition point, like _decode_once for plain ticks)."""
        self._harvest_spec(self._dispatch_spec())

    def _spec_tick(self) -> bool:
        """Try to make this sync tick a speculative round. False means the
        caller must run a plain decode block instead — speculation is off,
        gated (_spec_ok), fault-degraded (spec.draft), or the per-slot plan
        came up empty (every window 0/disabled)."""
        if self._spec_mode == "off":
            return False
        if not (self._spec_ok() and self._spec_draft_ok()):
            return False
        inf = self._dispatch_spec()
        if inf is None:
            return False
        self._harvest_spec(inf)
        return True

    def _harvest_any(self, inf):
        """Harvest whichever flavor of in-flight work ``inf`` is — the
        async tick's lookahead slot can hold a plain decode block or a
        speculative round (ngram mode) interchangeably."""
        if isinstance(inf, _InflightSpec):
            self._harvest_spec(inf)
        else:
            self._harvest(inf)

    def _fits(self, req: _Request) -> bool:
        if not self.paged:
            return True
        if req.spilled and (self.spill is None or not self.spill.contains(req)):
            # the tier evicted this block under budget pressure since the
            # preemption: resolve to the discard path NOW so the page math
            # below sizes the folded prompt, not a phantom block. (No race
            # with _take_block: evictions only happen on this thread's own
            # puts, never concurrently.)
            req.spilled = False
            self._fold_history(req)
            with self._admission_lock:
                self.spill_fallbacks += 1
        need = self._need_pages(req)
        if req._block is not None or req.spilled:
            if req.spilled:
                # in the resume path: LRU-refresh the tier entry so budget
                # pressure evicts a genuinely-cold block instead
                self.spill.touch(req)
            # block import allocates its whole need fresh (no page sharing
            # with the prefix index), so the chain doesn't discount it
            req._chain = None
            return need <= len(self._free_pages) + self._evictable_pages()
        if self.prefix_store is not None:
            # fleet-store LPM instead of the slot-local chain (mutually
            # exclusive by construction): a device hit discounts the
            # covered pages — the slot leases them instead of allocating.
            # A host hit discounts nothing (the import scatters into fresh
            # pages), it just records the plan for _assign_slot. Pure
            # probe: counters resolve once, at admission.
            if getattr(self.prefix_store, "federation", None) is not None \
                    and req._podfetch != "done":
                # pod federation attached: hold the request until the
                # waiting-queue pass has classified it (None) or its
                # in-flight fetch lands (pending) so the prefix isn't
                # redundantly prefilled — the fetch worker flips the flag
                # on every outcome, and a failed fetch just prefills
                # plain. Flag read only: the federation itself is never
                # touched here
                return False
            req._splan = None
            plan = self._store_lookup(req)
            discount = plan[1] if plan is not None and plan[0] == "device" else 0
            ok = need - discount <= len(self._free_pages) + self._evictable_pages()
            if ok and plan is not None:
                # only a fitting request carries its plan into _assign_slot
                # (same admission pass, same thread — no staleness window
                # beyond the store's own acquire re-check)
                req._splan = plan
            return ok
        chain = self._prefix_lookup(req)
        # the chain's own pages must not double as eviction fodder: they're
        # about to be mapped, so only OTHER cached pages can be reclaimed
        ok = need - len(chain) <= len(self._free_pages) + self._evictable_pages(
            exclude=[p for _, p in chain]
        )
        # only a fitting request hands its chain to _assign_slot (same
        # admission pass); a stale chain could reference since-evicted pages
        req._chain = chain if ok else None
        return ok

    def _admit_waiting(self):
        """Admit from the waiting line into free slots under the admission
        policy. fifo: strict order, a non-fitting head blocks the line.
        first_fit: scan past non-fitting requests (they keep their place)."""
        # Shed queued requests whose TTFT budget is already gone: prefilling
        # them would be wasted work (the consumer has timed out or is about
        # to). Host-local decision — nothing was broadcast for an unassigned
        # request, so worker mirrors never knew it existed.
        if self._waiting:
            now = self._clock()
            # produced == 0 guard: a woken cold-spilled request is back on
            # the line long after its first token was delivered — its TTFT
            # budget is history, not a shed signal; dropping it here would
            # kill a mid-stream session
            for req in [
                r for r in self._waiting
                if not r.cancelled and r.produced == 0
                and r.deadlines is not None
                and r.deadlines.ttft_deadline is not None
                and now > r.deadlines.ttft_deadline
            ]:
                self._waiting.remove(req)
                with self._admission_lock:  # read by resilience_stats()
                    self.shed_deadline += 1
                req.cancelled = True
                req.out.put(RequestTimeoutError(
                    "queue", now - req.deadlines.submitted_at,
                    req.deadlines.ttft_deadline - req.deadlines.submitted_at,
                ))
        # reap dead waiters first — under fifo a non-fitting head would
        # otherwise shadow a cancelled request behind it forever
        for req in [r for r in self._waiting if r.cancelled]:
            self._waiting.remove(req)
            self._drop_spill(req)  # its tier block frees with the stream
            req.out.put(None)
        while None in self._slots and self._waiting:
            pick = None
            for i, req in enumerate(self._waiting):
                if self._fits(req):
                    pick = i
                    break
                if self.policy == "fifo":
                    return  # head of line doesn't fit; hold the line
            if pick is None:
                return  # first_fit: nothing waiting fits right now
            self._assign_slot(self._waiting.pop(pick), self._slots.index(None))

    def _drain_submissions(self, block: bool = False):
        try:
            while True:
                req = self._submit.get(timeout=0.2) if block else self._submit.get_nowait()
                block = False
                if req is not None:
                    self._waiting.append(req)
        except queue.Empty:
            pass

    def _decoding(self) -> bool:
        """Host mirror of the device ``active`` mask: a slot is decoding iff
        it holds a request whose prefill completed. Exact by construction —
        ``active[slot]`` flips True only at prefill completion and False
        only in _finish/_preempt/_fail_all, each of which also clears
        ``_slots[slot]`` — so the branch gates on host state instead of a
        per-tick device round-trip."""
        return any(
            r is not None and self._prefill_done(r) for r in self._slots
        )

    def _quiesce(self):
        """Drain the pipeline: harvest the in-flight block (if any) so every
        host-visible consequence of it — emitted tokens, finishes, freed
        pages — has landed and the device is idle. Required before anything
        that reads device state or host token counts the lookahead block is
        still mutating: admission prefill, preemption, pool-pressure growth
        that might preempt, shutdown."""
        inf, self._inflight = self._inflight, None
        self._harvest_any(inf)

    def _growth_fits(self) -> bool:
        """True iff the next ``_grow_for_decode`` is guaranteed to cover
        every decoding slot's block from free + evictable pages alone, i.e.
        growth cannot preempt. Mirrors _grow_for_decode's want/cap math
        exactly; the aggregate bound is exact because evictions only free
        index-only pages (never counted in any slot's ``have``) and nothing
        else allocates between the check and the growth. The emitted/
        produced counts are one block stale in async mode — which the
        doubled ``_grow_ahead`` already covers — and ``cap`` is
        staleness-invariant (history and produced increment together)."""
        if not (self.paged and self.overcommit):
            return True
        page = self.engine.page_size
        K = self._grow_ahead
        need = 0
        for slot, req in enumerate(self._slots):
            if req is None or not self._prefill_done(req):
                continue
            have = len(self._pages_of.get(slot, ()))
            emitted = len(req.history)
            offset = req.prompt.size + max(0, emitted - 1)
            cap = self._pages_needed(
                req.prompt.size, emitted + (req.max_tokens - req.produced)
            )
            want = min(-(-(offset + K) // page), cap)
            need += max(0, want - have)
        return need <= len(self._free_pages) + self._evictable_pages()

    def _tick_async(self):
        """One double-buffered scheduler iteration: dispatch decode block
        t+1 BEFORE harvesting block t, so the harvest's device_get waits
        only on the already-finished block while the device computes ahead,
        and the host-side emit/stop/admission work below runs concurrently
        with it. Admission prefill, growth that could preempt, and the
        idle path quiesce the pipeline first (one-block drain), then the
        double-buffering resumes on the next tick."""
        inject("scheduler.tick", engine=id(self))  # fault harness: wedge/delay/fail a tick (match engine= to target one batcher)
        if self._migrate_requested:
            # drain: finish the in-flight block, then end every stream with
            # its ResumeState; the idle wait keeps the loop from spinning
            # while the dispatcher re-places the migrated requests
            self._quiesce()
            self._migrate_all_out()
            self._drain_submissions(block=True)
            return
        self._reap_cancelled()
        self._drain_submissions()
        cold = self._cold_candidates()
        if cold:
            # suspension device_gets sampler rows and rewrites page tables:
            # drain the lookahead block first
            self._quiesce()
            self._spill_cold(cold)
        self._wake_parked()
        self._prefetch_waiting()
        if (self._waiting and None in self._slots) or any(
            r is not None and not self._prefill_done(r) for r in self._slots
        ):
            # prefill (admission or mid-admission chunks) samples the first
            # token host-side and rewrites slot state: drain the lookahead
            # block before touching the engine
            self._quiesce()
        self._admit_waiting()
        prefilling = [
            r for r in self._slots
            if r is not None and not self._prefill_done(r)
        ]
        if prefilling:
            if self._decoding():
                self._prefill_rr += 1
                self._prefill_one_chunk(
                    prefilling[self._prefill_rr % len(prefilling)]
                )
            else:
                for req in prefilling:
                    self._prefill_one_chunk(req)
        if self._handoff_ready:
            # prefill-only completions: export + end those streams BEFORE
            # dispatch (pipeline still quiesced from the prefill above)
            self._handoff_out()
        if self._decoding():
            if self.paged and self.overcommit and not self._growth_fits():
                # growth might preempt (device_get of sampler rows + page
                # reshuffle): only safe against a drained pipeline
                self._quiesce()
            prev, self._inflight = self._inflight, None
            nxt = None
            if (
                self._spec_mode == "ngram"
                and self._spec_ok()
                and self._spec_draft_ok()
            ):
                # host history is one round stale here (prev not harvested
                # yet): extend it with prev's optimistic guess so the
                # n-gram match sees the tokens prev is about to emit. A
                # wrong guess only costs acceptance, never exactness.
                nxt = self._dispatch_spec(
                    prev.guess if isinstance(prev, _InflightSpec) else None
                )
            if nxt is None:
                nxt = self._dispatch_block()
            self._inflight = nxt
            self._harvest_any(prev)
        else:
            self._quiesce()  # leftover lookahead block of finished slots
            if not any(self._slots):
                # idle: block until the next request arrives (bounded wait,
                # so parked cold sessions still get their wake poll)
                self._drain_submissions(block=True)
                self._wake_parked()
                self._admit_waiting()

    def _tick(self):
        """One scheduler iteration: reap, admit waiting requests into free
        slots (policy + page-reservation gated), prefill mid-admission
        requests, one decode block for active slots.

        Prefill fairness: every prefill chunk stalls every decoding slot
        for its duration, so while anything is decoding, at most ONE chunk
        runs per tick (round-robin across admitting requests) — admission
        latency for long prompts trades against decode jitter bounded at
        one chunk per block. With nothing decoding, all admitting requests
        advance at full rate."""
        inject("scheduler.tick", engine=id(self))  # fault harness: wedge/delay/fail a tick (match engine= to target one batcher)
        if self._migrate_requested:
            self._quiesce()  # no-op in sync mode (nothing in flight)
            self._migrate_all_out()
            self._drain_submissions(block=True)
            return
        self._reap_cancelled()
        self._drain_submissions()
        cold = self._cold_candidates()
        if cold:
            self._spill_cold(cold)  # sync mode: nothing in flight to drain
        self._wake_parked()
        self._prefetch_waiting()
        self._admit_waiting()
        prefilling = [
            r for r in self._slots
            if r is not None and not self._prefill_done(r)
        ]
        decoding = self._decoding()
        if prefilling:
            if decoding:
                self._prefill_rr += 1
                self._prefill_one_chunk(
                    prefilling[self._prefill_rr % len(prefilling)]
                )
            else:
                for req in prefilling:
                    self._prefill_one_chunk(req)
        if self._handoff_ready:
            # prefill-only completions leave before the decode block
            self._handoff_out()
        if self._decoding():
            if not self._spec_tick():
                self._decode_once()
        elif not any(self._slots):
            # idle: block until the next request arrives (bounded wait,
            # so parked cold sessions still get their wake poll)
            self._drain_submissions(block=True)
            self._wake_parked()
            self._admit_waiting()

    def _fail_all(self, exc: BaseException):
        # a scheduler-thread failure is an incident: snapshot the flight
        # recorder before the streams die so their timelines survive
        tracing.auto_snapshot("scheduler_fail")
        # drop the lookahead block's futures (host-side); the wholesale
        # pool reset below reclaims whatever it was still writing
        self._inflight = None
        failed: list = []
        for slot, req in enumerate(self._slots):
            if req is not None:
                req.slot = -1
                self._slots[slot] = None
                note_release("scheduler.slot", (id(self), slot))
                failed.append(req)
                req.out.put(exc)
        self.active = self._zeros_like(self.active)
        if self.paged:
            # cache contents are unreliable after a failure: reset the pool
            # wholesale (all pages free, index dropped)
            self._pages_of.clear()
            self._page_ref.clear()
            self._prefix_index.clear()
            self._free_pages = list(range(self.engine.pool_pages - 1, -1, -1))
            oid = id(self)
            note_reset("scheduler.page", lambda k: k[0] == oid)
            if self.prefix_store is not None:
                # the fleet store's device entries for THIS engine point at
                # pages the wholesale reset just freed — drop them (marking
                # any outstanding leases dead so late releases are no-ops);
                # host-tier blocks are self-contained and stay valid
                self.prefix_store.drop_owner(self)
        for req in failed:
            # the drop above orphaned the dead slots' entries; retire their
            # leases through the normal idempotent path so the exactly-once
            # contract (and the leak ledger) sees every lease come back.
            # No demotion fires: a dropped entry's release returns None.
            self._drop_prefix_lease(req)
        if self.spill is not None:
            # spilled blocks reference requests whose streams just died;
            # host DRAM back to the budget
            self.spill.clear()
        for req in self._waiting:
            req.out.put(exc)
        self._waiting.clear()
        for req in self._parked:  # cold-spilled sessions die with the rest
            req.out.put(exc)
        self._parked.clear()
        while True:
            try:
                req = self._submit.get_nowait()
            except queue.Empty:
                break
            if req is not None:
                req.out.put(exc)

    def _loop(self):
        tick = self._tick_async if self._async else self._tick
        while not self._stop:
            try:
                t0 = time.perf_counter()
                b0 = self._tick_blocked_s_total
                c0 = self._tick_count
                tick()
                if self._tick_count > c0:
                    # only ticks that harvested a block carry the timing
                    # signal (idle waits would swamp the host-side average)
                    host = max(
                        0.0,
                        (time.perf_counter() - t0)
                        - (self._tick_blocked_s_total - b0),
                    )
                    self.tick_host_ms_last = host * 1000.0
                    self._tick_host_s_total += host
            except Exception as exc:  # noqa: BLE001 — a dead scheduler thread
                # would hang every consumer; surface the error to them instead
                self._fail_all(exc)
        # graceful shutdown: end every in-flight and queued request's stream.
        # Host-side only — no device ops here: the engine is being dropped,
        # and in multi-host serving a device op after the final broadcast
        # would be a one-rank collective entry (a hang, not a cleanup).
        self._inflight = None  # abandon the lookahead block's futures
        for slot, req in enumerate(self._slots):
            if req is not None:
                self._slots[slot] = None
                note_release("scheduler.slot", (id(self), slot))
                req.slot = -1
                # retire the slot's COW lease host-side: release WITHOUT
                # demotion (an export here would be a device op, and in
                # multi-host serving a one-rank collective entry). The
                # returned last-ref entry is dropped — close() is about to
                # drop_owner() the whole pool anyway.
                lease, req._please = req._please, None
                if lease is not None:
                    lease.release()
                req.out.put(None)
        for req in self._waiting:
            req.out.put(None)
        self._waiting.clear()
        for req in self._parked:  # parked streams end, like waiting ones
            req.out.put(None)
        self._parked.clear()
        while True:
            try:
                req = self._submit.get_nowait()
            except queue.Empty:
                break
            if req is not None:
                req.out.put(None)
