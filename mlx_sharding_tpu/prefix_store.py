"""Content-addressed prefix KV store: fleet-wide copy-on-write reuse.

At production traffic shapes — many sessions over a handful of system
prompts — the fleet should prefill each hot prefix ONCE. The engine-level
prompt cache (``--prompt-cache``) cannot grow into that: its index is
slot-local raw-byte page hashes inside one batcher, invisible to the
router, the other replicas, and the disagg coordinator. This module
composes the pieces the stack already has into the shared subsystem:

- **keying** — the chained chunk digests of ``utils.digests.chunk_digests``
  (the router's affinity scheme, extracted so router and store can never
  disagree): because digests are chained, the k-th digest alone
  content-addresses the entire k-page prefix, so lookup is
  longest-prefix-match over single dict probes, longest first.
- **device tier** — per-batcher entries mapping a digest chain to the pool
  pages holding its KV. Pages are shared copy-on-write across live slots:
  a hitting slot maps them read-only and starts decode/tail-prefill past
  them (writes land in its private pages — the same immutability argument
  as the engine prompt cache). Entries are refcounted WeightStore-style:
  one :class:`PrefixLease` per slot mapping the pages, plus the entry's
  own +1 on each page in the batcher's ``_page_ref`` accounting.
- **host tier** — a digest-keyed :class:`~mlx_sharding_tpu.kv_transfer.
  KVSpillTier` of host-materialized ``KVPageBlock``s. On LAST lease
  release the entry demotes: the batcher exports the pages (dispatch-only
  gather; the device→host copy runs on the tier's flusher) and the pool
  pages return to the free list — device residency exists only while some
  slot is live on the prefix. A later admission anywhere in the fleet
  re-imports the block (prefetch-staged when the scheduler sees it
  coming; demand import is the counted fallback) and re-registers the
  pages as a fresh device entry.

Insertion policy (one-shot prompts must not churn the store): a prefix is
registered only after ``insert_min_hits`` lookup MISSES of its full chain,
under a token bucket refilled per admission (``insert_burst``), and not at
all while the fleet brownout controller has paused inserts (serving hits
stays free — pausing reuse under pressure would be backwards).

Failure contract: fault site ``cache.prefix_lookup`` fires at the top of
every lookup/coverage probe; callers catch, count, and degrade to plain
prefill. An import failure re-prefills from token 0 into the pages the
slot already holds. Neither path can drop or corrupt a stream — greedy
token streams are bit-identical with the store on or off.
"""

from __future__ import annotations

import logging
from collections import OrderedDict
from typing import Optional

import numpy as np

from mlx_sharding_tpu import tracing
from mlx_sharding_tpu.analysis.runtime import make_lock, note_acquire, note_release
from mlx_sharding_tpu.kv_transfer import KVPageBlock, KVSpillTier
from mlx_sharding_tpu.testing.faults import inject
from mlx_sharding_tpu.utils.digests import chunk_digests

logger = logging.getLogger(__name__)


class _DeviceEntry:
    """One registered prefix resident in one batcher's page pool."""

    __slots__ = ("owner", "digests", "pages", "tokens", "nbytes", "refs",
                 "hits", "keys", "dropped")

    def __init__(self, owner, digests, pages, tokens, nbytes):
        self.owner = owner            # the batcher whose pool holds the pages
        self.digests = list(digests)  # full chain; digests[-1] is the host key
        self.pages = list(pages)      # pool page ids, chain order
        self.tokens = np.asarray(tokens, np.int32)  # the prefix ids (export)
        self.nbytes = int(nbytes)
        self.refs = 0                 # live leases (slots mapping the pages)
        self.hits = 0
        self.keys = []                # index keys THIS entry owns
        self.dropped = False          # drop_owner() ran; leases are orphans


class PrefixLease:
    """One slot's claim on a device entry's shared pages. Release is
    exactly-once (double release raises — the WeightStore discipline);
    the LAST release returns the entry to the caller for demotion."""

    __slots__ = ("_store", "_entry", "cover", "pages", "n_tokens", "_released")

    def __init__(self, store, entry, cover: int, n_tokens: int):
        self._store = store
        self._entry = entry
        self.cover = cover                       # chain prefix this slot maps
        self.pages = list(entry.pages[:cover])   # the shared page ids
        self.n_tokens = n_tokens
        self._released = False

    def release(self) -> Optional[_DeviceEntry]:
        """Drop this lease's ref; returns the entry iff this was the last
        ref (the caller demotes it to the host tier and unrefs its pages)."""
        return self._store._release(self)


class PrefixStore:
    """Fleet-wide two-tier prefix KV store shared by every batcher (and
    read by the router and disagg coordinator) in one serving process."""

    def __init__(self, *, host_bytes: int = 1 << 28,
                 insert_min_hits: int = 1, insert_burst: int = 32):
        if not isinstance(host_bytes, int) or isinstance(host_bytes, bool) \
                or host_bytes <= 0:
            raise ValueError(
                f"host_bytes must be a positive byte count, got {host_bytes!r}"
            )
        if insert_min_hits < 1:
            raise ValueError(
                f"insert_min_hits must be >= 1, got {insert_min_hits}"
            )
        if insert_burst < 1:
            raise ValueError(
                f"insert_burst must be >= 1, got {insert_burst}"
            )
        self._lock = make_lock("PrefixStore._lock")
        # (id(owner), digest) -> (entry, chain position + 1). Chained
        # digests make the probe exact: matching digests[i] means matching
        # the whole (i+1)-page prefix, so cover IS the index position.
        self._index: dict = {}
        # digest -> entries from ANY owner holding it (router hint + disagg
        # coverage probes, which don't care whose pool the pages sit in)
        self._by_digest: dict = {}
        self._host = KVSpillTier(host_bytes)
        self.page_size: Optional[int] = None
        # KV share-map layout the attached engines run (None == unshared).
        # Bound write-once like page_size; host-tier blocks carry the hash
        # they were exported under and a mismatch at bind time is a
        # configuration error, not an import-time checksum surprise.
        self.share_hash: Optional[str] = None
        self._share_bound = False
        # Compressed-latent codec layout the attached engines run
        # (kv_compress.py; None == raw transport). Same write-once
        # discipline: host-tier blocks compress under ONE geometry and the
        # pod heartbeat gossips this hash so mismatched peers skip each
        # other before any fetch moves bytes.
        self.compress_hash: Optional[str] = None
        self._compress_bound = False
        # pod federation handle (pod.PodFleet.attach_prefix_store sets it):
        # the scheduler's store-consult slow path calls federation.fetch()
        # on a local miss; None == single-host store, no pod consult
        self.federation = None
        # ---------------------------------------------- insertion policy
        self.insert_min_hits = insert_min_hits
        self.insert_burst = insert_burst
        self._bucket = float(insert_burst)  # refilled 1/admission, capped
        self._seen: "OrderedDict[bytes, int]" = OrderedDict()  # miss counts
        self._seen_cap = 4096
        self._paused = False
        # ---------------------------------------------------- counters
        self.queries = 0
        self.hits_device = 0
        self.hits_host = 0
        self.misses = 0
        self.tokens_reused = 0
        self.inserts = 0
        self.inserts_damped = 0
        self.cow_forks = 0
        self.demotions = 0
        self.demote_drops = 0     # last-release exports that failed/skipped
        self.evictions_reset = 0  # entries dropped by drop_owner (no export)
        self.imports_staged = 0   # host-tier imports that consumed a stage
        self.imports_demand = 0   # host-tier imports that marshaled numpy
        self.lookup_faults = 0    # cache.prefix_lookup degradations
        self.import_faults = 0    # host-block imports that fell to prefill

    # ------------------------------------------------------------ geometry
    def bind_page_size(self, page: int):
        """Each attaching batcher declares its pool page size; the chain is
        only shareable across identical page geometry, so a mismatch is a
        construction error, not a runtime degradation. Construction-time
        wiring (batchers are built sequentially), so no lock: ``page_size``
        is write-once-then-read-only."""
        existing = self.page_size
        if existing is None:
            self.page_size = int(page)
        elif existing != int(page):
            raise ValueError(
                f"prefix store is chained at page_size={existing}; an "
                f"engine with page_size={page} cannot share it"
            )

    def bind_share_hash(self, share_hash: Optional[str]):
        """Each attaching batcher declares its pool's KV share-map layout
        hash (``engine.kv_share_hash``; None == unshared/identity). Blocks
        only compose across identical layouts, so the check runs HERE, at
        construction — not as a geometry-checksum failure deep in an
        import at serve time. Write-once: a second engine binding a
        different layout, or a bind that disagrees with blocks already
        resident in the host tier, is a configuration error with a
        remediation hint."""
        if self._share_bound:
            if self.share_hash != share_hash:
                raise ValueError(
                    f"prefix store is bound to KV share-map hash "
                    f"{self.share_hash!r}; an engine with share hash "
                    f"{share_hash!r} cannot share it — serve every attached "
                    f"engine with the same --kv-share-map artifact"
                )
            return
        stale = {
            h for h in self._host.share_hashes() if h != share_hash
        }
        if stale:
            raise ValueError(
                f"prefix store host tier already holds blocks exported "
                f"under share-map hash(es) {sorted(str(h) for h in stale)} "
                f"but this engine binds {share_hash!r} — restart with the "
                f"matching --kv-share-map artifact (or a fresh store) "
                f"instead of changing KV layouts over resident blocks"
            )
        self.share_hash = share_hash
        self._share_bound = True

    def bind_compress_hash(self, compress_hash: Optional[str]):
        """Each attaching batcher declares its pool's compressed-latent
        codec layout (``engine.kv_compress_hash``; None == raw). Same
        write-once contract as :meth:`bind_share_hash`: blocks compressed
        under one geometry can only reconstruct under the same one, so a
        mismatch is a construction error with a remediation hint, not an
        import-time integrity surprise. Raw resident blocks (hash None)
        are always compatible — they import anywhere their geometry fits."""
        if self._compress_bound:
            if self.compress_hash != compress_hash:
                raise ValueError(
                    f"prefix store is bound to KV compress hash "
                    f"{self.compress_hash!r}; an engine with compress hash "
                    f"{compress_hash!r} cannot share it — serve every "
                    f"attached engine with the same model/--kv-compress-map "
                    f"geometry"
                )
            return
        stale = {
            h for h in self._host.compress_hashes()
            if h is not None and h != compress_hash
        }
        if stale:
            raise ValueError(
                f"prefix store host tier already holds blocks compressed "
                f"under hash(es) {sorted(str(h) for h in stale)} but this "
                f"engine binds {compress_hash!r} — restart with the "
                f"matching --kv-compress-map artifact (or a fresh store) "
                f"instead of changing KV layouts over resident blocks"
            )
        self.compress_hash = compress_hash
        self._compress_bound = True

    def digests_for(self, prompt) -> list:
        """The store's digest chain for ``prompt``: page-aligned chunks,
        capped one token short of the full prompt — the last prompt token
        must go through prefill to produce the first sample's logits."""
        if self.page_size is None:
            return []
        n = len(prompt)
        kmax = (n - 1) // self.page_size
        if kmax < 1:
            return []
        try:
            return chunk_digests(prompt, self.page_size, max_chunks=kmax)
        except (TypeError, ValueError):
            return []

    # ------------------------------------------------------------- lookup
    def lookup(self, owner, digests: list) -> Optional[tuple]:
        """Longest-prefix-match for an admission in ``owner``'s batcher:
        ``("device", cover)`` when the owner's pool already holds the
        prefix pages (zero-copy COW share), ``("host", cover)`` when the
        host tier holds an importable block, else None. Pure probe with no
        counter side effects — the scheduler polls this from its fit check
        every tick for a blocked queue head, then counts ONE resolution
        per admission via :meth:`count_lookup`. Fault site
        ``cache.prefix_lookup`` fires first — callers degrade to plain
        prefill and count via :meth:`count_lookup_fault`."""
        inject("cache.prefix_lookup", engine=id(owner))
        # self-instrumentation: the scheduler binds the admitting request's
        # trace (tracing.bind) around this call, so the LPM probe lands on
        # the right timeline without a signature change
        tr = tracing.current()
        if tr is None:
            return self._lookup(owner, digests)
        with tr.timed("prefix_lookup", chain=len(digests)):
            return self._lookup(owner, digests)

    def _lookup(self, owner, digests: list) -> Optional[tuple]:
        if not digests:
            return None
        oid = id(owner)
        with self._lock:
            for i in range(len(digests) - 1, -1, -1):
                if (oid, digests[i]) in self._index:
                    return ("device", i + 1)
        # host probe outside our lock (the tier locks internally; never
        # nest the two so the static lock graph stays a DAG)
        for i in range(len(digests) - 1, -1, -1):
            if self._host.contains(digests[i]):
                return ("host", i + 1)
        return None

    def count_lookup(self, kind: str, digests: Optional[list] = None):
        """Record one admission's lookup resolution: ``"device"`` /
        ``"host"`` / ``"miss"``. A miss also bumps the full-chain digest's
        seen-count, the signal ``insert_min_hits`` gates registration on —
        admissions, not polls, measure demand for a prefix."""
        with self._lock:
            self.queries += 1
            if kind == "device":
                self.hits_device += 1
            elif kind == "host":
                self.hits_host += 1
            else:
                self.misses += 1
                if digests:
                    full = digests[-1]
                    self._seen[full] = self._seen.get(full, 0) + 1
                    self._seen.move_to_end(full)
                    while len(self._seen) > self._seen_cap:
                        self._seen.popitem(last=False)

    def acquire(self, owner, digests: list, cover: int) -> Optional[PrefixLease]:
        """Lease the device entry covering ``digests[:cover]`` for one more
        slot (the COW fork: the new slot maps pages another holder still
        references). None if the entry vanished since lookup — callers
        fall back to plain prefill."""
        n_tokens = cover * (self.page_size or 0)
        with self._lock:
            hit = self._index.get((id(owner), digests[cover - 1]))
            if hit is None:
                return None
            entry, pos = hit
            if pos != cover:  # chained digests make this impossible; guard
                return None
            entry.refs += 1
            entry.hits += 1
            self.cow_forks += 1
            self.tokens_reused += n_tokens
            lease = PrefixLease(self, entry, cover, n_tokens)
            note_acquire("prefix.lease", id(lease), cover=cover)
        tr = tracing.current()
        if tr is not None:
            # the COW fork on the admitting request's timeline: how many
            # prefill tokens the store just deleted from its TTFT
            tr.point("prefix_lease", cover=cover, tokens=n_tokens)
        return lease

    def host_block(self, digest: bytes) -> Optional[KVPageBlock]:
        """The host tier's block for ``digest`` (shared — NOT removed; any
        number of admissions may import the same prefix). LRU-refreshes the
        entry so budget pressure evicts a colder prefix instead."""
        blk = self._host.peek(digest)
        if blk is not None:
            self._host.touch(digest)
        return blk

    # ----------------------------------------------------------- insertion
    def note_admission(self):
        """Token-bucket refill: one insert credit per admitted request, so
        the insert rate tracks admission rate instead of wall clock (and
        stays deterministic for tests)."""
        with self._lock:
            self._bucket = min(float(self.insert_burst), self._bucket + 1.0)

    def register(self, owner, digests: list, pages: list, tokens,
                 nbytes: int, *, force: bool = False) -> Optional[PrefixLease]:
        """Register a freshly prefilled (or freshly imported, with
        ``force=True``) prefix as a device entry and return the inserting
        slot's lease. Pure bookkeeping — no data moves; the pages are the
        slot's own prompt pages, which decode never rewrites. Returns None
        when the insertion policy declines (already resident, paused,
        below ``insert_min_hits``, bucket empty)."""
        if not digests:
            return None
        oid = id(owner)
        full = digests[-1]
        n_tok = len(digests) * (self.page_size or 0)
        # host probe before taking our lock (the tier locks internally;
        # never nest the two so the static lock graph stays a DAG)
        host_has = (not force) and self._host.contains(full)
        with self._lock:
            if (oid, full) in self._index:
                return None  # already resident (a concurrent twin won)
            if not force:
                if host_has:
                    return None  # host tier already serves it; no duplicate
                if self._paused:
                    self.inserts_damped += 1
                    return None
                if self._seen.get(full, 0) < self.insert_min_hits:
                    self.inserts_damped += 1
                    return None
                if self._bucket < 1.0:
                    self.inserts_damped += 1
                    return None
                self._bucket -= 1.0
            entry = _DeviceEntry(owner, digests, pages, tokens, nbytes)
            for i, d in enumerate(digests):
                key = (oid, d)
                if key not in self._index:  # first writer wins per digest
                    self._index[key] = (entry, i + 1)
                    entry.keys.append(key)
                    self._by_digest.setdefault(d, []).append(entry)
            if not entry.keys:
                return None  # every digest already indexed elsewhere
            entry.refs = 1
            self.inserts += 1
            self._seen.pop(full, None)
            lease = PrefixLease(self, entry, len(digests), n_tok)
            note_acquire("prefix.lease", id(lease), cover=len(digests))
            return lease

    # ------------------------------------------------------------- release
    def _release(self, lease: PrefixLease) -> Optional[_DeviceEntry]:
        with self._lock:
            if lease._released:
                raise RuntimeError(
                    "prefix lease released twice — the exactly-once release "
                    "discipline is broken (double-free of shared KV pages)"
                )
            lease._released = True
            note_release("prefix.lease", id(lease))
            entry = lease._entry
            if entry.dropped:
                return None  # drop_owner already reclaimed it wholesale
            entry.refs -= 1
            if entry.refs > 0:
                return None
            self._unindex(entry)
            return entry

    def _unindex(self, entry: _DeviceEntry):
        # caller holds self._lock
        for key in entry.keys:
            self._index.pop(key, None)
            lst = self._by_digest.get(key[1])
            if lst is not None:
                try:
                    lst.remove(entry)
                except ValueError:
                    pass
                if not lst:
                    self._by_digest.pop(key[1], None)
        entry.keys = []

    def host_put(self, digest: bytes, block: KVPageBlock) -> bool:
        """Demotion (or a pod-federated fetch): park an exported prefix
        block in the host tier under its full-chain digest. Returns the
        tier's verdict (budget/oversize rejects mean the prefix is simply
        gone — re-prefilled on next use). A block exported under a
        different share-map layout than the bound one is refused the same
        way: degraded to re-prefill, never resident-but-unimportable."""
        if self._share_bound and block.share_hash != self.share_hash:
            self.count_demote_drop()
            return False
        if (
            self._compress_bound
            and block.compress_hash is not None
            and block.compress_hash != self.compress_hash
        ):
            # compressed under a geometry no attached engine can
            # reconstruct — parking it would be resident-but-unimportable
            self.count_demote_drop()
            return False
        ok = self._host.put(digest, block)
        with self._lock:
            if ok:
                self.demotions += 1
            else:
                self.demote_drops += 1
        return ok

    def host_contains(self, digest: bytes) -> bool:
        return self._host.contains(digest)

    def host_inventory(self, cap: int = 64) -> list:
        """Hex digests of host-tier-resident prefix blocks, MRU-first and
        capped — the pod federation's gossip payload (pod.py rides it on
        the control-plane heartbeat exactly like WeightStore key digests).
        Hex, not bytes: heartbeat payloads must stay JSON-serializable."""
        out = []
        for key in self._host.keys():
            if len(out) >= cap:
                break
            if isinstance(key, (bytes, bytearray)):
                out.append(bytes(key).hex())
        return out

    def count_demote_drop(self):
        with self._lock:
            self.demote_drops += 1

    def drop_owner(self, owner):
        """Forget every device entry in ``owner``'s pool WITHOUT export —
        the pool was reset wholesale (``_fail_all``) or the batcher is
        closing, so the pages (and their contents) are already gone.
        Outstanding leases become orphans whose release is a no-op."""
        oid = id(owner)
        with self._lock:
            entries = {e for (o, _), (e, _) in list(self._index.items())
                       if o == oid}
            for entry in entries:
                self._unindex(entry)
                entry.dropped = True
                self.evictions_reset += 1

    # ------------------------------------------------- fleet-facing probes
    def covers_full(self, prompt) -> bool:
        """True when the store can serve ``prompt``'s ENTIRE page-aligned
        prefix (the disagg full-hit: phase 1 would prefill nothing worth a
        handoff, so the decode pool serves from token 0). Fires the
        ``cache.prefix_lookup`` fault site — the coordinator catches and
        runs the normal two-phase path."""
        inject("cache.prefix_lookup", probe="covers")
        digests = self.digests_for(prompt)
        if not digests:
            return False
        full = digests[-1]
        with self._lock:
            if self._by_digest.get(full):
                return True
        return self._host.contains(full)

    def owner_hint(self, prompt):
        """The batcher whose pool device-holds the longest prefix of
        ``prompt`` — the router's store-hit placement hint. None when only
        the host tier (importable anywhere) or nothing holds it."""
        digests = self.digests_for(prompt)
        with self._lock:
            for i in range(len(digests) - 1, -1, -1):
                entries = self._by_digest.get(digests[i])
                if entries:
                    return entries[0].owner
        return None

    # ------------------------------------------------------------ controls
    def pause_inserts(self, flag: bool):
        """Brownout rung (fleet.py ladder, level >= 1): under pressure new
        prefixes stop being ADMITTED to the store — registration is cheap
        but demotion exports and host-tier churn are not — while lookups
        keep serving hits, which shed prefill work exactly when the fleet
        needs it most."""
        with self._lock:
            self._paused = bool(flag)

    @property
    def inserts_paused(self) -> bool:
        with self._lock:
            return self._paused

    # -------------------------------------------------- counters for peers
    def count_lookup_fault(self):
        with self._lock:
            self.lookup_faults += 1

    def count_import(self, *, staged: bool, n_tokens: int = 0):
        with self._lock:
            if staged:
                self.imports_staged += 1
            else:
                self.imports_demand += 1
            self.tokens_reused += int(n_tokens)

    def count_import_fault(self):
        with self._lock:
            self.import_faults += 1

    # -------------------------------------------------------------- stats
    def stats(self) -> dict:
        host = self._host.stats()  # tier lock first; never under ours
        with self._lock:
            entries = {e for e, _ in self._index.values()}
            device_blocks = len(entries)
            device_bytes = sum(e.nbytes for e in entries)
            lookups = self.hits_device + self.hits_host + self.misses
            hits = self.hits_device + self.hits_host
            return {
                "device_blocks": device_blocks,
                "device_bytes": device_bytes,
                "host_blocks": host["blocks"],
                "host_bytes": host["bytes_in_use"],
                "host_budget_bytes": host["budget_bytes"],
                "queries": self.queries,
                "hits": hits,
                "hits_device": self.hits_device,
                "hits_host": self.hits_host,
                "misses": self.misses,
                "hit_rate": (hits / lookups) if lookups else 0.0,
                "tokens_reused": self.tokens_reused,
                "inserts": self.inserts,
                "inserts_damped": self.inserts_damped,
                "inserts_paused": self._paused,
                "cow_forks": self.cow_forks,
                "demotions": self.demotions,
                "demote_drops": self.demote_drops,
                "evictions_budget": host["evictions"],
                "evictions_oversize": host["rejects_oversize"],
                "evictions_reset": self.evictions_reset,
                "imports_staged": self.imports_staged,
                "imports_demand": self.imports_demand,
                "lookup_faults": self.lookup_faults,
                "import_faults": self.import_faults,
            }

    def close(self):
        self._host.close()
        with self._lock:
            for entry, _ in list(self._index.values()):
                entry.dropped = True
            self._index.clear()
            self._by_digest.clear()
