"""Seeded chaos campaigns, invariant checking, fault-schedule shrinking.

A :class:`Campaign` is a pure value: seed, fleet shape, arrival process,
and a schedule of :class:`FaultEvent`\\ s pinned to virtual timestamps.
:func:`run_campaign` builds a fresh simulated fleet (``fleetsim``), arms
each event through the production ``testing.faults`` API at its timestamp,
drives arrivals plus a post-storm settle trickle (probe traffic is what
closes breakers), drains the simulation to quiescence, and evaluates the
invariant library. Because the whole run is a pure function of the
campaign value, a failure IS its repro: re-running the same campaign
reproduces the same event log bit-for-bit (equal digests).

On failure, :func:`shrink` delta-debugs the fault schedule — re-running
fresh simulations on candidate subsets — down to a minimal schedule that
still violates the same invariant, and :func:`write_repro` /
:func:`load_repro` round-trip the result as a JSON repro file
(``python -m mlx_sharding_tpu.sim.chaos --replay <file>`` replays it).

Invariants (each returns a list of violation strings):

``no_dropped_streams``  every request ends completed / shed / client-
                        aborted — never dropped mid-stream.
``token_exact``         every delivered stream is a prefix of the
                        deterministic expected stream (resume/migration/
                        handoff never duplicated or corrupted a token).
``ledger_clean``        the runtime resource ledger balances at teardown
                        (no leaked slots, probe tickets, arms, binds).
``convergence``         after the storm: no live replica's breaker stuck
                        open, every brownout ladder back at level 0.
``queued_sane``         the aggregate queued gauge never went negative
                        and is zero at quiescence.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import random
from dataclasses import dataclass, field, asdict
from typing import Optional

from mlx_sharding_tpu import tracing
from mlx_sharding_tpu.analysis import runtime as mst_runtime
from mlx_sharding_tpu.sim.fleetsim import (
    FleetSim,
    build_fleet,
    drive_arrivals,
    token_at,
)
from mlx_sharding_tpu.sim.simkit import (
    SeededScheduleExplorer,
    Simulation,
    ddmin_trace,
)
from mlx_sharding_tpu.testing import faults

# exception name -> class, reusing the MST_FAULTS vocabulary so a repro
# file reads the same as a fault spec
_EXC = dict(faults._EXC_NAMES)

TERMINAL_OUTCOMES = ("completed", "shed", "client_aborted")


@dataclass
class FaultEvent:
    """One scheduled chaos action at virtual time ``t``.

    kinds: ``site`` (arm a fault site), ``host_kill`` (SIGKILL a host:
    fabric + engines), ``transport_kill`` (partition: fabric only),
    ``heartbeat_loss`` (drop N of one host's gossip publishes),
    ``breaker_trip`` (fail one replica's dispatches until its breaker
    opens), ``relay_crash`` (crash a host's engines mid-stream, healing
    after ``heal_after`` virtual seconds — the transient-death shape that
    exercises crash-resume AND breaker re-close)."""

    t: float
    kind: str
    site: Optional[str] = None
    host: Optional[int] = None
    exc: str = "fault"
    times: Optional[int] = 1
    after: int = 0
    match: Optional[dict] = None
    heal_after: float = 2.0

    def sites(self) -> tuple:
        if self.kind == "site":
            return (self.site,) if self.site else ()
        if self.kind == "heartbeat_loss":
            return ("multihost.exchange",)
        if self.kind == "breaker_trip":
            return ("replica.dispatch",)
        return ()


@dataclass
class Campaign:
    name: str
    seed: int = 0
    n_hosts: int = 4
    replicas_per_host: int = 2
    duration_s: float = 20.0
    settle_s: float = 15.0
    arrival: str = "surge"
    base_rate: float = 2.0
    max_tokens: int = 10
    surge_factor: float = 10.0
    schedule: list = field(default_factory=list)
    # the deliberately-broken knob: disables BOTH resume layers (the
    # dispatcher's crash-resume and the driver's cross-host failover), so
    # a mid-stream crash becomes a dropped stream the invariants catch
    resume_streams: bool = True
    # schedule exploration (all asdict/JSON-safe): ``schedule_seed=None``
    # keeps the classic totally-ordered scheduler — bit-identical digests
    # per seed. A non-None seed arms a SeededScheduleExplorer; a non-empty
    # ``schedule_trace`` replays exactly those forced divergences instead
    # (the shrunk-repro path)
    schedule_seed: Optional[int] = None
    schedule_quantum: float = 0.002
    schedule_change_points: int = 4
    schedule_trace: tuple = ()
    invariants: tuple = ("no_dropped_streams", "token_exact",
                         "ledger_clean", "convergence", "queued_sane")

    def sites(self) -> frozenset:
        return frozenset(s for ev in self.schedule for s in ev.sites())


@dataclass
class CampaignResult:
    campaign: Campaign
    digest: str
    violations: list
    outcomes: dict       # outcome -> count
    n_requests: int
    n_events: int
    # divergent scheduler picks this run actually made — what
    # shrink_schedule() delta-debugs; empty when exploration was off
    schedule_trace: tuple = ()

    @property
    def ok(self) -> bool:
        return not self.violations


def _apply_event(fs: FleetSim, ev: FaultEvent):
    sim = fs.sim
    if ev.kind == "site":
        sim.record("chaos_arm", site=ev.site)
        faults.arm(ev.site, exc=_EXC[ev.exc], times=ev.times,
                   after=ev.after, match=ev.match)
    elif ev.kind == "host_kill":
        fs.kill_host(ev.host % len(fs.hosts))
    elif ev.kind == "transport_kill":
        fs.kill_transport(ev.host % len(fs.hosts))
    elif ev.kind == "heartbeat_loss":
        sim.record("chaos_heartbeat_loss", host=ev.host)
        faults.arm("multihost.exchange", exc=_EXC[ev.exc],
                   times=ev.times or 3, match={"host": ev.host})
    elif ev.kind == "breaker_trip":
        host = fs.hosts[(ev.host or 0) % len(fs.hosts)]
        sim.record("chaos_breaker_trip", host=host.host_id)
        # fail enough consecutive dispatches on replica 0 to open its
        # breaker; the settle trickle's probe then has to close it again
        faults.arm("replica.dispatch", exc=_EXC[ev.exc],
                   times=ev.times or host.rs.breaker_threshold,
                   match={"replica": 0})
    elif ev.kind == "relay_crash":
        host = fs.hosts[(ev.host or 0) % len(fs.hosts)]
        sim.record("chaos_relay_crash", host=host.host_id)
        for rep in host.replicas:
            rep.crash()
        heal = max(0.1, ev.heal_after)

        def _heal(host=host):
            sim.record("chaos_heal", host=host.host_id)
            for rep in host.replicas:
                rep.heal()

        sim.schedule(heal, _heal)
    else:
        raise ValueError(f"unknown chaos event kind {ev.kind!r}")


# ------------------------------------------------------------- invariants
def _inv_no_dropped_streams(fs: FleetSim) -> list:
    out = []
    for rid, rec in fs.requests.items():
        if rec["outcome"] not in TERMINAL_OUTCOMES:
            out.append(
                f"stream {rid} ended {rec['outcome']!r} after "
                f"{len(rec['tokens'])} tokens (hops={rec['hops']})"
            )
    return out


def _inv_token_exact(fs: FleetSim) -> list:
    out = []
    for rid, rec in fs.requests.items():
        toks = rec["tokens"]
        want = [token_at(rec["prompt"], i) for i in range(len(toks))]
        if toks != want:
            i = next(
                (j for j, (a, b) in enumerate(zip(toks, want)) if a != b),
                min(len(toks), len(want)),
            )
            out.append(
                f"stream {rid} diverged at token {i}: got {toks[i:i + 3]} "
                f"want {want[i:i + 3]} (degradations={rec['degradations']})"
            )
    return out


def _inv_ledger_clean(fs: FleetSim, ledger) -> list:
    if ledger is None:
        return []
    try:
        ledger.assert_clean()
    except AssertionError as e:
        return [str(e)]
    return []


def _inv_convergence(fs: FleetSim) -> list:
    out = []
    for host in fs.live_hosts():
        for st in host.rs.replica_stats():
            if st["retired"] or st["draining"]:
                continue
            if st["breaker"] == "open":
                out.append(
                    f"host {host.host_id} replica {st['replica']} breaker "
                    "still open after settle"
                )
        bo = host.ctrl.brownout
        if bo is not None and bo.level() != 0:
            out.append(
                f"host {host.host_id} brownout stuck at level {bo.level()}"
            )
    return out


def _inv_queued_sane(fs: FleetSim) -> list:
    out = []
    if fs.queued_negative:
        out.append(
            f"queued gauge went negative {fs.queued_negative} time(s)"
        )
    q = fs.total_queued()
    if q != 0:
        out.append(f"aggregate queued gauge is {q} at quiescence, want 0")
    return out


INVARIANTS = {
    "no_dropped_streams": _inv_no_dropped_streams,
    "token_exact": _inv_token_exact,
    "convergence": _inv_convergence,
    "queued_sane": _inv_queued_sane,
}


# ---------------------------------------------------------------- running
def run_campaign(camp: Campaign) -> CampaignResult:
    """Execute one campaign in a fresh simulation and judge it. Always
    tears down (disarm + abort actors + close fleets) before returning, so
    campaigns can run back-to-back in one process."""
    explorer = None
    if camp.schedule_trace:
        explorer = SeededScheduleExplorer(
            random.Random(0), quantum=camp.schedule_quantum,
            replay=[tuple(p) for p in camp.schedule_trace])
    elif camp.schedule_seed is not None:
        # derived from (campaign seed, schedule seed) so N exploration
        # runs of one campaign draw N independent priority orders
        h = hashlib.blake2b(
            f"{camp.seed}:schedule:{camp.schedule_seed}".encode(),
            digest_size=8).digest()
        explorer = SeededScheduleExplorer(
            random.Random(int.from_bytes(h, "big")),
            quantum=camp.schedule_quantum,
            change_points=camp.schedule_change_points)
    sim = Simulation(seed=camp.seed, explorer=explorer)
    prev_ledger = mst_runtime._RESOURCES
    ledger = mst_runtime.instrument_resources()
    tracing.set_campaign(camp.name, seed=camp.seed, clock=sim.clock)
    horizon = camp.duration_s + camp.settle_s
    fs = build_fleet(
        sim, n_hosts=camp.n_hosts,
        replicas_per_host=camp.replicas_per_host,
        horizon_s=horizon, resume_streams=camp.resume_streams,
    )
    if not camp.resume_streams:
        fs.max_hops = 1  # the driver's failover is a resume layer too
    try:
        drive_arrivals(
            fs, kind=camp.arrival, duration_s=camp.duration_s,
            base_rate=camp.base_rate, max_tokens=camp.max_tokens,
            surge_factor=camp.surge_factor,
        )
        # settle trickle: light traffic after the storm window — breaker
        # probes need live requests to close, brownout needs calm load to
        # step its ladder back down
        trickle = sim.rng.stream("settle")
        n_settle = max(3, int(camp.settle_s * 0.5))
        for i in range(n_settle):
            delay = camp.duration_s + (i + 1) * (
                camp.settle_s * 0.6 / n_settle
            )
            prompt = [trickle.randrange(997) for _ in range(4)]
            host = trickle.randrange(camp.n_hosts)

            def _go(i=i, prompt=prompt, host=host):
                fs.submit(f"settle-{i}", prompt, 4, host=host)

            sim.schedule(delay, _go)
        for ev in sorted(camp.schedule, key=lambda e: (e.t,)):
            if ev.t > horizon:
                raise ValueError(
                    f"fault event at t={ev.t} beyond horizon {horizon}"
                )
            sim.schedule(ev.t, lambda ev=ev: _apply_event(fs, ev))
        sim.run()  # drain to quiescence: zero wall-clock sleeps throughout
        sim.record("quiesce", requests=len(fs.requests))
        violations = []
        for name in camp.invariants:
            if name == "ledger_clean":
                continue  # judged after teardown below
            for v in INVARIANTS[name](fs):
                violations.append(f"{name}: {v}")
        digest = sim.digest()
    finally:
        faults.disarm()
        tracing.set_campaign(None)
        sim.close()  # unwind parked actors -> their finally blocks release
        for host in fs.hosts:
            try:
                host.rs.close()
            except Exception:  # noqa: BLE001 — teardown is best-effort
                pass
        mst_runtime._RESOURCES = prev_ledger
    if "ledger_clean" in camp.invariants:
        violations += [
            f"ledger_clean: {v}" for v in _inv_ledger_clean(fs, ledger)
        ]
    outcomes: dict = {}
    for rec in fs.requests.values():
        key = rec["outcome"] or "unfinished"
        outcomes[key] = outcomes.get(key, 0) + 1
    return CampaignResult(
        campaign=camp, digest=digest, violations=violations,
        outcomes=outcomes, n_requests=len(fs.requests),
        n_events=len(camp.schedule),
        schedule_trace=(tuple(tuple(p) for p in explorer.trace)
                        if explorer is not None and not camp.schedule_trace
                        else camp.schedule_trace),
    )


# --------------------------------------------------------------- shrinking
def _violated_names(result: CampaignResult) -> frozenset:
    return frozenset(v.split(":", 1)[0] for v in result.violations)


def shrink(camp: Campaign, *, max_runs: int = 200) -> CampaignResult:
    """Delta-debug ``camp.schedule`` to a 1-minimal failing subset.

    Classic ddmin over the fault-event list: the predicate is "re-running
    a fresh simulation with this subset still violates at least one of the
    invariants the full campaign violated". Every probe is a full fresh
    run (determinism makes that sound); ``max_runs`` bounds the spend.
    Returns the result of the minimal campaign (its ``.campaign`` holds
    the shrunk schedule)."""
    base = run_campaign(camp)
    if base.ok:
        return base
    target = _violated_names(base)
    runs = [0]

    def fails(schedule: list) -> Optional[CampaignResult]:
        if runs[0] >= max_runs:
            return None
        runs[0] += 1
        cand = Campaign(**{**asdict(camp), "schedule": []})
        cand.schedule = list(schedule)  # keep FaultEvent objects intact
        res = run_campaign(cand)
        return res if (_violated_names(res) & target) else None

    events = list(camp.schedule)
    best = base
    n = 2
    while len(events) >= 2:
        chunk = max(1, len(events) // n)
        reduced = None
        # try each complement (drop one chunk at a time)
        for i in range(0, len(events), chunk):
            cand = events[:i] + events[i + chunk:]
            res = fails(cand)
            if res is not None:
                reduced, best = cand, res
                break
        if reduced is not None:
            events = reduced
            n = max(2, n - 1)
        elif n >= len(events):
            break
        else:
            n = min(len(events), n * 2)
    # an empty schedule can also fail (a broken knob, not a broken storm)
    if events:
        res = fails([])
        if res is not None:
            events, best = [], res
    minimal = Campaign(**{**asdict(camp), "schedule": []})
    minimal.schedule = events
    if best is base and events != list(camp.schedule):
        best = run_campaign(minimal)
    best.campaign.schedule = events
    return best


# ------------------------------------------------------ schedule exploration
def _with(camp: Campaign, **over) -> Campaign:
    """A campaign copy with fields overridden, FaultEvents kept intact."""
    cand = Campaign(**{**asdict(camp), **over, "schedule": []})
    cand.schedule = list(camp.schedule)
    return cand


def explore(camp: Campaign, *, n_seeds: int = 32,
            on_seed=None) -> Optional[CampaignResult]:
    """Run ``camp`` under ``n_seeds`` randomized schedules.

    Each seed perturbs only the scheduler's choice among events within the
    exploration quantum (PCT priorities + change points); arrivals, fault
    timestamps and RNG streams are untouched. On the first failing seed the
    divergence trace is delta-debugged with :func:`shrink_schedule` and the
    minimal replay's result is returned — its ``.campaign.schedule_trace``
    is the repro. Returns ``None`` when every seed holds the invariants.
    """
    for s in range(n_seeds):
        res = run_campaign(_with(camp, schedule_seed=s, schedule_trace=()))
        if on_seed is not None:
            on_seed(s, res)
        if not res.ok:
            return shrink_schedule(res)
    return None


def shrink_schedule(base: CampaignResult, *,
                    max_runs: int = 200) -> CampaignResult:
    """ddmin a failing exploration's divergence trace to a 1-minimal
    forced-divergence set that still violates one of the same invariants,
    then return the minimal deterministic replay's result."""
    camp = base.campaign
    target = _violated_names(base)
    runs = [0]
    cache: dict = {}

    def fails(tr) -> bool:
        key = tuple(tuple(p) for p in tr)
        if key in cache:
            return cache[key]
        if runs[0] >= max_runs:
            return False
        runs[0] += 1
        # schedule_seed cleared: an empty forced trace must mean "the
        # default schedule", not "explore again with the same seed"
        res = run_campaign(_with(camp, schedule_trace=key,
                                 schedule_seed=None))
        cache[key] = bool(_violated_names(res) & target)
        return cache[key]

    # an empty trace failing means the schedule was never the trigger
    minimal = ([] if fails([])
               else ddmin_trace(list(base.schedule_trace), fails))
    return run_campaign(
        _with(camp, schedule_seed=None,
              schedule_trace=tuple(tuple(p) for p in minimal)))


# -------------------------------------------------------------- repro files
def write_repro(path: str, result: CampaignResult) -> None:
    camp = result.campaign
    doc = {
        "format": "mst-chaos-repro-v1",
        "campaign": {
            **{k: v for k, v in asdict(camp).items() if k != "schedule"},
            "invariants": list(camp.invariants),
            "schedule": [asdict(ev) for ev in camp.schedule],
        },
        "digest": result.digest,
        "violations": result.violations,
        "outcomes": result.outcomes,
    }
    with open(path, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")


def load_repro(path: str) -> Campaign:
    with open(path) as f:
        doc = json.load(f)
    if doc.get("format") != "mst-chaos-repro-v1":
        raise ValueError(f"{path}: not a chaos repro file")
    spec = dict(doc["campaign"])
    schedule = [FaultEvent(**ev) for ev in spec.pop("schedule")]
    spec["invariants"] = tuple(spec["invariants"])
    # pre-exploration repro files lack the schedule_* fields; JSON also
    # flattens the trace's tuples into lists — normalize both
    spec["schedule_trace"] = tuple(
        tuple(p) for p in spec.get("schedule_trace", ()))
    camp = Campaign(**spec)
    camp.schedule = schedule
    return camp


# -------------------------------------------------------- scenario library
def _storm_schedule(t0: float, sites, *, times: int = 2,
                    spacing: float = 0.7) -> list:
    excs = {
        "server.sse_write": "broken_pipe",
        "multihost.exchange": "drop",
    }
    return [
        FaultEvent(t=t0 + i * spacing, kind="site", site=s,
                   exc=excs.get(s, "fault"), times=times)
        for i, s in enumerate(sites)
    ]


def _required_sites() -> list:
    from mlx_sharding_tpu.analysis.lifecycle import REQUIRED_FAULT_SITES
    seen, out = set(), []
    for sites in REQUIRED_FAULT_SITES.values():
        for s in sites:
            if s not in seen:
                seen.add(s)
                out.append(s)
    return sorted(out)


def scenario_site_storm(seed: int = 7) -> Campaign:
    """Every REQUIRED fault site armed mid-surge (the coverage-gate
    scenario: a newly required site lands here automatically)."""
    return Campaign(
        name="site_storm", seed=seed, n_hosts=4, duration_s=18.0,
        arrival="surge", base_rate=2.5,
        schedule=_storm_schedule(5.0, _required_sites()),
    )


def scenario_host_death(seed: int = 11) -> Campaign:
    """A host dies mid-surge, another loses its transport, heartbeats
    drop: peers must detect staleness while every started stream fails
    over token-exactly."""
    return Campaign(
        name="host_death", seed=seed, n_hosts=5, duration_s=18.0,
        arrival="surge", base_rate=2.5,
        schedule=[
            FaultEvent(t=7.0, kind="host_kill", host=1),
            FaultEvent(t=9.0, kind="transport_kill", host=2),
            FaultEvent(t=6.0, kind="heartbeat_loss", host=3, exc="drop",
                       times=3),
        ],
    )


def scenario_breaker_storm(seed: int = 13) -> Campaign:
    """Breaker trips plus a transient relay crash: opens must re-close
    during settle (the convergence invariant's reason to exist)."""
    return Campaign(
        name="breaker_storm", seed=seed, n_hosts=3, duration_s=16.0,
        arrival="herd", base_rate=3.0,
        schedule=[
            FaultEvent(t=2.0, kind="breaker_trip", host=0, exc="runtime",
                       times=3),
            FaultEvent(t=4.0, kind="relay_crash", host=1, heal_after=2.0),
        ],
    )


def scenario_surge_100(seed: int = 17, *, n_hosts: int = 100) -> Campaign:
    """The acceptance campaign: 100 hosts, 10x surge, host deaths +
    transport kills + a required-site fault storm, all in one seeded run."""
    schedule = [
        FaultEvent(t=8.0, kind="host_kill", host=17),
        FaultEvent(t=9.5, kind="host_kill", host=61),
        FaultEvent(t=11.0, kind="transport_kill", host=33),
        FaultEvent(t=12.5, kind="heartbeat_loss", host=5, exc="drop",
                   times=3),
    ] + _storm_schedule(8.0, _required_sites(), times=3, spacing=0.5)
    return Campaign(
        name="surge_100", seed=seed, n_hosts=n_hosts, duration_s=24.0,
        settle_s=18.0, arrival="surge", base_rate=8.0, surge_factor=10.0,
        schedule=schedule,
    )


def scenario_prefix_owner_death(seed: int = 19) -> Campaign:
    """The prefix-owner host dies mid-fetch while another host's
    heartbeats drop (stale inventories keep advertising the dead owner):
    every pod prefix consult must degrade to plain prefill — streams
    stay token-exact and none drop."""
    return Campaign(
        name="prefix_owner_death", seed=seed, n_hosts=4,
        duration_s=18.0, arrival="tenant_skew", base_rate=2.5,
        schedule=[
            # faults on the fetch control point while the hot tenant is live
            FaultEvent(t=5.0, kind="site", site="pod.prefix_fetch",
                       times=4),
            # the owner of the hot prefix dies mid-storm...
            FaultEvent(t=6.0, kind="host_kill", host=0),
            # ...and a peer's gossip stalls, so its inventory view of the
            # dead owner goes stale instead of being torn down
            FaultEvent(t=6.5, kind="heartbeat_loss", host=2, exc="drop",
                       times=3),
            FaultEvent(t=8.0, kind="site", site="pod.prefix_fetch",
                       times=2),
        ],
    )


def scenario_compress_fault_handoff(seed: int = 23) -> Campaign:
    """The compressed-latent codec (kv_compress.py, site
    ``cache.compress``) faults mid-handoff while the handoff control
    points themselves stay flaky: encode faults must ship blocks RAW
    (counted, never lost), decode faults must land on the counted
    re-prefill path — zero dropped streams, ledger clean, token-exact."""
    return Campaign(
        name="compress_fault_handoff", seed=seed, n_hosts=4,
        duration_s=18.0, arrival="surge", base_rate=2.5,
        schedule=[
            # the codec faults first on encode (export side, mid-spill /
            # mid-handoff)...
            FaultEvent(t=4.5, kind="site", site="cache.compress", times=3),
            # ...then the handoff control point itself wobbles...
            FaultEvent(t=6.0, kind="site", site="disagg.handoff", times=2),
            FaultEvent(t=6.5, kind="site", site="pod.handoff", times=2),
            # ...and the codec faults again while resumes are in flight
            # (the decode/reconstruct leg: counted re-prefill, no drops)
            FaultEvent(t=8.0, kind="site", site="cache.compress", times=3),
            FaultEvent(t=9.0, kind="site", site="cache.import", times=2),
        ],
    )


SCENARIOS = {
    "site_storm": scenario_site_storm,
    "host_death": scenario_host_death,
    "breaker_storm": scenario_breaker_storm,
    "prefix_owner_death": scenario_prefix_owner_death,
    "compress_fault_handoff": scenario_compress_fault_handoff,
    "surge_100": scenario_surge_100,
}


def scenario_sites(name: str) -> frozenset:
    """Fault sites a scenario arms (the coverage gate cross-checks the
    union of these against ``lifecycle.REQUIRED_FAULT_SITES``)."""
    return SCENARIOS[name]().sites()


# --------------------------------------------------------------------- CLI
def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m mlx_sharding_tpu.sim.chaos",
        description="Run seeded chaos campaigns against the simulated fleet",
    )
    ap.add_argument("--scenario", choices=sorted(SCENARIOS),
                    default="site_storm")
    ap.add_argument("--seed", type=int, default=None)
    ap.add_argument("--smoke", action="store_true",
                    help="tiny seeded campaign, every invariant judged "
                         "(the scripts/check.sh gate)")
    ap.add_argument("--replay", metavar="REPRO",
                    help="replay a repro file and re-judge its invariants")
    ap.add_argument("--repro-out", metavar="PATH",
                    help="on failure, shrink and write the repro here")
    ap.add_argument("--explore", type=int, metavar="N", default=0,
                    help="additionally run N seeded schedule explorations "
                         "(PCT-randomized event interleavings); a failing "
                         "seed is ddmin-shrunk to a minimal forced-"
                         "divergence repro")
    args = ap.parse_args(argv)

    if args.replay:
        camp = load_repro(args.replay)
    elif args.smoke:
        camp = scenario_site_storm(seed=args.seed or 7)
        camp = Campaign(**{**asdict(camp), "schedule": []})
        camp.n_hosts, camp.duration_s, camp.settle_s = 3, 10.0, 8.0
        camp.base_rate = 2.0
        camp.schedule = _storm_schedule(3.0, _required_sites(),
                                        spacing=0.4)
        camp.schedule.append(FaultEvent(t=5.0, kind="host_kill", host=2))
    else:
        factory = SCENARIOS[args.scenario]
        camp = factory(args.seed) if args.seed is not None else factory()

    res = run_campaign(camp)
    print(f"campaign {camp.name} seed={camp.seed} hosts={camp.n_hosts} "
          f"events={res.n_events}")
    print(f"  requests={res.n_requests} outcomes={res.outcomes}")
    print(f"  digest={res.digest}")
    if res.ok and args.explore > 0:
        bad = explore(camp, n_seeds=args.explore)
        if bad is not None:
            print(f"  schedule exploration: seed "
                  f"{bad.campaign.schedule_seed} fails; shrunk to "
                  f"{len(bad.campaign.schedule_trace)} divergence(s)")
            if args.repro_out:
                write_repro(args.repro_out, bad)
                print(f"  repro written to {args.repro_out}")
            for v in bad.violations:
                print(f"    {v}")
            return 1
        print(f"  schedule exploration: {args.explore} seed(s) green")
    if res.ok:
        print("  invariants: all green")
        return 0
    print(f"  VIOLATIONS ({len(res.violations)}):")
    for v in res.violations:
        print(f"    {v}")
    if args.repro_out:
        shrunk = shrink(camp)
        write_repro(args.repro_out, shrunk)
        print(f"  shrunk to {len(shrunk.campaign.schedule)} event(s); "
              f"repro written to {args.repro_out}")
    return 1


if __name__ == "__main__":
    raise SystemExit(main())
