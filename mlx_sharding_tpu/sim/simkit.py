"""Discrete-event simulation kernel: virtual time, deterministic actors.

The harness has three parts:

- an **event queue** ordered by ``(virtual time, sequence number)`` over a
  shared :class:`~mlx_sharding_tpu.utils.clock.VirtualClock` — the same
  clock object is injected into every real control-plane component the
  fleet simulator instantiates, so breaker probe intervals, brownout
  dwell, autoscaler hysteresis and heartbeat staleness all advance in
  lockstep with the simulation;
- a **deterministic thread-step scheduler**: request streams run real
  blocking generator code (``ReplicaSet.generate_step`` unmodified) on
  ordinary Python threads, but only ONE thread ever runs at a time — an
  actor blocks in :meth:`Simulation.sleep` (virtual seconds, zero wall
  clock) and hands control back to the event loop via an Event handshake.
  With a single runnable thread and a totally-ordered event queue, the
  interleaving is a pure function of the seed;
- a seeded :class:`SimRng` whose named substreams keep arrival processes,
  placement choices and chaos schedules independent of each other — adding
  a draw to one stream never perturbs the others.

Every interesting occurrence is appended to an **event log**;
:meth:`Simulation.digest` hashes it, and two runs of the same seed must
produce equal digests (the determinism acceptance gate and the contract
that makes a chaos repro file trustworthy).
"""

from __future__ import annotations

import hashlib
import heapq
import random
import threading
from typing import Callable, Optional

from mlx_sharding_tpu.utils.clock import VirtualClock


class SimAborted(BaseException):
    """Raised inside a parked actor when the simulation is torn down, so
    mid-stream generators unwind their ``finally`` blocks (slot releases,
    probe tickets) instead of leaking them into the runtime ledger.
    BaseException on purpose: serving code that swallows ``Exception``
    must not be able to swallow the teardown."""


class SimRng:
    """Seeded RNG with named substreams.

    ``stream("arrivals")`` always yields the same :class:`random.Random`
    for the same (seed, name) pair, derived through blake2b so streams are
    statistically independent and — the property the shrinker leans on —
    draws on one stream never shift another stream's sequence."""

    def __init__(self, seed: int):
        self.seed = int(seed)
        self._streams: dict = {}

    def stream(self, name: str) -> random.Random:
        r = self._streams.get(name)
        if r is None:
            h = hashlib.blake2b(
                f"{self.seed}:{name}".encode(), digest_size=8
            ).digest()
            r = random.Random(int.from_bytes(h, "big"))
            self._streams[name] = r
        return r


class SeededScheduleExplorer:
    """PCT-style randomized schedule exploration for :class:`Simulation`.

    The default scheduler is totally ordered: the heap pops ``(t, seq)``
    minima, so one seed is one interleaving. Real fleets are not so
    polite — two events a few hundred microseconds apart can land in
    either order. The explorer widens the pop: among the events within
    ``quantum`` virtual seconds of the heap head (at most ``window`` of
    them), it picks by per-entity *priority* (an actor name, or the loop
    for scheduled calls), drawn once per entity from the seeded ``rng``.
    At a handful of **change points** (the PCT trick) the current
    top-priority entity is demoted below everyone, forcing the schedules
    a static priority order can never produce. ``VirtualClock.set``
    ignores backward jumps, so within-quantum reordering keeps time
    monotonic.

    Every pick that diverges from the default order is appended to
    ``trace`` as ``(step, rank)`` — rank into the sorted candidate list.
    Passing a trace back via ``replay=`` forces exactly those divergences
    (every other step takes the default event), which makes a failing
    exploration a deterministic repro and gives :func:`ddmin_trace`
    something to shrink: the minimal divergence set that still fails IS
    the race, usually 1–3 choice points.
    """

    #: change points are drawn over this many steps — enough for every
    #: sim in the tree; later steps just keep the final priority order
    HORIZON = 4096

    def __init__(self, rng: random.Random, *, quantum: float = 0.002,
                 change_points: int = 4, window: int = 8,
                 replay: Optional[list] = None):
        self.quantum = float(quantum)
        self.window = int(window)
        self.steps = 0
        self.trace: list = []  # [(step, rank)] divergent picks
        self._rng = rng
        self._replay = (None if replay is None
                        else {int(s): int(r) for s, r in replay})
        self._prio: dict = {}
        self._change_at = (frozenset() if replay is not None else frozenset(
            rng.randrange(self.HORIZON) for _ in range(change_points)))

    @staticmethod
    def _entity(entry) -> str:
        _t, _seq, kind, payload = entry
        return payload.name if kind == "resume" else "loop-call"

    def pick(self, heap: list):
        """Remove and return the chosen entry; restores heap order."""
        step, self.steps = self.steps, self.steps + 1
        head_t = heap[0][0]
        cands = [e for e in heapq.nsmallest(self.window, heap)
                 if e[0] <= head_t + self.quantum]
        rank = 0
        if len(cands) > 1:
            if self._replay is not None:
                rank = min(self._replay.get(step, 0), len(cands) - 1)
            else:
                for e in cands:
                    ent = self._entity(e)
                    if ent not in self._prio:
                        self._prio[ent] = self._rng.random()
                if step in self._change_at:
                    top = max((self._entity(e) for e in cands),
                              key=self._prio.__getitem__)
                    self._prio[top] = -self._rng.random()
                rank = max(range(len(cands)), key=lambda i: (
                    self._prio[self._entity(cands[i])], -i))
                if rank != 0:
                    self.trace.append((step, rank))
        if rank == 0:
            return heapq.heappop(heap)
        chosen = cands[rank]
        heap.remove(chosen)
        heapq.heapify(heap)
        return chosen


def ddmin_trace(trace: list, fails) -> list:
    """Delta-debug a divergence trace to a 1-minimal failing subset.

    ``fails(subset) -> bool`` must re-run the scenario from scratch with
    only those forced divergences (determinism makes each probe sound).
    The same ddmin loop the chaos shrinker uses on fault schedules, small
    enough to share with schedule traces."""
    items = list(trace)
    if not fails(items):
        return items
    n = 2
    while len(items) >= 2:
        chunk = max(1, len(items) // n)
        reduced = None
        for i in range(0, len(items), chunk):
            cand = items[:i] + items[i + chunk:]
            if fails(cand):
                reduced = cand
                break
        if reduced is not None:
            items = reduced
            n = max(2, n - 1)
        elif n >= len(items):
            break
        else:
            n = min(len(items), n * 2)
    return items


class _Actor:
    __slots__ = ("name", "go", "yielded", "done", "exc", "thread")

    def __init__(self, name: str):
        self.name = name
        self.go = threading.Event()       # loop -> actor: run now
        self.yielded = threading.Event()  # actor -> loop: parked or done
        self.done = False
        self.exc: Optional[BaseException] = None
        self.thread: Optional[threading.Thread] = None


class Simulation:
    """The event loop. Create one per scenario; drive with :meth:`run`.

    ``schedule``/``every`` queue plain callables on the loop thread;
    ``spawn`` starts an actor (a real thread stepped deterministically);
    ``sleep`` is the ONLY way simulated code should pass time. The
    ``virtual_sleep`` bound method doubles as a drop-in ``sleep=`` for
    components whose wait loops run on the loop thread (``ReplicaSet.drain``):
    called there, it advances virtual time by pumping due events inline, so
    in-flight streams genuinely unwind under the waiter."""

    def __init__(self, seed: int = 0,
                 explorer: Optional[SeededScheduleExplorer] = None):
        self.clock = VirtualClock()
        self.rng = SimRng(seed)
        self.seed = int(seed)
        # schedule exploration is strictly opt-in: with explorer=None the
        # pop below is the plain heap minimum and the interleaving is the
        # same pure function of the seed it always was
        self.explorer = explorer
        self._heap: list = []   # (t, seq, kind, payload)
        self._seq = 0
        self._log: list = []
        self._actors: dict = {}  # thread ident -> _Actor
        self._aborting = False
        self._spawned = 0

    # ------------------------------------------------------------ event log
    def record(self, event: str, **fields):
        """Append one line to the event log (the digest input). Fields are
        rendered sorted so dict construction order can't leak in."""
        tail = " ".join(
            f"{k}={fields[k]}" for k in sorted(fields)
        )
        self._log.append(
            f"{self.clock.now:.6f} {event}{' ' + tail if tail else ''}"
        )

    def digest(self) -> str:
        return hashlib.blake2b(
            "\n".join(self._log).encode(), digest_size=16
        ).hexdigest()

    @property
    def events(self) -> list:
        return list(self._log)

    # ----------------------------------------------------------- scheduling
    def now(self) -> float:
        return self.clock.now

    def _push(self, t: float, kind: str, payload):
        heapq.heappush(self._heap, (t, self._seq, kind, payload))
        self._seq += 1

    def schedule(self, delay: float, fn: Callable[[], None]):
        """Run ``fn`` on the loop thread ``delay`` virtual seconds from
        now."""
        if delay < 0:
            raise ValueError("cannot schedule into the past")
        self._push(self.clock.now + delay, "call", fn)

    def every(self, interval: float, fn: Callable[[], None], *,
              until: Optional[float] = None, phase: float = 0.0):
        """Run ``fn`` every ``interval`` virtual seconds (first firing at
        ``phase``), rescheduling itself while ``now < until``. A bounded
        horizon is what lets :meth:`run` drain to empty: past ``until`` the
        only events left are in-flight actors finishing their streams."""
        if interval <= 0:
            raise ValueError("interval must be positive")

        def _tick():
            fn()
            if until is None or self.clock.now + interval <= until:
                self.schedule(interval, _tick)

        self.schedule(phase, _tick)

    # ----------------------------------------------------------------- actors
    def spawn(self, fn: Callable[[], None], name: str):
        """Start an actor: ``fn`` runs on its own thread but is stepped by
        the event loop — it must pass time only via :meth:`sleep`."""
        self._spawned += 1
        actor = _Actor(name)

        def _main():
            actor.go.wait()
            actor.go.clear()
            try:
                if not self._aborting:
                    fn()
            except SimAborted:
                pass
            except BaseException as e:  # noqa: BLE001 — surfaced by the loop
                actor.exc = e
            finally:
                actor.done = True
                # mst: allow(MST501): loop parks on yielded while an actor runs
                self._actors.pop(threading.get_ident(), None)
                actor.yielded.set()

        t = threading.Thread(
            target=_main, name=f"sim-{name}", daemon=True
        )
        actor.thread = t
        t.start()
        self._actors[t.ident] = actor
        self._push(self.clock.now, "resume", actor)
        return actor

    def sleep(self, dt: float):
        """Actor-side: park for ``dt`` virtual seconds. The calling thread
        blocks on an Event (a handoff, not a wall-clock sleep) until the
        loop reaches the wake-up timestamp."""
        actor = self._actors.get(threading.get_ident())
        if actor is None:
            raise RuntimeError("sleep() called off any actor thread — use "
                               "virtual_sleep for loop-thread waits")
        self._push(self.clock.now + max(0.0, dt), "resume", actor)
        actor.yielded.set()
        actor.go.wait()
        actor.go.clear()
        if self._aborting:
            raise SimAborted()

    def virtual_sleep(self, dt: float):
        """Drop-in ``sleep=`` for simulated components. On an actor thread
        it parks the actor; on the loop thread (a wait loop inside a
        scheduled event, e.g. a drain waiting for in-flight streams) it
        advances virtual time by running every event due in the window —
        which is exactly what lets those streams unwind."""
        if threading.get_ident() in self._actors:
            self.sleep(dt)
            return
        end = self.clock.now + max(0.0, dt)
        while self._heap and self._heap[0][0] <= end:
            self._step()
        self.clock.set(end)

    def _resume(self, actor: _Actor):
        actor.yielded.clear()
        actor.go.set()
        actor.yielded.wait()
        if actor.done and actor.exc is not None:
            exc, actor.exc = actor.exc, None
            raise RuntimeError(
                f"sim actor {actor.name!r} died: {exc!r}"
            ) from exc

    # ------------------------------------------------------------------ loop
    def _step(self):
        if self.explorer is not None and len(self._heap) > 1:
            t, _, kind, payload = self.explorer.pick(self._heap)
        else:
            t, _, kind, payload = heapq.heappop(self._heap)
        self.clock.set(t)
        if kind == "call":
            payload()
        elif not payload.done:  # "resume" for a finished actor is a no-op
            self._resume(payload)

    def run(self, until: Optional[float] = None):
        """Process events in order. ``until=None`` drains the queue —
        every periodic source must be bounded (see :meth:`every`) and every
        actor must terminate, which is the quiesce the invariant checkers
        want. With ``until`` set, stops before the first later event."""
        while self._heap:
            if until is not None and self._heap[0][0] > until:
                break
            self._step()
        if until is not None:
            self.clock.set(until)

    def close(self):
        """Teardown: abort every parked actor so generators unwind their
        finally blocks (probe tickets, slot counts) before the runtime
        leak ledger is checked."""
        self._aborting = True
        for _ in range(10_000):  # bounded: each pass retires >= 1 actor
            pending = [a for a in list(self._actors.values()) if not a.done]
            if not pending:
                break
            a = pending[0]
            a.yielded.clear()
            a.go.set()
            a.yielded.wait()
