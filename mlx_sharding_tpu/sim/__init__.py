"""Deterministic discrete-event fleet simulator + chaos campaigns.

``simkit``   — the harness: :class:`~mlx_sharding_tpu.sim.simkit.Simulation`
               (event queue over a shared ``VirtualClock``, deterministic
               thread-step scheduler, seeded ``SimRng``, event-log digest).
``fleetsim`` — real control-plane objects (``ReplicaSet`` /
               ``FleetAutoscaler`` / ``BrownoutController`` / ``PodFleet``
               over a ``LoopbackHub``) composed around stub ``SimReplica``
               engines, plus the synthetic arrival processes.
``chaos``    — seeded fault campaigns over the ``testing.faults`` site
               registry, the invariant-checker library, and the
               delta-debugging shrinker that reduces a failing campaign to
               a minimal replayable repro file.

Everything here runs with zero hardware and zero wall-clock sleeps: the
same seed always produces the same event log (bit-identical digests), so
any failure a campaign finds is a repro, not an anecdote.
"""

from mlx_sharding_tpu.sim.simkit import (  # noqa: F401
    SeededScheduleExplorer,
    SimRng,
    Simulation,
    ddmin_trace,
)
