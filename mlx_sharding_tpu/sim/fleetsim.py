"""Real control plane, stub data plane: the simulated fleet.

A :class:`SimHost` is the production composition with the engines swapped
out: a real :class:`~mlx_sharding_tpu.replicas.ReplicaSet` (breakers,
routing, drain/resume) over :class:`SimReplica` stubs, a real
:class:`~mlx_sharding_tpu.fleet.FleetAutoscaler` +
:class:`~mlx_sharding_tpu.fleet.BrownoutController`, all inside a real
:class:`~mlx_sharding_tpu.pod.PodFleet` on a shared
:class:`~mlx_sharding_tpu.pod.LoopbackHub` fabric — every component
handed the simulation's one ``VirtualClock``. Nothing here re-implements
policy; the point is that chaos campaigns exercise the SAME routing,
breaker, drain, brownout and pod-gossip code that serves production
traffic, at 100s-of-hosts scale.

:class:`SimReplica` is the batcher-shaped stub engine: a deterministic
token function (so token-exactness is checkable to the bit), virtual
per-token latency that stretches under load (so pressure/brownout/
autoscaler dynamics are real), the ``_resume`` protocol for token-exact
continuation, ``migrate_out`` for drains, and crash/heal hooks for the
chaos engine. It carries the engine-side fault sites (``scheduler.tick``,
``spec.draft``, ``cache.export``, ``cache.import``) through the same
``testing.faults.inject`` calls the real scheduler does.

The request driver runs each stream as a simulation actor through the
production dispatch path, carrying the remaining control-point sites
(``server.sse_write`` per delivered chunk, ``cache.prefix_lookup`` at
admission, ``disagg.handoff`` / ``pod.handoff`` at the two-phase and
cross-host control points) and modeling the pod story end to end: a host
death mid-stream re-places the stream on a survivor with a caller-seeded
``ResumeState`` — token-exact, never dropped.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Callable, Optional

from mlx_sharding_tpu.fleet import BrownoutController, FleetAutoscaler
from mlx_sharding_tpu.pod import LoopbackHub, PodFleet
from mlx_sharding_tpu.replicas import ReplicaSet
from mlx_sharding_tpu.resilience import (
    QueueFullError,
    ReplicasUnavailableError,
    ResumeState,
)
from mlx_sharding_tpu.sim.simkit import Simulation
from mlx_sharding_tpu.testing.faults import inject

VOCAB = 50021  # prime, so token_at mixes well


def token_at(prompt, i: int) -> int:
    """The deterministic token function: what token ``i`` of ``prompt``'s
    stream MUST be, wherever and however many times it is (re)computed.
    Token-exactness across crash-resume, drains and cross-host handoffs
    reduces to comparing against this."""
    key = ",".join(str(int(t)) for t in prompt) + f"|{i}"
    h = hashlib.blake2b(key.encode(), digest_size=4).digest()
    return int.from_bytes(h, "big") % VOCAB


class SimReplica:
    """Batcher-shaped stub engine (see module docstring)."""

    concurrent = True
    supports_resume = True

    def __init__(self, sim: Simulation, name: str, *, slots: int = 4,
                 queue_cap: int = 16, tick_s: float = 0.05,
                 draft: bool = True):
        self.sim = sim
        self.name = name
        self.slots = int(slots)
        self.queue_cap = int(queue_cap)
        self.tick_s = float(tick_s)
        self.draft = draft
        self._n = 0            # admitted streams (active + queued model)
        self._crashed = False
        self._migrate = False
        self.closed = False
        self.pressure_level = 0
        self.shed_queue_full = 0
        self.draft_faults = 0
        self.export_faults = 0
        self.import_faults = 0

    # ------------------------------------------------------------- surfaces
    def stats(self):
        return (self.slots, min(self._n, self.slots),
                max(0, self._n - self.slots))

    def resilience_stats(self):
        return {"timeouts": 0, "shed_queue_full": self.shed_queue_full,
                "shed_deadline": 0, "max_queue": self.queue_cap,
                "scheduler_thread_live": not self._crashed}

    def set_pressure(self, level: int):
        self.pressure_level = int(level)

    def close(self):
        self.closed = True

    # ---------------------------------------------------------- chaos hooks
    def crash(self):
        """Engine death: new dispatches and in-flight streams raise at
        their next step — the ReplicaSet's crash-resume path takes over."""
        self._crashed = True

    def heal(self):
        self._crashed = False

    # --------------------------------------------------------------- drain
    def migrate_out(self, deadline: Optional[float] = None) -> int:
        try:
            inject("cache.export", replica=self.name)
        except Exception:  # noqa: BLE001 — export fault degrades blockless,
            self.export_faults += 1  # the resume stays token-exact
        self._migrate = True
        return self._n

    # -------------------------------------------------------------- serving
    def generate_step(self, prompt_tokens, **kw):
        if self.closed or self._crashed:
            raise RuntimeError(f"sim replica {self.name} is down")
        resume: Optional[ResumeState] = kw.pop("_resume", None)
        hist: list = []
        if resume is not None:
            hist = [int(t) for t in (resume.history or [])]
            if resume.block is not None:
                try:
                    inject("cache.import", replica=self.name)
                except Exception:  # noqa: BLE001 — demand re-prefill path:
                    self.import_faults += 1  # same tokens, more virtual work
                    self.sim.sleep(self.tick_s * 2)
        if self._n >= self.slots + self.queue_cap:
            self.shed_queue_full += 1
            raise QueueFullError(self._n - self.slots, self.queue_cap)
        max_tokens = int(kw.get("max_tokens", 16))
        if self.pressure_level >= 1:
            # the brownout ladder's level-1 contract: cap generation length
            max_tokens = min(max_tokens, 8)
        self._n += 1
        try:
            for i in range(len(hist), max_tokens):
                # per-token latency stretches with oversubscription, so a
                # surge genuinely raises pressure instead of just fanning out
                load = max(1.0, self._n / max(1, self.slots))
                self.sim.sleep(self.tick_s * load)
                inject("scheduler.tick", engine=id(self), replica=self.name)
                if self.draft and self.pressure_level < 2:
                    try:
                        inject("spec.draft", engine=id(self))
                    except Exception:  # noqa: BLE001 — a sick draft source
                        self.draft_faults += 1  # degrades THIS tick to plain
                if self.closed or self._crashed:
                    raise RuntimeError(
                        f"sim replica {self.name} died mid-stream"
                    )
                if self._migrate:
                    from mlx_sharding_tpu.resilience import (
                        RequestMigratedError,
                    )
                    raise RequestMigratedError(ResumeState(
                        prompt=prompt_tokens, history=list(hist),
                        produced=len(hist),
                        block=("simblock", len(hist)),
                    ))
                tok = token_at(prompt_tokens, i)
                hist.append(tok)
                yield (tok, None)
        finally:
            self._n -= 1


@dataclass
class SimHost:
    host_id: int
    rs: ReplicaSet
    ctrl: FleetAutoscaler
    fleet: PodFleet
    transport: object
    replicas: list
    alive: bool = True
    heartbeat_misses: int = 0


@dataclass
class FleetSim:
    """The whole simulated deployment plus the request ledger the
    invariant checkers read."""

    sim: Simulation
    hub: LoopbackHub
    hosts: list = field(default_factory=list)
    # request ledger: rid -> record dict (outcome, delivered tokens, hops)
    requests: dict = field(default_factory=dict)
    queued_negative: int = 0
    max_hops: int = 4

    # ------------------------------------------------------------- topology
    def live_hosts(self) -> list:
        return [h for h in self.hosts if h.alive]

    def kill_host(self, host_id: int):
        """SIGKILL one host: the fabric bounces its messages, heartbeats
        freeze (peers declare it dead by staleness), its engines crash so
        in-flight streams fail over, and its periodic ticks stop."""
        host = self.hosts[host_id]
        if not host.alive:
            return
        host.alive = False
        self.hub.kill(host_id)
        for rep in host.replicas:
            rep.crash()
        self.sim.record("host_kill", host=host_id)

    def kill_transport(self, host_id: int):
        """Partition one host off the fabric without killing its engines:
        peers see a stale heartbeat (death detection fires) while the host
        keeps serving the streams it already owns."""
        host = self.hosts[host_id]
        self.hub.kill(host_id)
        self.sim.record("transport_kill", host=host_id)

    def sample_queued(self):
        """The queued-gauge sanity probe, sampled on every pod tick: the
        aggregate must never go negative (the wake-sentinel-leak bug
        class) — and must be zero once the fleet quiesces."""
        for host in self.live_hosts():
            _, _, queued = host.rs.stats()
            if queued < 0:
                self.queued_negative += 1

    def total_queued(self) -> int:
        return sum(h.rs.stats()[2] for h in self.live_hosts())

    # ------------------------------------------------------------- requests
    def submit(self, rid: str, prompt: list, max_tokens: int, *,
               host: int, cross_host: bool = False, two_phase: bool = False,
               shared_prefix: bool = False):
        rec = {
            "rid": rid, "prompt": prompt, "max_tokens": max_tokens,
            "host": host, "outcome": None, "tokens": [], "hops": 0,
            "degradations": [],
        }
        self.requests[rid] = rec
        self.sim.record("arrive", rid=rid, host=host)
        self.sim.spawn(
            lambda: self._serve(rec, cross_host=cross_host,
                                two_phase=two_phase,
                                shared_prefix=shared_prefix),
            name=f"req-{rid}",
        )

    def _route_host(self, preferred: int) -> Optional[SimHost]:
        if self.hosts[preferred].alive:
            return self.hosts[preferred]
        for host in self.hosts:  # the load balancer skips dead backends
            if host.alive:
                return host
        return None

    def _serve(self, rec: dict, *, cross_host: bool, two_phase: bool,
               shared_prefix: bool):
        rid = rec["rid"]
        host = self._route_host(rec["host"])
        if host is None:
            rec["outcome"] = "shed"
            self.sim.record("shed", reason="no_live_host", rid=rid)
            return
        if shared_prefix:
            try:
                inject("cache.prefix_lookup", probe="sim")
            except Exception:  # noqa: BLE001 — degrade to plain prefill
                rec["degradations"].append("prefix_lookup_fault")
            # the pod-federated prefix consult: a local miss pulls the
            # owner's blob over the fabric (PodPrefixFederation.fetch);
            # any fault there also degrades to plain prefill — the stream
            # is never wrong and never drops
            try:
                inject("pod.prefix_fetch", digest="sim")
            except Exception:  # noqa: BLE001 — plain prefill
                rec["degradations"].append("prefix_fetch_fault")
        if two_phase:
            try:
                inject("disagg.handoff", n_bytes=0)
            except Exception:  # noqa: BLE001 — serve-in-place
                rec["degradations"].append("handoff_fault")
        if cross_host:
            # the pod handoff control point: on success, decode lands on the
            # least-pressured live peer (the REAL pick_remote over the
            # gossip view); any fault degrades to the origin's local plan
            try:
                inject("pod.handoff", n_bytes=0)
                dest = host.fleet.handoff.pick_remote()
                if dest is not None and self.hosts[dest].alive:
                    host = self.hosts[dest]
                    rec["degradations"].append(f"pod_handoff:{dest}")
            except Exception:  # noqa: BLE001 — origin serves in place
                rec["degradations"].append("pod_handoff_fault")
        resume: Optional[ResumeState] = None
        while True:
            rec["hops"] += 1
            try:
                kw = {"max_tokens": rec["max_tokens"]}
                if resume is not None:
                    kw["_resume"] = resume
                for item in host.rs.generate_step(rec["prompt"], **kw):
                    tok = item[0] if isinstance(item, tuple) else item
                    try:
                        inject("server.sse_write")
                    except Exception:  # noqa: BLE001 — the CLIENT vanished;
                        # closing the stream is their doing, not a drop
                        rec["outcome"] = "client_aborted"
                        self.sim.record("client_abort", rid=rid)
                        return
                    rec["tokens"].append(int(tok))
                rec["outcome"] = "completed"
                self.sim.record("done", n=len(rec["tokens"]), rid=rid)
                return
            except QueueFullError:
                if rec["tokens"]:
                    # a mid-stream migration target may be full; that sheds
                    # NEW work, never a started stream — move it elsewhere
                    host = self._failover(rec, host)
                    if host is None:
                        self.sim.record("drop", kind="QueueFullError",
                                        rid=rid)
                        return
                    resume = ResumeState(
                        prompt=rec["prompt"], history=list(rec["tokens"]),
                        produced=len(rec["tokens"]),
                    )
                    continue
                rec["outcome"] = "shed"
                self.sim.record("shed", reason="queue_full", rid=rid)
                return
            except ReplicasUnavailableError:
                if not rec["tokens"]:
                    rec["outcome"] = "shed"
                    self.sim.record("shed", reason="unavailable", rid=rid)
                    return
                host = self._failover(rec, host)
                if host is None:
                    return
                resume = ResumeState(
                    prompt=rec["prompt"], history=list(rec["tokens"]),
                    produced=len(rec["tokens"]),
                )
            except Exception as exc:  # noqa: BLE001 — a host died under the
                # stream: the pod contract is a token-exact drain onto a
                # survivor, driven here by the origin's request owner
                host = self._failover(rec, host)
                if host is None:
                    self.sim.record(
                        "drop", kind=type(exc).__name__, rid=rid
                    )
                    return
                resume = ResumeState(
                    prompt=rec["prompt"], history=list(rec["tokens"]),
                    produced=len(rec["tokens"]),
                )

    def _failover(self, rec: dict, current: SimHost) -> Optional[SimHost]:
        if len(rec["tokens"]) > rec.get("_last_fail_len", -1):
            # progress since the last failure: fresh failover budget — the
            # bound exists to stop zero-progress ping-pong, not to cap how
            # many distinct storms one long stream may live through
            rec["hops"] = 1
        rec["_last_fail_len"] = len(rec["tokens"])
        if rec["hops"] >= self.max_hops:
            rec["outcome"] = "dropped"
            return None
        # seeded spread, not first-live: two storm-hit hosts must not
        # ping-pong a stream between each other while the rest of the
        # fleet sits healthy
        alive = [h for h in self.hosts if h.alive and h is not current]
        if alive:
            host = alive[self.sim.rng.stream("failover").randrange(
                len(alive))]
            rec["degradations"].append(f"failover:{host.host_id}")
            return host
        if current.alive:
            return current  # single-host fleet: retry in place
        rec["outcome"] = "dropped"
        return None


# ---------------------------------------------------------------- builders
def build_fleet(sim: Simulation, *, n_hosts: int, replicas_per_host: int = 2,
                slots: int = 4, tick_s: float = 0.05,
                max_replicas: int = 4, heartbeat_timeout_s: float = 5.0,
                ctrl_interval_s: float = 2.0, pod_interval_s: float = 1.0,
                horizon_s: float = 60.0,
                resume_streams: bool = True) -> FleetSim:
    """Compose ``n_hosts`` production control planes over one hub and
    schedule their periodic ticks (deterministically staggered). The
    ``resume_streams=False`` knob exists for deliberately-broken campaigns:
    it disables the dispatcher's crash-resume, so a mid-stream crash drops
    the stream — the violation the shrinker demo minimizes."""
    hub = LoopbackHub(clock=sim.clock)
    fs = FleetSim(sim=sim, hub=hub)
    for h in range(n_hosts):
        reps = [
            SimReplica(sim, f"h{h}r{j}", slots=slots, tick_s=tick_s)
            for j in range(replicas_per_host)
        ]
        rs = ReplicaSet(
            list(reps), probe_interval=2.0, resume_streams=resume_streams,
            clock=sim.clock, sleep=sim.virtual_sleep,
        )
        spawned = [replicas_per_host]

        def factory(sim=sim, h=h, spawned=spawned):
            spawned[0] += 1
            return SimReplica(sim, f"h{h}r{spawned[0] - 1}",
                              slots=slots, tick_s=tick_s)

        ctrl = FleetAutoscaler(
            rs, factory, clock=sim.clock, interval_s=ctrl_interval_s,
            max_replicas=max_replicas, scale_up_sustain_s=2.0,
            scale_down_sustain_s=8.0, cooldown_s=4.0, drain_deadline_s=5.0,
            brownout=BrownoutController(clock=sim.clock, dwell_s=2.0),
        )
        transport = hub.register(h)
        fleet = PodFleet(
            h, transport, rs, controllers=[ctrl], clock=sim.clock,
            heartbeat_timeout_s=heartbeat_timeout_s,
        )
        host = SimHost(host_id=h, rs=rs, ctrl=ctrl, fleet=fleet,
                       transport=transport, replicas=reps)
        fs.hosts.append(host)

        def pod_tick(host=host):
            if not host.alive:
                return
            try:
                # the gossip heartbeat IS the pod collective: a faulted
                # exchange means this host misses one publish round — the
                # heartbeat-loss chaos kind, detected by peers as staleness
                inject("multihost.exchange", host=host.host_id)
                host.fleet.tick()
            except Exception:  # noqa: BLE001 — one lost round, not a death
                host.heartbeat_misses += 1
            fs.sample_queued()

        def ctrl_tick(host=host):
            if not host.alive:
                return
            out = host.ctrl.tick()
            action = out.get("action")
            if action:
                sim.record("autoscale", action=action, host=host.host_id)

        # deterministic stagger so 100 hosts don't tick at one timestamp
        sim.every(pod_interval_s, pod_tick, until=horizon_s,
                  phase=(h % 10) * pod_interval_s / 10.0)
        sim.every(ctrl_interval_s, ctrl_tick, until=horizon_s,
                  phase=0.1 + (h % 10) * ctrl_interval_s / 10.0)
    return fs


# --------------------------------------------------------- arrival processes
def _mk_prompt(rng, prompt_len: int = 6) -> list:
    return [rng.randrange(VOCAB) for _ in range(prompt_len)]


def drive_arrivals(fs: FleetSim, *, kind: str, duration_s: float,
                   base_rate: float, max_tokens: int = 12,
                   surge_factor: float = 10.0,
                   tenant_hot_share: float = 0.8):
    """Schedule a synthetic arrival process onto the fleet.

    ``diurnal``   — a sinusoid-shaped wave over ``duration_s`` (one "day").
    ``herd``      — a thundering herd: the whole load lands in the first
                    10% of the window, then silence.
    ``tenant_skew`` — one hot tenant (shared prefix, sticky to one host
                    cohort) takes ``tenant_hot_share`` of traffic.
    ``surge``     — steady base load with a ``surge_factor``× step through
                    the middle third (the 10×-surge replay).
    """
    sim = fs.sim
    rng = sim.rng.stream(f"arrivals:{kind}")
    place = sim.rng.stream("placement")
    n_hosts = len(fs.hosts)
    hot_prompt = _mk_prompt(rng)
    t, i = 0.0, 0
    while t < duration_s:
        rate = base_rate
        if kind == "diurnal":
            frac = t / duration_s
            rate = base_rate * (0.25 + 0.75 * (1 - abs(2 * frac - 1)))
        elif kind == "herd":
            rate = base_rate * 10.0 if t < duration_s * 0.1 else 0.0
        elif kind == "surge":
            in_surge = duration_s / 3 <= t < 2 * duration_s / 3
            rate = base_rate * (surge_factor if in_surge else 1.0)
        if rate <= 0:
            t += duration_s * 0.05
            continue
        t += rng.expovariate(rate)
        if t >= duration_s:
            break
        rid = f"{kind}-{i}"
        i += 1
        hot = kind == "tenant_skew" and rng.random() < tenant_hot_share
        prompt = list(hot_prompt) if hot else _mk_prompt(rng)
        host = (place.randrange(max(1, n_hosts // 4)) if hot
                else place.randrange(n_hosts))
        delay, shared = t, hot

        def _go(rid=rid, prompt=prompt, host=host, shared=shared,
                cross=place.random() < 0.2, two=place.random() < 0.2):
            fs.submit(rid, prompt, max_tokens, host=host, cross_host=cross,
                      two_phase=two, shared_prefix=shared)

        sim.schedule(delay, _go)
    return i
