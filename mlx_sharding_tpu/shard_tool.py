"""Offline per-stage checkpoint writer.

Capability parity with the reference's ``sharding_weight.py``: stream the
source checkpoint, keep only one stage's tensors (layers in
``[start, end)``; embedding on the first stage — and on the last too for
tied-embedding models like Gemma-2; final norm + head on the last stage —
ref: sharding_weight.py:16-24, shard/server/model/gemma2.py:23-24), write
``model-{start:05d}-{end:05d}.safetensors`` plus a filtered ``weight_map``
index (ref: sharding_weight.py:26-46), bake ``start_layer``/``end_layer``
into the shard's config.json so the shard self-describes
(ref: sharding_weight.py:48-60), and copy tokenizer/aux files
(ref: sharding_weight.py:63-71).

Improvement over the reference: ``--num-stages N`` emits every stage in one
pass instead of one invocation per shard, and quantized triples
(weight/scales/biases) are kept together automatically since filtering is
key-prefix based.
"""

from __future__ import annotations

import json
import shutil
from pathlib import Path

from mlx_sharding_tpu.config import config_from_dict
from mlx_sharding_tpu.loading import (
    filter_stage_weights,
    get_model_path,
    load_raw_weights,
)

_AUX_SKIP_SUFFIXES = (".safetensors", ".safetensors.index.json")


def save_sharded_weights(
    model_path: str | Path,
    output_dir: str | Path,
    start_layer: int,
    end_layer: int,
    total_layers: int | None = None,
    emit_native: bool = False,
) -> Path:
    """Write one stage's checkpoint into ``output_dir``. Returns the dir.
    With ``emit_native`` the stage is additionally materialized through the
    model's weight mapper and saved as a native (Orbax) checkpoint under
    ``output_dir/native/`` — stacked, transposed, restore-ready."""
    model_path = get_model_path(str(model_path))
    output_dir = Path(output_dir)
    output_dir.mkdir(parents=True, exist_ok=True)

    with open(model_path / "config.json") as f:
        config_dict = json.load(f)
    if total_layers is not None:
        config_dict["num_hidden_layers"] = total_layers
    config_dict["start_layer"] = start_layer
    config_dict["end_layer"] = end_layer
    config = config_from_dict(dict(config_dict))

    weights = load_raw_weights(model_path)
    kept = filter_stage_weights(weights, config)

    from safetensors.flax import save_file

    shard_name = f"model-{start_layer:05d}-{end_layer:05d}.safetensors"
    save_file(kept, output_dir / shard_name, metadata={"format": "flax"})

    index = {
        "metadata": {"total_parameters": len(kept)},
        "weight_map": {k: shard_name for k in sorted(kept)},
    }
    with open(output_dir / "model.safetensors.index.json", "w") as f:
        json.dump(index, f, indent=2)

    with open(output_dir / "config.json", "w") as f:
        json.dump(config_dict, f, indent=2)

    copy_other_files(model_path, output_dir)

    if emit_native:
        import jax.numpy as jnp

        from mlx_sharding_tpu.checkpoint import save_native_checkpoint
        from mlx_sharding_tpu.models import get_model_class
        from mlx_sharding_tpu.loading import dequantize_weights

        weights_for_map = kept
        if config.quantization is not None:
            weights_for_map = dequantize_weights(kept, config.quantization)
        model = get_model_class(config.model_type)(config)
        params = model.map_weights(weights_for_map, jnp.bfloat16)
        native_dir = output_dir / "native"
        save_native_checkpoint(native_dir, params, config)
        copy_other_files(model_path, native_dir)
    return output_dir


def copy_other_files(model_path: Path, output_dir: Path) -> None:
    """Tokenizer + aux files travel with every shard (ref:
    sharding_weight.py:63-71); weights and config are freshly written."""
    for item in model_path.iterdir():
        if item.name == "config.json" or item.name.endswith(_AUX_SKIP_SUFFIXES):
            continue
        if item.is_file():
            shutil.copy2(item, output_dir / item.name)


def even_partition(num_layers: int, num_stages: int) -> list[tuple[int, int]]:
    """[start, end) bounds per stage; remainder layers go to the earliest
    stages so later (post-norm-heavy) stages stay lighter."""
    base, rem = divmod(num_layers, num_stages)
    bounds = []
    start = 0
    for s in range(num_stages):
        size = base + (1 if s < rem else 0)
        bounds.append((start, start + size))
        start += size
    return bounds


def shard_all_stages(
    model_path: str | Path,
    output_root: str | Path,
    num_stages: int,
    emit_native: bool = False,
) -> list[Path]:
    model_path = get_model_path(str(model_path))
    with open(model_path / "config.json") as f:
        num_layers = json.load(f)["num_hidden_layers"]
    dirs = []
    for i, (start, end) in enumerate(even_partition(num_layers, num_stages)):
        out = Path(output_root) / f"stage_{i:02d}"
        dirs.append(
            save_sharded_weights(model_path, out, start, end, emit_native=emit_native)
        )
    return dirs


def main(argv=None):
    import argparse

    parser = argparse.ArgumentParser(
        description="Partition a checkpoint into pipeline-stage checkpoints "
        "(TPU-native equivalent of the reference's sharding_weight.py)"
    )
    parser.add_argument("--model", required=True, help="source model path or HF repo")
    parser.add_argument("--output-dir", required=True)
    parser.add_argument("--start-layer", type=int)
    parser.add_argument("--end-layer", type=int)
    parser.add_argument("--total-layers", type=int, default=None)
    parser.add_argument(
        "--num-stages", type=int, default=None,
        help="emit all stages at once under output-dir/stage_NN/",
    )
    parser.add_argument(
        "--emit-native", action="store_true",
        help="also write each stage as a native (Orbax) checkpoint under "
        "<stage>/native/ — stacked and transposed, restore-ready",
    )
    args = parser.parse_args(argv)

    if args.num_stages:
        dirs = shard_all_stages(
            args.model, args.output_dir, args.num_stages, args.emit_native
        )
        for d in dirs:
            print(d)
    else:
        if args.start_layer is None or args.end_layer is None:
            parser.error("--start-layer/--end-layer required without --num-stages")
        print(
            save_sharded_weights(
                args.model, args.output_dir, args.start_layer, args.end_layer,
                args.total_layers, emit_native=args.emit_native,
            )
        )


if __name__ == "__main__":
    main()
