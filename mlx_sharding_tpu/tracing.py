"""Per-request span tracing: flight recorder + Chrome ``trace_event`` export.

Aggregate counters (``/metrics``) say *that* p99 TTFT regressed; they
cannot say *which hop* cost what for *which request*. This module is the
per-request instrument: a :class:`RequestTrace` records typed spans —
``queue_wait``, ``prefix_lookup``, ``prefill``, ``handoff_export`` /
``handoff_transfer`` / ``handoff_import``, ``decode_tick``, ``spill``,
``wake``, ``prefetch``, ``migration``, ``sse_write`` — into a bounded,
lock-correct structure, and finished traces land in a ring buffer (the
"flight recorder", ``--trace-buffer N`` requests) that serves
``GET /admin/trace/{request_id}`` and ``GET /admin/trace/dump`` as Chrome
``chrome://tracing`` JSON.

Cost contract: with ``--trace off`` (the default — the module-level tracer
starts unconfigured) every instrumentation site is one attribute load and
one ``is None`` branch; no span object, no timestamp, no lock is ever
touched. The mstcheck rule MST112 enforces exactly this shape inside
tick-hot scheduler functions: any ``tracing.``/span call there must sit
under an ``if tr is not None:``-style guard.

Sampling: ``--trace sample`` traces one request in ``sample_n`` (counter-
based, deterministic — no wall clock, no RNG); ``--trace on`` traces all.

Post-mortems: :func:`auto_snapshot` freezes the live + ring traces into a
bounded snapshot list. It is called on breaker trip
(``ReplicaSet._record_failure``), wedge detection
(``ContinuousBatcher.close`` join timeout), and every fault-site firing
(``testing.faults.inject``), so the victim request's timeline survives the
incident even after the ring cycles.

Timebase: ``time.perf_counter()`` throughout (never ``time.time()`` —
wall clock steps under NTP and is banned from hot paths by MST107/MST112).
Chrome ``ts`` values are microseconds relative to the tracer's epoch, so
every trace in a dump shares one timeline.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import deque
from typing import Optional

from mlx_sharding_tpu.analysis.runtime import make_lock, note_acquire, note_release

# the typed span vocabulary — one lane per type in the Chrome export
SPAN_TYPES = (
    "queue_wait",
    "prefix_lookup",
    "prefill",
    "handoff_export",
    "handoff_transfer",
    "handoff_import",
    "decode_tick",
    "spill",
    "wake",
    "prefetch",
    "migration",
    "sse_write",
)

# hard bound per trace: a runaway stream degrades to a truncated timeline
# (with a drop counter), never to unbounded memory
MAX_SPANS_PER_TRACE = 4096
# snapshots kept (each is a frozen copy of live+ring at incident time)
MAX_SNAPSHOTS = 8


class RequestTrace:
    """One request's span timeline. All mutation is under a leaf lock —
    spans arrive from the scheduler tick thread while the server thread
    may be exporting — and every recording method is cheap enough that
    call sites only need the ``if tr is not None:`` no-op guard."""

    __slots__ = ("request_id", "t0", "_lock", "_spans", "_marks", "_meta",
                 "_dropped", "done")

    def __init__(self, request_id: str, t0: Optional[float] = None):
        self.request_id = str(request_id)
        self.t0 = time.perf_counter() if t0 is None else float(t0)
        self._lock = make_lock("RequestTrace._lock")
        self._spans: list = []   # (name, t0, t1, args) perf_counter seconds
        self._marks: list = []   # (name, t, args) instant events
        self._meta: dict = {}
        self._dropped = 0
        self.done = False

    # ------------------------------------------------------------ recording
    def add(self, name: str, t0: float, t1: float, **args):
        """Record a completed span with caller-measured endpoints. The
        caller takes the two ``perf_counter()`` stamps so the lock is held
        for the append only, never across the timed work."""
        with self._lock:
            if len(self._spans) >= MAX_SPANS_PER_TRACE:
                self._dropped += 1
                return
            self._spans.append((name, float(t0), float(t1), args or None))

    def point(self, name: str, **args):
        """Record an instant event (first token, fault firing, failover)."""
        t = time.perf_counter()
        with self._lock:
            if len(self._marks) >= MAX_SPANS_PER_TRACE:
                self._dropped += 1
                return
            self._marks.append((name, t, args or None))

    @contextlib.contextmanager
    def timed(self, name: str, **args):
        """Span context manager for non-hot call sites (store lookups,
        handoff phases, SSE writes). Hot paths use :meth:`add` directly."""
        t0 = time.perf_counter()
        try:
            yield self
        finally:
            self.add(name, t0, time.perf_counter(), **args)

    def note(self, **meta):
        """Attach request metadata (prompt tokens, replica, role...)."""
        with self._lock:
            self._meta.update(meta)

    # ------------------------------------------------------------- reading
    def freeze(self) -> dict:
        """A consistent, immutable copy for snapshots and export."""
        with self._lock:
            return {
                "request_id": self.request_id,
                "t0": self.t0,
                "spans": list(self._spans),
                "marks": list(self._marks),
                "meta": dict(self._meta),
                "dropped": self._dropped,
                "done": self.done,
            }

    def span_names(self) -> list:
        with self._lock:
            return [s[0] for s in self._spans]

    def mark_names(self) -> list:
        with self._lock:
            return [m[0] for m in self._marks]


class Tracer:
    """The flight recorder: live traces by request id, a bounded ring of
    finished traces, and frozen incident snapshots."""

    def __init__(self, *, mode: str = "off", buffer: int = 256,
                 sample_n: int = 8, profile: bool = False):
        if mode not in ("off", "sample", "on"):
            raise ValueError(f"trace mode must be off/sample/on, got {mode!r}")
        if buffer < 1:
            raise ValueError(f"trace buffer must be >= 1, got {buffer}")
        if sample_n < 1:
            raise ValueError(f"sample_n must be >= 1, got {sample_n}")
        self.mode = mode
        self.buffer = int(buffer)
        self.sample_n = int(sample_n)
        self.profile = bool(profile)
        self.epoch = time.perf_counter()  # shared timebase for dumps
        self._lock = make_lock("Tracer._lock")
        self._live: dict = {}                 # request_id -> RequestTrace
        self._ring: deque = deque(maxlen=self.buffer)
        self._snapshots: list = []            # (reason, [frozen trace, ...])
        self._seq = 0                         # begin() calls (sampling base)
        self._started = 0                     # traces actually created

    @property
    def enabled(self) -> bool:
        return self.mode != "off"

    # ----------------------------------------------------------- lifecycle
    def begin(self, request_id: Optional[str] = None) -> Optional[RequestTrace]:
        """Start tracing one request. Returns None when off or unsampled —
        every downstream site then short-circuits on the None check."""
        if self.mode == "off":
            return None
        with self._lock:
            self._seq += 1
            if self.mode == "sample" and (self._seq - 1) % self.sample_n:
                return None
            if request_id is None:
                request_id = f"req-{self._seq}"
            tr = RequestTrace(request_id)
            self._live[tr.request_id] = tr
            self._started += 1
            return tr

    def finish(self, tr: Optional[RequestTrace]):
        """Retire a trace into the ring. Accepts None so call sites don't
        need their own guard at request teardown."""
        if tr is None:
            return
        with tr._lock:
            tr.done = True
        with self._lock:
            self._live.pop(tr.request_id, None)
            self._ring.append(tr)

    # ------------------------------------------------------------- reading
    def get(self, request_id: str) -> Optional[dict]:
        """Frozen trace for ``request_id`` from live, ring, or snapshots
        (newest first)."""
        with self._lock:
            tr = self._live.get(request_id)
            ring = list(self._ring)
            snaps = list(self._snapshots)
        if tr is not None:
            return tr.freeze()
        for cand in reversed(ring):
            if cand.request_id == request_id:
                return cand.freeze()
        for _, frozen, _camp in reversed(snaps):
            for f in frozen:
                if f["request_id"] == request_id:
                    return f
        return None

    def dump(self) -> list:
        """Frozen copies of every live + ring trace (oldest first)."""
        with self._lock:
            traces = list(self._ring) + list(self._live.values())
        return [t.freeze() for t in traces]

    def snapshot(self, reason: str) -> dict:
        """Freeze the recorder for a post-mortem: live and ring traces are
        copied (the originals keep recording) into a bounded snapshot list
        keyed by ``reason`` (``fault:<site>``, ``breaker_open``, ``wedge``)."""
        frozen = self.dump()
        # campaign provenance: when a chaos campaign is active (sim/chaos
        # sets it), the snapshot carries the campaign's seed and the VIRTUAL
        # timestamp of the incident — enough to link a production-shaped
        # post-mortem back to its replayable repro file
        camp = campaign_stamp()
        with self._lock:
            self._snapshots.append((reason, frozen, camp))
            while len(self._snapshots) > MAX_SNAPSHOTS:
                self._snapshots.pop(0)
        out = {"reason": reason, "traces": frozen}
        if camp is not None:
            out["campaign"] = camp
        return out

    def snapshots(self) -> list:
        with self._lock:
            snaps = list(self._snapshots)
        out = []
        for r, f, camp in snaps:
            entry = {"reason": r, "traces": f}
            if camp is not None:
                entry["campaign"] = camp
            out.append(entry)
        return out

    def stats(self) -> dict:
        with self._lock:
            return {
                "mode": self.mode,
                "buffer": self.buffer,
                "sample_n": self.sample_n,
                "profile": self.profile,
                "live": len(self._live),
                "ring": len(self._ring),
                "snapshots": len(self._snapshots),
                "begun": self._seq,
                "sampled": self._started,
            }

    # -------------------------------------------------------------- export
    def export_request(self, request_id: str) -> Optional[dict]:
        frozen = self.get(request_id)
        if frozen is None:
            return None
        return chrome_trace([frozen], epoch=self.epoch)

    def export_dump(self) -> dict:
        out = chrome_trace(self.dump(), epoch=self.epoch)
        with self._lock:
            snaps = list(self._snapshots)
        out["snapshots"] = [
            dict(
                {"reason": r,
                 "requests": [f["request_id"] for f in frozen]},
                **({"campaign": camp} if camp is not None else {}),
            )
            for r, frozen, camp in snaps
        ]
        return out


# --------------------------------------------------------- chrome export
def _lane(name: str) -> int:
    """Stable tid per span type so every request renders the same lanes."""
    try:
        return SPAN_TYPES.index(name) + 1
    except ValueError:
        return len(SPAN_TYPES) + 1


def chrome_trace(frozen_traces: list, *, epoch: float) -> dict:
    """Chrome ``trace_event`` JSON (the ``chrome://tracing`` / Perfetto
    format): one process per request, one thread lane per span type,
    ``ts``/``dur`` in microseconds relative to ``epoch``."""
    events = []
    for pid, f in enumerate(frozen_traces, start=1):
        rid = f["request_id"]
        events.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": f"request {rid}"},
        })
        for lane_name in SPAN_TYPES:
            events.append({
                "name": "thread_name", "ph": "M", "pid": pid,
                "tid": _lane(lane_name), "args": {"name": lane_name},
            })
        for name, t0, t1, args in f["spans"]:
            events.append({
                "name": name, "ph": "X", "cat": "request",
                "ts": round((t0 - epoch) * 1e6, 1),
                "dur": round(max(0.0, t1 - t0) * 1e6, 1),
                "pid": pid, "tid": _lane(name),
                "args": dict(args or {}, request_id=rid),
            })
        for name, t, args in f["marks"]:
            events.append({
                "name": name, "ph": "i", "s": "p", "cat": "request",
                "ts": round((t - epoch) * 1e6, 1),
                "pid": pid, "tid": _lane(name.split(":", 1)[0]),
                "args": dict(args or {}, request_id=rid),
            })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# ----------------------------------------------------- module-level wiring
_TRACER: Optional[Tracer] = None
_TRACER_LOCK = threading.Lock()
_TLS = threading.local()


def configure(mode: str = "off", *, buffer: int = 256, sample_n: int = 8,
              profile: bool = False) -> Tracer:
    """Install the process-wide tracer (``--trace``/``--trace-buffer``/
    ``--trace-profile``). Replaces any previous tracer wholesale so tests
    can reconfigure; serving configures once at startup."""
    global _TRACER
    t = Tracer(mode=mode, buffer=buffer, sample_n=sample_n, profile=profile)
    with _TRACER_LOCK:
        _TRACER = t
    return t


def get_tracer() -> Optional[Tracer]:
    return _TRACER


def begin(request_id: Optional[str] = None) -> Optional[RequestTrace]:
    """Convenience: start a trace on the process tracer (None when off)."""
    t = _TRACER
    if t is None:
        return None
    return t.begin(request_id)


def finish(tr: Optional[RequestTrace]):
    t = _TRACER
    if t is not None:
        t.finish(tr)


# ------------------------------------------------------ thread-local bind
def current() -> Optional[RequestTrace]:
    """The trace bound to the calling thread (see :class:`bind`) — how
    leaf modules (prefix_store, kv_transfer) and the fault harness stamp
    the right request without signature changes."""
    return getattr(_TLS, "trace", None)


class bind:
    """Bind ``tr`` (possibly None) to the calling thread for a region::

        with tracing.bind(req._trace):
            store.lookup(owner, digests)   # lookup self-instruments
    """

    __slots__ = ("_tr", "_prev")

    def __init__(self, tr: Optional[RequestTrace]):
        self._tr = tr

    def __enter__(self):
        self._prev = getattr(_TLS, "trace", None)
        _TLS.trace = self._tr
        note_acquire("tracing.bind", id(self))
        return self._tr

    def __exit__(self, *exc):
        _TLS.trace = self._prev
        note_release("tracing.bind", id(self))
        return False


# ------------------------------------------------------------ post-mortem
# chaos-campaign provenance: while a seeded campaign is running, every
# flight-recorder snapshot is stamped with the campaign's identity and the
# VIRTUAL time of the incident, so a production-shaped post-mortem links
# straight back to the repro file that replays it bit-identically.
_CAMPAIGN: Optional[dict] = None


def set_campaign(name: Optional[str], seed: Optional[int] = None,
                 clock=None):
    """Install (or, with ``name=None``, clear) the active chaos-campaign
    context. ``clock`` is the campaign's virtual clock; it is read at each
    snapshot to stamp ``t_virtual``."""
    global _CAMPAIGN
    if name is None:
        _CAMPAIGN = None
    else:
        _CAMPAIGN = {"name": str(name), "seed": int(seed or 0),
                     "clock": clock}


def campaign_stamp() -> Optional[dict]:
    """The JSON-safe provenance dict for the active campaign (None when no
    campaign is running)."""
    camp = _CAMPAIGN
    if camp is None:
        return None
    out = {"name": camp["name"], "seed": camp["seed"]}
    clock = camp.get("clock")
    if clock is not None:
        try:
            out["t_virtual"] = float(clock())
        except Exception:  # noqa: BLE001 — provenance never breaks a snapshot
            pass
    return out


def auto_snapshot(reason: str):
    """Freeze the flight recorder on an incident (breaker trip, wedge,
    fault firing). Near-free no-op when tracing is off."""
    t = _TRACER
    if t is not None and t.enabled:
        try:
            t.snapshot(reason)
        except Exception:  # a sick recorder must never worsen an incident
            pass


def record_fault(site: str):
    """Called by ``testing.faults.inject`` when an armed fault actually
    fires: stamp the bound request's timeline with the degradation event,
    then snapshot so the victim's trace survives the ring."""
    tr = current()
    if tr is not None:
        tr.point(f"fault:{site}", site=site)
    auto_snapshot(f"fault:{site}")


# -------------------------------------------------- XLA profiler bridging
def profile_enabled() -> bool:
    t = _TRACER
    return bool(t is not None and t.enabled and t.profile)


def profile_span(name: str):
    """``jax.profiler.TraceAnnotation`` context for a sampled decode block
    (``--trace-profile``), so host spans line up with the XLA timeline in
    an on-chip ``profile_trace`` capture. Null context when jax's profiler
    is unavailable — tracing must not create a jax dependency."""
    try:
        from jax.profiler import TraceAnnotation
        return TraceAnnotation(name)
    except Exception:
        return contextlib.nullcontext()
