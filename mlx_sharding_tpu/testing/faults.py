"""Fault-injection harness for the serving resilience layer.

Deterministic failure testing needs a way to *make* the steady-state
disasters happen on demand: a wedged engine tick, a replica that stalls or
errors, a multi-host exchange that never completes, a client that vanishes
mid-SSE-stream. This module plants named injection points at those sites —
``inject("<site>")`` calls that are a single dict lookup when nothing is
armed — and lets tests (or an operator, via ``MST_FAULTS``) arm them with a
delay, a gate (block until released), or an exception.

Sites wired into the serving stack:

- ``scheduler.tick``      — top of every ContinuousBatcher scheduler tick
  (arm a gate/delay here to wedge the engine mid-generation); ctx
  ``engine=id(batcher)`` (match it to target one batcher among several)
- ``scheduler.harvest``   — the harvest boundary of a dispatched decode
  block, just before THE tick sync (kill the in-flight block here to test
  that the async pipeline sheds cleanly: no wedged slots, pages returned)
- ``replica.dispatch``    — before a ReplicaSet routes a request into a
  replica; ctx ``replica=<i>`` (match to delay/fail one specific replica)
- ``multihost.exchange``  — top of every ControlPlane collective (raise
  :class:`DropExchange` to simulate a peer that never arrives)
- ``server.sse_write``    — every SSE chunk write in the HTTP layer (raise
  ``BrokenPipeError`` to kill a stream mid-generation)
- ``cache.export``        — top of every KV page-block export (preemption
  spill / drain migration; raise here to force the blockless fallback)
- ``cache.import``        — top of every KV page-block import at resume
  (raise here to force a re-prefill instead of a block re-import)
- ``cache.prefetch``      — top of ``KVPageBlock.prefetch`` (the overlapped
  host→device stage; raise here to force the counted demand-import path —
  the stream must still resume token-exact)
- ``replica.drain``       — entry of ``ReplicaSet.drain(i)``, after the
  replica is marked draining; ctx ``replica=<i>`` (kill a drain
  mid-migration to test the quarantine-and-retry path)
- ``autoscaler.tick``     — top of every FleetAutoscaler control tick
  (raise/delay here to prove a sick controller leaves the static fleet
  serving and never drops a stream)
- ``replica.spawn``       — before the autoscaler's ReplicaFactory builds
  a new replica (raise here to test scale-up failure degrading to the
  current fleet)
- ``disagg.handoff``      — the prefill→decode handoff control point in
  the DisaggCoordinator, after the first token but before the block's
  device→host copy; ctx ``n_bytes=<block payload>`` (raise here to force
  serve-in-place: the prefill pool finishes the stream itself)
- ``cache.prefix_lookup`` — top of every PrefixStore LPM probe (admission
  lookup, disagg full-hit check); ctx ``engine=id(batcher)`` or
  ``probe="covers"`` (raise here to prove a sick store degrades to plain
  prefill — the stream is never wrong and never drops)
- ``pod.handoff``         — the cross-host prefill→decode handoff control
  point in ``PodHandoff.serve_remote``, before any wire work; ctx
  ``n_bytes=<block payload>`` (raise here to force the origin's local
  plan — serve-in-place with the block intact, never a dropped stream)
- ``pod.prefix_fetch``    — top of ``PodPrefixFederation.fetch``, before
  the pod-view owner lookup; ctx ``digest=<hex>`` (raise here to prove a
  sick federation degrades to plain prefill — counted in
  ``stats()["fallbacks"]["fetch_fault"]``, the stream is never wrong and
  never drops)
- ``cache.compress``      — top of every compressed-latent KV encode and
  decode (``kv_compress.KVCompressCodec``); ctx ``op="encode"`` (raise
  to prove a faulted compressor ships the block RAW — counted, never
  lost) or ``op="decode"`` (raise to prove a faulted reconstruction
  lands on the consumer's counted re-prefill path — never a wrong or
  dropped stream)
- ``spec.draft``          — before each speculative round's draft
  proposals (n-gram lookup or draft-engine forward); ctx
  ``engine=id(batcher)`` (raise here to prove a sick draft source
  degrades THAT tick to plain decode — counted in
  ``spec_stats()["draft_faults"]``, streams stay token-exact and are
  never dropped)

Programmatic use (the fault-injection test suite)::

    from mlx_sharding_tpu.testing import faults
    gate = threading.Event()
    f = faults.arm("scheduler.tick", gate=gate, after=2, times=1)
    ...            # tick 3 blocks until gate.set(); f.fired counts hits
    faults.disarm()

Env activation (``MST_FAULTS``), for wedging a live deployment::

    MST_FAULTS="scheduler.tick:delay=5:times=1,replica.dispatch:exc=runtime"

Every armed fault auto-expires after ``times`` firings (default: forever),
and gates wait at most ``GATE_MAX_WAIT_S`` so a forgotten ``gate.set()``
can never hang a suite.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from mlx_sharding_tpu.analysis.runtime import note_acquire, note_release

# safety bound on gate waits: a test that forgets to release its gate gets
# a slow test, not a hung interpreter
GATE_MAX_WAIT_S = 30.0


class FaultError(RuntimeError):
    """Default exception raised by an armed fault with ``exc=True``."""


class DropExchange(Exception):
    """Raised at ``multihost.exchange`` to simulate a collective whose peer
    never arrives; ControlPlane converts it into its dead-plane path."""


_EXC_NAMES = {
    "fault": FaultError,
    "runtime": RuntimeError,
    "broken_pipe": BrokenPipeError,
    "timeout": TimeoutError,
    "drop": DropExchange,
}


@dataclass
class Fault:
    site: str
    delay: float = 0.0
    gate: Optional[threading.Event] = None
    exc: object = None  # exception instance/class, or None
    times: Optional[int] = None  # firings before auto-disarm; None = forever
    after: int = 0  # skip the first N hits (arm "on the Nth call")
    match: Optional[dict] = None  # ctx subset that must match to fire
    fired: int = 0  # observability for test assertions
    skipped: int = 0
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def _applies(self, ctx: dict) -> bool:
        if self.match:
            for k, v in self.match.items():
                if ctx.get(k) != v:
                    return False
        with self._lock:
            if self.times is not None and self.fired >= self.times:
                return False
            if self.skipped < self.after:
                self.skipped += 1
                return False
            self.fired += 1
            return True

    def trigger(self):
        if self.gate is not None:
            self.gate.wait(timeout=GATE_MAX_WAIT_S)
        if self.delay > 0:
            time.sleep(self.delay)
        if self.exc is not None:
            e = self.exc
            raise e() if isinstance(e, type) else e


# site -> list[Fault]; empty dict == fully disarmed (the inject fast path)
_ARMED: dict[str, list[Fault]] = {}
_ARM_LOCK = threading.Lock()

# parse-failure accounting (see _parse_env): lifetime count of MST_FAULTS
# entries that were dropped as malformed, exported to /metrics as
# ``mst_faults_malformed_total`` so a typo'd fault spec in a live
# deployment is a visible counter, not just a log line at boot
_MALFORMED = 0
# strict mode: tests (and MST_FAULTS_STRICT=1 deployments) turn the
# warning into a raise — a chaos campaign must not silently run with half
# its schedule dropped
_STRICT = False


class MalformedFaultSpec(ValueError):
    """A ``MST_FAULTS`` entry failed to parse under strict mode."""


def set_strict(enabled: bool) -> None:
    """Toggle strict parsing of fault specs (tests arm this so a typo in a
    campaign schedule fails loudly instead of quietly doing nothing)."""
    global _STRICT
    with _ARM_LOCK:
        _STRICT = bool(enabled)


def malformed_total() -> int:
    """Lifetime count of dropped-as-malformed fault specs."""
    with _ARM_LOCK:
        return _MALFORMED


def armed_sites() -> dict[str, int]:
    """Currently armed sites -> armed-fault count, for the
    ``mst_faults_armed{site}`` gauge: a fault left armed in a live
    deployment (a forgotten MST_FAULTS, a campaign that didn't disarm)
    should be visible on every scrape, not discovered during an incident."""
    with _ARM_LOCK:
        return {site: len(lst) for site, lst in _ARMED.items() if lst}


def arm(
    site: str,
    *,
    delay: float = 0.0,
    gate: Optional[threading.Event] = None,
    exc: object = None,
    times: Optional[int] = None,
    after: int = 0,
    match: Optional[dict] = None,
) -> Fault:
    """Arm a fault at ``site``; returns the Fault for assertions."""
    f = Fault(site=site, delay=delay, gate=gate, exc=exc, times=times,
              after=after, match=match)
    with _ARM_LOCK:
        _ARMED.setdefault(site, []).append(f)
    note_acquire("faults.arm", id(f), site=site)
    return f


def disarm(site: Optional[str] = None):
    """Disarm one site, or everything when ``site`` is None."""
    with _ARM_LOCK:
        if site is None:
            dropped = [f for lst in _ARMED.values() for f in lst]
            _ARMED.clear()
        else:
            dropped = _ARMED.pop(site, [])
    for f in dropped:
        note_release("faults.arm", id(f))


def inject(site: str, **ctx):
    """Injection point: no-op unless a matching fault is armed at ``site``.
    May sleep (delay/gate) and/or raise (exc) per the armed fault."""
    if not _ARMED:  # fast path: nothing armed anywhere
        return
    for f in _ARMED.get(site, ()):
        if f._applies(ctx):
            try:
                # stamp the degradation on the victim request's timeline
                # and snapshot the flight recorder BEFORE the fault fires —
                # after trigger() the stack is already unwinding. Lazy
                # import: faults must stay importable from anywhere without
                # dragging the tracing module in at arm time.
                from mlx_sharding_tpu import tracing

                tracing.record_fault(site)
            except Exception:  # noqa: BLE001 — tracing never blocks a fault
                pass
            f.trigger()


def _parse_env(spec: str):
    """``MST_FAULTS="site:key=val:key=val,site2:..."`` — flag-activated
    faults for wedging a live deployment without code changes."""
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        site, kw = fields[0], {}
        try:
            for kv in fields[1:]:
                k, _, v = kv.partition("=")
                if k == "delay":
                    kw["delay"] = float(v)
                elif k == "times":
                    kw["times"] = int(v)
                elif k == "after":
                    kw["after"] = int(v)
                elif k == "exc":
                    kw["exc"] = _EXC_NAMES[v]
                elif k:
                    raise KeyError(k)  # unknown key: count it, don't guess
            arm(site, **kw)
        except (KeyError, ValueError) as e:
            global _MALFORMED
            with _ARM_LOCK:
                _MALFORMED += 1
                strict = _STRICT
            if strict:
                raise MalformedFaultSpec(
                    f"malformed MST_FAULTS entry {part!r}"
                ) from e
            # a malformed fault spec must never take down serving — faults
            # are a debugging tool, not a dependency
            import logging

            logging.getLogger(__name__).warning(
                "ignoring malformed MST_FAULTS entry %r", part
            )


if os.environ.get("MST_FAULTS_STRICT", "").lower() in ("1", "true", "yes"):
    set_strict(True)
if os.environ.get("MST_FAULTS"):
    _parse_env(os.environ["MST_FAULTS"])
