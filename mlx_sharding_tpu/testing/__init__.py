"""Test-support utilities shipped with the package (fault injection)."""
