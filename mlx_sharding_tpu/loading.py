"""Checkpoint loading.

TPU-native counterpart of the reference's loader (ref: shard/utils.py:33-68):
resolve a local path or HF repo, read ``config.json``, inject the pipeline
bounds ``start_layer``/``end_layer`` (ref: shard/utils.py:36-39), read every
``*.safetensors``, drop out-of-stage weights (the reference's per-model
``sanitize``, ref: shard/server/model/llama.py:92-107), dequantize MLX
grouped-quant triples when ``config.quantization`` is present
(ref: shard/utils.py:54-65), and hand the result to the model's weight mapper
which transposes/stacks into the scan-ready pytree.
"""

from __future__ import annotations

import json
import re
from pathlib import Path
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from mlx_sharding_tpu.models import build_model
from mlx_sharding_tpu.ops.quant import dequantize

LAYER_RE = re.compile(r"(?:model\.)?layers\.(\d+)\.")


def get_model_path(path_or_repo: str, revision: Optional[str] = None) -> Path:
    """Local directory, else HF hub snapshot (ref: mlx_lm.get_model_path used
    at shard/utils.py:34)."""
    p = Path(path_or_repo)
    if p.exists():
        return p
    from huggingface_hub import snapshot_download

    return Path(
        snapshot_download(
            repo_id=path_or_repo,
            revision=revision,
            # params/** covers native (Orbax) checkpoints uploaded to a repo —
            # the marker alone matching *.json must not strand the payload.
            allow_patterns=[
                "*.json", "*.safetensors", "*.model", "tokenizer*", "params/**",
            ],
        )
    )


def checkpoint_signature(
    path_or_repo: str, *, keep_quantized: bool = False
) -> str:
    """Stable content identity of a checkpoint for ``weights.WeightKey``:
    the resolved on-disk path plus the quantization config and whether the
    load keeps packed triples resident. Two replicas may alias one resident
    tree only when this string matches — same files, same dequant decisions,
    same in-memory layout."""
    path = get_model_path(path_or_repo)
    quant = None
    cfg = path / "config.json"
    if cfg.exists():
        with open(cfg) as f:
            quant = json.load(f).get("quantization")
    if quant:
        qsig = (
            f"gs{int(quant.get('group_size', 64))}"
            f"b{int(quant.get('bits', 4))}"
        )
        packed = "packed" if keep_quantized else "dense"
    else:
        qsig, packed = "dense", "dense"
    return f"{path.resolve()}::{qsig}::{packed}"


def load_config(
    model_path: Path,
    start_layer: Optional[int] = None,
    end_layer: Optional[int] = None,
) -> dict:
    with open(model_path / "config.json") as f:
        config = json.load(f)
    # Dynamic sharding: bounds from the CLI override whatever the checkpoint
    # baked in (ref: shard/utils.py:36-39).
    if start_layer is not None:
        config["start_layer"] = start_layer
    if end_layer is not None:
        config["end_layer"] = end_layer
    return config


def load_raw_weights(model_path: Path) -> dict[str, jnp.ndarray]:
    """Read every *.safetensors in the directory (ref: shard/utils.py:40-45).
    framework="flax" so bf16 tensors load without a numpy detour."""
    from safetensors import safe_open

    files = sorted(model_path.glob("*.safetensors"))
    if not files:
        raise FileNotFoundError(f"No safetensors found in {model_path}")
    weights: dict[str, jnp.ndarray] = {}
    for file in files:
        with safe_open(file, framework="flax") as f:
            for k in f.keys():
                weights[k] = f.get_tensor(k)
    return weights


def dequantize_weights(
    weights: dict[str, jnp.ndarray],
    quantization: dict,
    dtype=jnp.bfloat16,
    keep_packed_layers: bool = False,
    keep_dense_re: str | None = None,
) -> dict[str, jnp.ndarray]:
    """Process every MLX ``{weight, scales, biases}`` triple. Default:
    collapse to a dense weight — mirrors the predicate the reference feeds
    nn.quantize, a param is quantized iff its ``.scales`` sibling exists
    (shard/utils.py:58-63). With ``keep_packed_layers``, decoder-layer
    projections AND the vocab pair (embed_tokens / lm_head — published
    4-bit checkpoints quantize them too, and the head matmul is the largest
    dense per-token read) stay packed as ``{q, scales, biases}`` dicts for
    the fused dequant-matmul path; norms are still dequantized.
    ``keep_dense_re`` (model.packed_keep_dense_re) names layer weights that
    are consumed as tensors, not matmul operands — those dequantize even in
    packed mode (MoE routers, MLA kv_b under the compressed cache)."""
    group_size = int(quantization.get("group_size", 64))
    bits = int(quantization.get("bits", 4))
    dense_re = re.compile(keep_dense_re) if keep_dense_re else None
    out: dict = {}
    for name, value in weights.items():
        base, _, leaf = name.rpartition(".")
        if leaf in ("scales", "biases"):
            continue  # consumed alongside their .weight
        if leaf == "weight" and f"{base}.scales" in weights:
            if (
                keep_packed_layers
                and (
                    LAYER_RE.search(name)
                    or "embed_tokens" in name
                    or "lm_head" in name
                )
                and not (dense_re and dense_re.search(name))
            ):
                # scales/biases stay in the checkpoint dtype (fp16 for
                # published 4-bit checkpoints) — both matmul paths cast to
                # f32 on the fly, and f32 residency would add ~11% to the
                # weight bytes streamed per decode step for nothing
                out[name] = {
                    "q": value,
                    "scales": weights[f"{base}.scales"],
                    "biases": weights[f"{base}.biases"],
                }
                continue
            value = dequantize(
                value,
                weights[f"{base}.scales"],
                weights[f"{base}.biases"],
                group_size,
                bits,
                dtype,
            )
        out[name] = value
    return out


def filter_stage_weights(
    weights: dict[str, jnp.ndarray], config
) -> dict[str, jnp.ndarray]:
    """Sanitize-by-range (ref: shard/server/model/llama.py:92-107 and
    sharding_weight.py:16-24): keep layers in [start, end); embedding only
    where the stage needs it; final norm + head only on the last stage.
    Rotary inv_freq buffers are always dropped."""
    kept: dict[str, jnp.ndarray] = {}
    for name, value in weights.items():
        if "rotary_emb.inv_freq" in name:
            continue
        m = LAYER_RE.search(name)
        if m:
            if config.start_layer <= int(m.group(1)) < config.end_layer:
                kept[name] = value
            continue
        if "embed_tokens" in name:
            if config.needs_embed:
                kept[name] = value
            continue
        if name.startswith(("model.norm", "norm.")) or "lm_head" in name:
            if config.needs_head:
                kept[name] = value
            continue
        kept[name] = value
    return kept


def load_model(
    path_or_repo: str,
    start_layer: Optional[int] = None,
    end_layer: Optional[int] = None,
    dtype=jnp.bfloat16,
    keep_quantized: bool = False,
):
    """Full load path (ref: shard/utils.py:33-68). Returns (model, params).
    Native (Orbax) checkpoints are detected and restored directly.
    ``keep_quantized`` keeps 4-bit decoder-layer weights packed in HBM
    (fused dequant-matmul) on architectures that support it."""
    model_path = get_model_path(path_or_repo)
    from mlx_sharding_tpu.checkpoint import is_native_checkpoint, load_native_checkpoint

    if is_native_checkpoint(model_path):
        if keep_quantized:
            raise ValueError(
                "keep_quantized is not supported for native (Orbax) "
                "checkpoints — they store dense weights"
            )
        return load_native_checkpoint(model_path, start_layer, end_layer, dtype=dtype)
    config_dict = load_config(model_path, start_layer, end_layer)
    model, config = build_model(config_dict)
    if keep_quantized and not getattr(model, "supports_packed", False):
        raise ValueError(
            f"keep_quantized is not supported for {type(model).__name__}"
        )
    if keep_quantized and config.quantization is None:
        # a silent dense load would quietly cost 4x the expected HBM
        raise ValueError(
            "keep_quantized requires a quantized checkpoint "
            "(no 'quantization' key in config.json)"
        )
    weights = load_raw_weights(model_path)
    if config.quantization is not None:
        weights = dequantize_weights(
            weights, config.quantization, dtype,
            keep_packed_layers=keep_quantized,
            keep_dense_re=model.packed_keep_dense_re(),
        )
    weights = filter_stage_weights(weights, config)
    params = model.map_weights(weights, dtype)
    # paths that must materialize dense values from packed params (embed
    # row dequant) produce this dtype, so packed and dense loads agree
    model.compute_dtype = dtype
    return model, params


# ---------------------------------------------------------------------------
# Helpers for the per-model weight mappers


def fetch_weight(weights: dict, key: str, dtype, transpose: bool = True):
    """One checkpoint tensor, packed-or-dense: a packed ``{q, scales,
    biases}`` triple passes through untouched (it keeps MLX's (out, in)
    orientation — the fused dequant-matmul contracts against it); a dense
    array is cast and, for projections, transposed to (in, out) for
    ``x @ W``. The single fetch convention for every model's weight mapper."""
    w = weights[key]
    if isinstance(w, dict):
        return w
    w = jnp.asarray(w, dtype)
    return w.T if transpose else w


def stack_tree(items: list):
    """Stack a list of same-structure packed-or-dense entries on a new
    leading axis: a plain array is a single-leaf tree, a packed triple
    stacks per leaf into {q: (N, …), scales: (N, …), biases: (N, …)}."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *items)


def collect_layer_stack(
    weights: dict[str, jnp.ndarray],
    config,
    per_layer_names: dict[str, tuple[str, bool]],
    dtype,
) -> dict[str, jnp.ndarray]:
    """{hf_suffix → (our_name, transpose)} applied across the stage's layer
    range and stacked on a leading axis (global HF indices
    start_layer..end_layer map to stack rows 0..L)."""
    stacked: dict[str, list] = {our: [] for our, _ in per_layer_names.values()}
    for i in range(config.start_layer, config.end_layer):
        for hf_suffix, (our_name, transpose) in per_layer_names.items():
            key = f"model.layers.{i}.{hf_suffix}"
            if key not in weights:
                key = f"layers.{i}.{hf_suffix}"
            stacked[our_name].append(fetch_weight(weights, key, dtype, transpose))
    return {k: stack_tree(v) for k, v in stacked.items()}


def first_key(weights: dict, *candidates: str):
    for c in candidates:
        if c in weights:
            return weights[c]
    raise KeyError(f"none of {candidates} present in checkpoint")


def vocab_param(value, dtype, transpose: bool = False):
    """Embed table / LM head param: packed triples (keep-quantized loads)
    stay in MLX (V, …) orientation — base.embed_tokens/apply_head consume
    them directly; dense arrays cast (and for untied heads transpose to the
    (H, V) matmul orientation)."""
    if isinstance(value, dict):
        return value
    value = jnp.asarray(value, dtype)
    return value.T if transpose else value
