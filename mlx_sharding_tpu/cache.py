"""Functional, preallocated KV cache.

TPU-native replacement for the reference's growable per-layer ``KVCache``
objects (ref: shard/server/server.py:9-10,22; shard/utils.py:142-150). The
reference mutates a Python-global list of caches per RPC; on TPU that would
force re-compilation and host round-trips, so instead the cache is a pytree of
fixed-capacity HBM buffers carried through the jitted step function and
updated with ``lax.dynamic_update_slice`` — donated each step so XLA updates
in place.

Layout: keys/values are stacked across the stage's local layers:
    k, v : (num_layers, batch, max_seq, n_kv_heads, head_dim)
plus a scalar ``offset`` (the reference's ``KVCache.offset``, used for the
causal-mask shift at shard/server/model/llama.py:48-53).

MLA models cache differently-shaped tensors (tuple head dims,
ref: shard/server/model/deepseek_v2.py:120-125); they use the same structure
with their own head dims per tensor.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class KVCache(NamedTuple):
    k: jax.Array  # (L, B, S, H_kv, D_k) — or {"d": int8, "s": f32} (paged int8)
    v: jax.Array  # (L, B, S, H_kv, D_v) — same
    offset: jax.Array  # scalar int32 — number of valid positions

    @property
    def max_seq(self) -> int:
        return kv_data(self.k).shape[2]

    @property
    def num_layers(self) -> int:
        return kv_data(self.k).shape[0]


def is_quantized_kv(buf) -> bool:
    """True for an int8 KV buffer: ``{"d": int8 data, "s": float scales}``
    with the scale's trailing dim 1 broadcasting over head_dim."""
    return isinstance(buf, dict) and "d" in buf


def kv_data(buf) -> jax.Array:
    """The data leaf of a KV buffer — the int8 payload for quantized pools,
    the array itself otherwise. Shape-only bookkeeping (page counts, slot
    geometry) reads this so it never cares about the storage mode."""
    return buf["d"] if is_quantized_kv(buf) else buf


def quantize_kv_rows(rows: jax.Array) -> dict:
    """(…, H, D) float rows → ``{"d": int8, "s": f32 (…, H, 1)}`` with a
    per-row-per-head symmetric scale ``max|x| / 127``.

    Per-ROW scales (not per-page) are deliberate: ragged decode writes one
    row into a page per tick, and a per-page scale would force a read-
    modify-write rescale of the other rows on every write. Rows are
    independent — writeback, scatter, and rewind all stay pure writes."""
    x = rows.astype(jnp.float32)
    s = jnp.max(jnp.abs(x), axis=-1, keepdims=True) / 127.0
    s = jnp.maximum(s, 1e-12)
    d = jnp.clip(jnp.round(x / s), -127, 127).astype(jnp.int8)
    return {"d": d, "s": s.astype(jnp.float32)}


def dequantize_kv(buf, dtype=jnp.float32) -> jax.Array:
    """Inverse of :func:`quantize_kv_rows`; passes dense buffers through
    (after a dtype cast) so call sites handle both storage modes."""
    if not is_quantized_kv(buf):
        return buf.astype(dtype)
    return (buf["d"].astype(jnp.float32) * buf["s"]).astype(dtype)


def init_cache(
    num_layers: int,
    batch: int,
    max_seq: int,
    n_kv_heads: int,
    head_dim,
    dtype=jnp.bfloat16,
) -> KVCache:
    """Allocate an empty cache. ``head_dim`` may be an int or a
    ``(k_dim, v_dim)`` tuple for MLA (ref: deepseek_v2.py:120-125)."""
    if isinstance(head_dim, (tuple, list)):
        k_dim, v_dim = head_dim
    else:
        k_dim = v_dim = head_dim
    return KVCache(
        k=jnp.zeros((num_layers, batch, max_seq, n_kv_heads, k_dim), dtype),
        v=jnp.zeros((num_layers, batch, max_seq, n_kv_heads, v_dim), dtype),
        offset=jnp.zeros((), jnp.int32),
    )


def write_layer_kv(
    k_buf: jax.Array,
    v_buf: jax.Array,
    k_new: jax.Array,
    v_new: jax.Array,
    offset: jax.Array,
) -> tuple[jax.Array, jax.Array]:
    """Write ``k_new``/``v_new`` (B, T, H_kv, D) into one layer's
    full-capacity buffers (B, S, H_kv, D) at position ``offset``.

    Used inside the per-layer body of the ``lax.scan`` over stacked layers:
    the scan consumes ``cache.k``/``cache.v`` as per-layer xs and re-stacks
    the returned buffers as ys, so no dynamic indexing on the layer axis is
    ever needed. The shared ``offset`` counter is advanced once per step by
    :func:`advance` (as in the reference, every layer's cache grows in
    lockstep)."""
    zero = jnp.zeros((), jnp.int32)
    k = jax.lax.dynamic_update_slice(k_buf, k_new.astype(k_buf.dtype), (zero, offset, zero, zero))
    v = jax.lax.dynamic_update_slice(v_buf, v_new.astype(v_buf.dtype), (zero, offset, zero, zero))
    return k, v


def advance(cache: KVCache, n_tokens) -> KVCache:
    return cache._replace(offset=cache.offset + jnp.asarray(n_tokens, jnp.int32))


def check_capacity(cache: KVCache, n_new: int) -> None:
    """Host-side guard: ``dynamic_update_slice`` clamps out-of-range starts,
    which would silently overwrite valid entries rather than error. Call this
    outside jit (the generate loop does) before writing ``n_new`` tokens."""
    offset = int(cache.offset)
    if offset + n_new > cache.max_seq:
        raise ValueError(
            f"KV cache overflow: offset {offset} + {n_new} new tokens exceeds "
            f"capacity {cache.max_seq}. Allocate a larger max_seq."
        )


def reset(cache: KVCache) -> KVCache:
    """Equivalent of the reference's ResetCache RPC (shard/server/server.py:59-71):
    invalidate without reallocating."""
    return cache._replace(offset=jnp.zeros((), jnp.int32))


def export_pool_pages(cache: KVCache, page_ids: jax.Array):
    """Gather pool pages out of a paged cache's k/v buffers.

    ``page_ids`` is an int32 vector of pool-page indices; the paged pool
    layout puts the pool axis at position 2 of every leaf
    ``(S, L, pool_pages+1, B, page, H, D)``, so a ``take`` along axis 2
    lifts a request's page chain out of the pool in one gather per leaf —
    int8 pools (``{"d", "s"}`` dicts) come through ``jax.tree`` with their
    scales attached, which is what makes the exported block a faithful
    copy of the quantized codes rather than a lossy dequant/requant trip.

    Pure and jittable: callers jit it once and reuse the program per page
    count. Returns ``(k_pages, v_pages)`` pytrees shaped like the pool
    leaves with the pool axis narrowed to ``len(page_ids)``."""
    take = lambda leaf: jnp.take(leaf, page_ids, axis=2)  # noqa: E731
    return jax.tree.map(take, cache.k), jax.tree.map(take, cache.v)


def import_pool_pages(
    cache: KVCache, k_pages, v_pages, page_ids: jax.Array
) -> KVCache:
    """Scatter previously exported page payloads into pool pages
    ``page_ids`` of a paged cache — the inverse of
    :func:`export_pool_pages`. The payload leaves may be host (numpy)
    arrays from a spilled block or device arrays from a live one; dtypes
    are cast to the pool's (a bf16→bf16 or int8→int8 identity in
    practice — cross-mode imports are rejected before this call by
    ``KVPageBlock.compatible_with``).

    Residency note: when the leaves are host numpy, the ``jnp.asarray``
    below IS the demand-paged host→device marshal — the stall the
    scheduler's prefetch path avoids by handing this function
    ``KVPageBlock.payload()`` device arrays staged ahead of the resume
    tick (then the asarray is an identity and the jitted scatter runs
    against buffers already on device)."""

    def put(pool, blk):
        return pool.at[:, :, page_ids].set(jnp.asarray(blk).astype(pool.dtype))

    return cache._replace(
        k=jax.tree.map(put, cache.k, k_pages),
        v=jax.tree.map(put, cache.v, v_pages),
    )


def rewind_slot_offset(cache: KVCache, slot, steps) -> KVCache:
    """Roll one slot's write offset back by ``steps`` positions (floored at
    0). ``offset`` must be the per-slot ``(M,)`` layout of the batched
    engines, not the scalar single-stream layout.

    Used by the async continuous batcher when reclaiming a slot that
    retired while a lookahead decode block was still in flight: the block's
    frozen active mask advanced the dead slot's offset up to one block past
    its true end, and the offset must not point past the pages being
    returned to the pool."""
    steps = jnp.asarray(steps, jnp.int32)
    new = jnp.maximum(cache.offset[slot] - steps, 0)
    return cache._replace(offset=cache.offset.at[slot].set(new))
