"""Token sampling — fully on-device, branchless, jit-fused into the decode step.

Semantic parity with the reference's sampler closure
(ref: shard/utils.py:126-139 — logit bias, argmax at temperature 0, top-p
else categorical) and its repetition penalty over a sliding token window
(ref: shard/utils.py:166-177). The TPU-native difference: everything here is
traced into the same XLA program as the model forward, with temperature /
top-p / penalty as *dynamic* scalars, so changing sampler settings never
recompiles and the only per-token host transfer is the sampled token id.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp


class SamplerParams(NamedTuple):
    """Dynamic sampler state — one pytree so it jits as leaves."""

    temperature: jax.Array  # scalar f32; 0 → greedy
    top_p: jax.Array  # scalar f32; 1 → full distribution
    repetition_penalty: jax.Array  # scalar f32; 1 → off
    bias_indices: jax.Array  # (K,) int32, pad with 0
    bias_values: jax.Array  # (K,) f32, pad with 0 (no-op)


def make_sampler_params(
    temperature: float = 0.0,
    top_p: float = 1.0,
    repetition_penalty: Optional[float] = None,
    logit_bias: Optional[dict[int, float]] = None,
    min_bias_slots: int = 16,
) -> SamplerParams:
    # Buffer sized to the request (rounded to a power of two so distinct bias
    # counts reuse a handful of compiled programs); every entry is applied —
    # the reference applies all of them too (shard/utils.py:128-131).
    n = len(logit_bias) if logit_bias else 0
    slots = max(min_bias_slots, 1 << (n - 1).bit_length() if n else 0)
    bias_idx = jnp.zeros((slots,), jnp.int32)
    bias_val = jnp.zeros((slots,), jnp.float32)
    if logit_bias:
        items = list(logit_bias.items())
        bias_idx = bias_idx.at[: len(items)].set(
            jnp.asarray([int(k) for k, _ in items], jnp.int32)
        )
        bias_val = bias_val.at[: len(items)].set(
            jnp.asarray([float(v) for _, v in items], jnp.float32)
        )
    return SamplerParams(
        temperature=jnp.asarray(temperature, jnp.float32),
        top_p=jnp.asarray(top_p, jnp.float32),
        repetition_penalty=jnp.asarray(
            1.0 if repetition_penalty is None else repetition_penalty, jnp.float32
        ),
        bias_indices=bias_idx,
        bias_values=bias_val,
    )


def apply_logit_bias(logits: jax.Array, indices: jax.Array, values: jax.Array):
    """Scatter-add biases. Padding entries have value 0 → no-op whatever the
    index (matches ref logit_bias semantics, shard/utils.py:128-131)."""
    return logits.at[..., indices].add(values)


def apply_repetition_penalty(
    logits: jax.Array, recent_tokens: jax.Array, penalty: jax.Array
) -> jax.Array:
    """Penalize tokens in ``recent_tokens`` (B, W), -1 = empty slot.

    Positive scores are divided by ``penalty``, negative multiplied — the
    standard CTRL-style rule the reference applies over its sliding window
    (shard/utils.py:166-177, via mlx_lm.apply_repetition_penalty)."""

    def one(logits_row, tokens_row):
        valid = tokens_row >= 0
        gather_idx = jnp.where(valid, tokens_row, 0)
        scores = logits_row[gather_idx]
        penalized = jnp.where(scores > 0, scores / penalty, scores * penalty)
        # Route empty slots out of bounds and drop them, so a padding slot can
        # never clobber a real token's penalized value (duplicate-index
        # scatter is last-write-wins).
        scatter_idx = jnp.where(valid, tokens_row, logits_row.shape[0])
        return logits_row.at[scatter_idx].set(penalized, mode="drop")

    return jax.vmap(one)(logits, recent_tokens)


def top_p_filter(logits: jax.Array, top_p: jax.Array) -> jax.Array:
    """Mask logits outside the top-p nucleus (ref: mlx_lm top_p_sampling used
    at shard/utils.py:136). Keeps the smallest prefix of the sorted
    distribution whose mass reaches ``top_p``; top_p >= 1 keeps everything.

    The full-vocab sort costs ~1ms/token at a 128K vocab on a v5e, so the
    whole filter sits behind a ``lax.cond`` — requests at the top_p=1
    default never pay for it. (Under vmap — the batched scheduler sampler —
    cond lowers to select and both branches run, same as before.)"""

    def nucleus(lo):
        sorted_logits = jnp.sort(lo, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep_sorted = (cum - probs) < top_p  # kept iff mass before it < top_p
        min_kept = jnp.min(
            jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1, keepdims=True
        )
        return jnp.where(lo >= min_kept, lo, -jnp.inf)

    return jax.lax.cond(top_p < 1.0, nucleus, lambda lo: lo, logits)


def transform_logits(
    logits: jax.Array,
    recent_tokens: Optional[jax.Array],
    params: SamplerParams,
) -> jax.Array:
    """bias → repetition penalty: the request-transformed logits every
    downstream consumer (greedy argmax, logprob reporting, nucleus
    sampling, speculative verification) derives from."""
    logits = apply_logit_bias(
        logits.astype(jnp.float32), params.bias_indices, params.bias_values
    )
    if recent_tokens is not None:
        logits = apply_repetition_penalty(
            logits, recent_tokens, params.repetition_penalty
        )
    return logits


def nucleus_logits(lo: jax.Array, params: SamplerParams) -> jax.Array:
    """Temperature then top-p on transformed logits — the log-domain
    (unnormalized) final sampling distribution of the sampled branch.
    Temperature first, THEN the nucleus cut: the kept set must be computed
    on the tempered distribution (matches mlx_lm top_p_sampling semantics
    used at ref shard/utils.py:136). Speculative rejection sampling defines
    both its p and q through this same function, which is what keeps its
    acceptance ratio aligned with what sample_token actually samples."""
    safe_temp = jnp.maximum(params.temperature, 1e-6)
    return top_p_filter(lo / safe_temp, params.top_p)


def sample_token(
    key: jax.Array,
    logits: jax.Array,  # (B, V) f32
    params: SamplerParams,
    recent_tokens: Optional[jax.Array] = None,  # (B, W) int32, -1 padded
) -> tuple[jax.Array, jax.Array]:
    """Returns (token (B,), logprobs (B, V)). Temperature / top-p are
    dynamic scalars, so one compiled program covers every request's sampler
    settings; the sampled branch (gumbel draw + nucleus sort) sits behind a
    ``lax.cond`` so greedy requests — the serving default — skip it."""
    logits = transform_logits(logits, recent_tokens, params)

    logprobs = jax.nn.log_softmax(logits, axis=-1)

    def sampled_fn(lo):
        filtered = nucleus_logits(lo, params)
        return jax.random.categorical(key, filtered, axis=-1).astype(jnp.int32)

    token = jax.lax.cond(
        params.temperature > 0,
        sampled_fn,
        lambda lo: jnp.argmax(lo, axis=-1).astype(jnp.int32),
        logits,
    )
    return token, logprobs


def stack_sampler_params(params_list: list[SamplerParams]) -> SamplerParams:
    """Per-request sampler params → one batched pytree with leading (B,)
    (bias buffers padded to a common width). Used by the continuous-batching
    scheduler, where every microbatch slot runs its own request with its own
    temperature/top-p/penalty/bias."""
    slots = max(p.bias_indices.shape[0] for p in params_list)

    def pad(p: SamplerParams) -> SamplerParams:
        n = p.bias_indices.shape[0]
        if n == slots:
            return p
        return p._replace(
            bias_indices=jnp.pad(p.bias_indices, (0, slots - n)),
            bias_values=jnp.pad(p.bias_values, (0, slots - n)),
        )

    return jax.tree.map(lambda *xs: jnp.stack(xs), *[pad(p) for p in params_list])


def set_sampler_slot(
    batched: SamplerParams, slot: int, one: SamplerParams
) -> SamplerParams:
    """Write one request's params into row ``slot`` of a batched pytree
    (bias buffers truncated/padded to the batched width)."""
    width = batched.bias_indices.shape[1]
    n = one.bias_indices.shape[0]
    if n < width:
        one = one._replace(
            bias_indices=jnp.pad(one.bias_indices, (0, width - n)),
            bias_values=jnp.pad(one.bias_values, (0, width - n)),
        )
    elif n > width:
        raise ValueError(
            f"logit_bias with {n} entries exceeds the scheduler's per-slot "
            f"bias width {width}"
        )
    return jax.tree.map(lambda full, x: full.at[slot].set(x), batched, one)


def transform_logits_batched(
    logits: jax.Array,  # (B, V)
    recent_tokens: jax.Array,  # (B, W) int32, -1 padded
    params: SamplerParams,  # every leaf with leading (B,)
) -> jax.Array:
    """Per-row bias → repetition penalty — the batched transform_logits
    (one continuous-batching slot per row)."""
    logits = logits.astype(jnp.float32)
    logits = jax.vmap(lambda l, i, v: l.at[i].add(v))(
        logits, params.bias_indices, params.bias_values
    )
    return jax.vmap(
        lambda l, r, p: apply_repetition_penalty(l[None], r[None], p)[0]
    )(logits, recent_tokens, params.repetition_penalty)


def nucleus_logits_batched(lo: jax.Array, params: SamplerParams) -> jax.Array:
    """Per-row temperature + top-p on transformed logits — the batched
    nucleus_logits; with transform_logits_batched it defines each slot's
    full sampling distribution (the p and q of batched speculative
    rejection sampling)."""
    safe_temp = jnp.maximum(params.temperature, 1e-6)[:, None]
    return jax.vmap(top_p_filter)(lo / safe_temp, params.top_p)


def sample_token_batched(
    keys: jax.Array,  # (B, 2) uint32 — one PRNG key per row
    logits: jax.Array,  # (B, V) f32
    params: SamplerParams,  # every leaf with leading (B,)
    recent_tokens: jax.Array,  # (B, W) int32, -1 padded
) -> tuple[jax.Array, jax.Array]:
    """Per-row sampling with per-row params and per-row PRNG keys — each
    continuous-batching slot behaves exactly like a solo request with that
    seed, so draining a slot and re-running the request serially reproduces
    its tokens."""
    logits = transform_logits_batched(logits, recent_tokens, params)

    logprobs = jax.nn.log_softmax(logits, axis=-1)
    greedy = jnp.argmax(logits, axis=-1)
    filtered = nucleus_logits_batched(logits, params)
    sampled = jax.vmap(lambda k, l: jax.random.categorical(k, l))(keys, filtered)
    token = jnp.where(params.temperature > 0, sampled, greedy)
    return token.astype(jnp.int32), logprobs


def update_recent_tokens(recent: jax.Array, token: jax.Array) -> jax.Array:
    """Shift the (B, W) window left and append the new token — the device-side
    version of the reference's ``repetition_context`` deque trim
    (shard/utils.py:171-177)."""
    return jnp.concatenate([recent[:, 1:], token[:, None]], axis=1)


def init_recent_tokens(batch: int, window: int, prompt=None) -> jax.Array:
    """Start the window from the prompt tail so the penalty applies to prompt
    content immediately (ref seeds repetition_context from the prompt,
    shard/utils.py:152-155). ``prompt``: optional (B, T) array-like."""
    recent = jnp.full((batch, window), -1, jnp.int32)
    if prompt is not None:
        import numpy as _np

        tail = _np.asarray(prompt, _np.int32)[:, -window:]
        recent = recent.at[:, window - tail.shape[1] :].set(jnp.asarray(tail))
    return recent
