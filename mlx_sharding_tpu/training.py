"""Training step (next-token LM objective) with multi-axis sharding.

The reference is inference-only (SURVEY §1: "no training"); this module
exists because a TPU framework's mesh story must cover the update path too:
parameters carry tensor-parallel specs (column/row split, parallel/tp.py)
with the stacked-layer axis placed on ``pp``, the batch on ``dp`` and the
sequence on ``sp`` — all as GSPMD sharding constraints on one jitted
value_and_grad + optax step, letting XLA place the collectives (psum for TP
partials and DP gradient reduction) on ICI.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mlx_sharding_tpu.parallel.mesh import AXIS_DP, AXIS_PP, AXIS_SP, AXIS_TP
from mlx_sharding_tpu.parallel.tp import llama_param_specs, prune_specs


def lm_loss(model, params, tokens):
    """Mean next-token cross-entropy. Runs the same stage body as inference
    (a throwaway full-length cache keeps one code path)."""
    b, t = tokens.shape
    cache = model.make_cache(b, t, jnp.float32)
    logits, _ = model(params, tokens, cache)
    logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
    tgt = tokens[:, 1:]
    nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
    return nll.mean()


class TrainState(NamedTuple):
    params: dict
    opt_state: optax.OptState
    step: jax.Array


def make_train_step(model, optimizer, mesh: Mesh, param_specs=None):
    """Returns (init_fn, step_fn), both jitted with NamedShardings so every
    tensor lives where its spec says — params split over (pp, tp), data over
    (dp, sp)."""
    if param_specs is None:
        param_specs = llama_param_specs(tp=AXIS_TP, layers=AXIS_PP)

    def init(params):
        specs = prune_specs(param_specs, params)
        shardings = jax.tree.map(
            lambda s: NamedSharding(mesh, s), specs,
            is_leaf=lambda x: isinstance(x, P),
        )
        params = jax.device_put(params, shardings)
        opt_state = optimizer.init(params)
        return TrainState(params, opt_state, jnp.zeros((), jnp.int32))

    data_sharding = NamedSharding(mesh, P(AXIS_DP, AXIS_SP))

    @partial(jax.jit, donate_argnums=(0,))
    def step(state: TrainState, tokens):
        tokens = jax.lax.with_sharding_constraint(tokens, data_sharding)
        loss, grads = jax.value_and_grad(partial(lm_loss, model))(
            state.params, tokens
        )
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss

    return init, step
