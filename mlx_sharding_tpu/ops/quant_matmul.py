"""Pallas fused dequant-matmul: 4-bit weights stay packed in HBM.

Round 1 dequantized MLX grouped-quant checkpoints to dense bf16 at load —
correct, but it forfeits the point of 4-bit weights on the decode path,
which is BANDWIDTH: decode is HBM-bound, and streaming 4-bit words + one
scale/bias pair per 64 weights moves ~4x fewer bytes than bf16 (SURVEY §7
"hard part (a)"; ROADMAP r1 queue item). This kernel keeps the packed
``{q, scales, biases}`` triple resident and fuses unpack → affine →
matmul inside VMEM.

Structure — shaped by what Mosaic actually compiles on a v5e (dynamic
lane-dim slices and lane-merging reshapes are both rejected by the layout
inference, so neither an in-kernel ``fori_loop`` over the reduction nor a
``(out, words, 8) → (out, in)`` unpack reshape can be used):

- 3-D grid (M tiles, OUT tiles, IN blocks); the IN axis is a sequential
  reduction dimension — partials accumulate into an fp32 VMEM scratch,
  written to the output tile on the last IN step.
- The unpack never materializes an (out, in) tile. Each uint32 word holds 8
  nibbles; the kernel processes 8 *nibble planes* ``(q >> 4j) & 0xF`` of
  shape (out, words) and runs one MXU sub-dot per plane against the
  matching activation plane. The activations arrive pre-permuted to
  word-major order (x_r[m, j, w] = x[m, 8w + j], a cheap XLA transpose
  traced into the surrounding program), so every sub-dot is a plain
  lane-contraction.
- Per-group scales/biases expand group→word lanes via a tiny iota-built
  0/1 matrix on the MXU (E[g, w] = [w//8 == g]) — broadcast+reshape lane
  expansion is exactly the shape cast Mosaic rejects. The bias term folds
  into one extra sub-dot against the plane-summed activations:
  ``out += Σ_j x_j @ (nib_j · s_w)ᵀ + (Σ_j x_j) @ b_wᵀ``.

Layout contract is exactly the checkpoint's (mlx.core.quantize,
ref shard/utils.py:54-65): ``q`` (out, in*bits/32) LSB-first nibbles,
``scales``/``biases`` (out, in/group_size) — validated bit-exactly by
tests/test_quant_golden.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_M = 128
DEFAULT_BLOCK_OUT = 128
# IN-blocks must keep the packed-word lane dim 128-aligned: 1024 inputs =
# 128 uint32 words. Smaller/indivisible IN dims run as one whole block.
DEFAULT_BLOCK_IN = 1024

# Per-program VMEM budget for the adaptive block picker. Decode-shape
# profiling on the v5e showed per-program overhead dominating at the old
# 128x128x1024 blocks (a (8192, 3072) matvec = 192 programs of ~72KB of
# packed bytes each ran 8x off the bandwidth roofline) — so blocks grow
# until the q tile + its fp32 expansion scratch fill a healthy VMEM slice.
_VMEM_BUDGET_BYTES = 6 * 1024 * 1024


def pick_block_in(in_dim: int, cap: int = 8192) -> int:
    """IN block: the whole (unpartitioned) dim is always lane-legal and
    maximizes bytes per program; partition only when the dim is too large,
    in 1024-input steps (128 uint32 word lanes)."""
    if in_dim <= cap or in_dim % DEFAULT_BLOCK_IN:
        return in_dim
    best = DEFAULT_BLOCK_IN
    d = DEFAULT_BLOCK_IN
    while d <= cap:
        if in_dim % d == 0:
            best = d
        d += DEFAULT_BLOCK_IN
    return best


def pick_block_out(out_dim: int, words: int, block_m: int = 1, per_word: int = 8) -> int:
    """Largest divisor of OUT (a multiple of 128, or the whole dim) whose
    working set fits the per-program VMEM budget: per out row ~16 bytes per
    word lane (q 4 + s_w/b_w 8 + one nibble plane 4), plus the activation
    tile and accumulator scaling with block_m."""
    fixed = block_m * (words * per_word + words) * 4  # x_r tile + x_sum
    limit = max((_VMEM_BUDGET_BYTES - fixed) // (16 * words + 4 * block_m), 128)
    if out_dim <= limit:
        return out_dim
    best = None
    d = 128
    while d <= limit:
        if out_dim % d == 0:
            best = d
        d += 128
    return best if best is not None else min(out_dim, DEFAULT_BLOCK_OUT)


def _kernel(x_ref, q_ref, s_ref, b_ref, o_ref, acc_ref, *, bits, group_size):
    per_word = 32 // bits
    mask = (1 << bits) - 1
    bo, words = q_ref.shape
    gpb = s_ref.shape[-1]
    wpg = group_size // per_word  # words per quant group

    @pl.when(pl.program_id(2) == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # group→word lane expansion on the MXU: E[g, w] = [w // wpg == g]
    gi = jax.lax.broadcasted_iota(jnp.int32, (gpb, words), 0)
    wi = jax.lax.broadcasted_iota(jnp.int32, (gpb, words), 1)
    expand = (wi // wpg == gi).astype(jnp.float32)
    dot = functools.partial(
        jax.lax.dot_general, preferred_element_type=jnp.float32
    )
    contract_last = (((1,), (1,)), ((), ()))
    s_w = dot(s_ref[0].astype(jnp.float32), expand, (((1,), (0,)), ((), ())))
    b_w = dot(b_ref[0].astype(jnp.float32), expand, (((1,), (0,)), ((), ())))

    wq = q_ref[...]  # (bo, words) uint32
    acc = acc_ref[...]
    x_sum = jnp.zeros((x_ref.shape[0], words), jnp.float32)
    for j in range(per_word):
        # nibbles are 0..15: the int32 detour is exact (no uint32→f32 cast
        # exists in Mosaic)
        nib = ((wq >> (j * bits)) & mask).astype(jnp.int32).astype(jnp.float32)
        xj = x_ref[:, j, :].astype(jnp.float32)  # (bm, words)
        acc = acc + dot(xj, nib * s_w, contract_last)
        x_sum = x_sum + xj
    acc_ref[...] = acc + dot(x_sum, b_w, contract_last)

    @pl.when(pl.program_id(2) == pl.num_programs(2) - 1)
    def _done():
        o_ref[...] = acc_ref[...].astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("group_size", "bits", "block_m", "block_out", "block_in",
                     "interpret"),
)
def quant_matmul_pallas(
    x: jax.Array,  # (M, IN)
    q: jax.Array,  # (OUT, IN * bits / 32) uint32
    scales: jax.Array,  # (OUT, IN / group_size)
    biases: jax.Array,  # (OUT, IN / group_size)
    *,
    group_size: int = 64,
    bits: int = 4,
    block_m: int = DEFAULT_BLOCK_M,
    block_out: int | None = None,
    block_in: int | None = None,
    interpret: bool = False,
) -> jax.Array:
    """x @ dequant(q, scales, biases).T without materializing the dense
    weight. M and OUT must divide by their block sizes; IN by block_in."""
    m, in_dim = x.shape
    out_dim = q.shape[0]
    per_word = 32 // bits
    block_m = min(block_m, m)
    if block_in is None:
        block_in = pick_block_in(in_dim)
    block_in = min(block_in, in_dim)
    if block_out is None:
        block_out = pick_block_out(out_dim, block_in // per_word, block_m, per_word)
    block_out = min(block_out, out_dim)
    if block_in % group_size or block_in % per_word:
        raise ValueError(
            f"block_in {block_in} must be a multiple of group_size "
            f"{group_size} and {per_word}"
        )
    if m % block_m or out_dim % block_out or in_dim % block_in:
        raise ValueError(
            f"shapes (M={m}, OUT={out_dim}, IN={in_dim}) must divide block "
            f"sizes ({block_m}, {block_out}, {block_in})"
        )

    n_in = in_dim // block_in
    gpb = block_in // group_size
    words = block_in // per_word
    # (M, IN) → word-major planes: x_r[m, j, W] = x[m, 8W + j]
    x_r = x.reshape(m, in_dim // per_word, per_word).transpose(0, 2, 1)
    # (OUT, G) → (n_in, OUT, groups_per_block): gives every grid step a
    # statically-addressed scale block (lane dim = gpb, whole → legal)
    s3 = scales.reshape(out_dim, n_in, gpb).transpose(1, 0, 2)
    b3 = biases.reshape(out_dim, n_in, gpb).transpose(1, 0, 2)

    grid = (m // block_m, out_dim // block_out, n_in)
    return pl.pallas_call(
        functools.partial(_kernel, bits=bits, group_size=group_size),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_m, per_word, words), lambda mi, oi, ii: (mi, 0, ii)),
            pl.BlockSpec((block_out, words), lambda mi, oi, ii: (oi, ii)),
            pl.BlockSpec((1, block_out, gpb), lambda mi, oi, ii: (ii, oi, 0)),
            pl.BlockSpec((1, block_out, gpb), lambda mi, oi, ii: (ii, oi, 0)),
        ],
        out_specs=pl.BlockSpec((block_m, block_out), lambda mi, oi, ii: (mi, oi)),
        out_shape=jax.ShapeDtypeStruct((m, out_dim), x.dtype),
        scratch_shapes=[pltpu.VMEM((block_m, block_out), jnp.float32)],
        compiler_params=getattr(
            pltpu, "CompilerParams", getattr(pltpu, "TPUCompilerParams", None)
        )(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(x_r, q, s3, b3)
