"""Pallas fused dequant-matmul: 4-bit weights stay packed in HBM.

Round 1 dequantized MLX grouped-quant checkpoints to dense bf16 at load —
correct, but it forfeits the point of 4-bit weights on the decode path,
which is BANDWIDTH: decode is HBM-bound, and streaming 4-bit words + one
scale/bias pair per 64 weights moves ~4x fewer bytes than bf16 (SURVEY §7
"hard part (a)"; ROADMAP r1 queue item). This kernel keeps the packed
``{q, scales, biases}`` triple resident and fuses unpack → affine →
matmul inside VMEM.

Structure — shaped by what Mosaic actually compiles on a v5e (dynamic
lane-dim slices and lane-merging reshapes are both rejected by the layout
inference, so neither an in-kernel ``fori_loop`` over the reduction nor a
``(out, words, 8) → (out, in)`` unpack reshape can be used):

- 3-D grid (M tiles, OUT tiles, IN blocks); the IN axis is a sequential
  reduction dimension — partials accumulate into an fp32 VMEM scratch,
  written to the output tile on the last IN step.
- The unpack never materializes an (out, in) tile. Each uint32 word holds 8
  nibbles; the kernel processes 8 *nibble planes* ``(q >> 4j) & 0xF`` of
  shape (out, words) and runs one MXU sub-dot per plane against the
  matching activation plane. The activations arrive pre-permuted to
  word-major order (x_r[m, j, w] = x[m, 8w + j], a cheap XLA transpose
  traced into the surrounding program), so every sub-dot is a plain
  lane-contraction.
- Per-group scales/biases expand group→word lanes via a tiny iota-built
  0/1 matrix on the MXU (E[g, w] = [w//8 == g]) — broadcast+reshape lane
  expansion is exactly the shape cast Mosaic rejects. The bias term folds
  into one extra sub-dot against the plane-summed activations:
  ``out += Σ_j x_j @ (nib_j · s_w)ᵀ + (Σ_j x_j) @ b_wᵀ``.

Layout contract is exactly the checkpoint's (mlx.core.quantize,
ref shard/utils.py:54-65): ``q`` (out, in*bits/32) LSB-first nibbles,
``scales``/``biases`` (out, in/group_size) — validated bit-exactly by
tests/test_quant_golden.py.

Two kernels share that math:

- :func:`quant_matmul_pallas` — the 3-D-grid prefill/batch kernel above.
- :func:`quant_gemv_pipelined` — the decode (M ≤ 8) specialization. At
  M=1 the 3-D grid's per-program overhead dominates: each (OUT, IN) tile
  is one tiny MXU burst and the automatic pipeline re-fetches the scale
  blocks through their relayout. This kernel instead runs ONE grid step
  per OUT tile and streams the IN reduction through a manual
  double-buffered HBM→VMEM DMA pipeline (``pltpu.make_async_copy`` into
  2-slot scratch buffers): while the MXU chews IN-block ``i``, the DMAs
  for block ``i+1``'s packed words / scales / biases / activation planes
  are already in flight, so the sub-dots overlap the next tile's weight
  fetch instead of stalling on it. ``q``/``scales``/``biases`` are
  sliced straight out of their checkpoint layouts (no host-side
  relayout of multi-GB weight stacks); only the tiny activation is
  pre-permuted to word-major planes.

Block sizes come from :func:`get_gemv_blocks`: a shape-keyed autotune
cache (populated by :func:`autotune_gemv` — engines sweep each distinct
(OUT, IN) once at load on a real TPU and every same-shaped layer reuses
the winner) with the :func:`pick_decode_blocks` VMEM-fit heuristic as
the cold/CPU fallback.
"""

from __future__ import annotations

import functools
import os
import time

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_M = 128
DEFAULT_BLOCK_OUT = 128
# IN-blocks must keep the packed-word lane dim 128-aligned: 1024 inputs =
# 128 uint32 words. Smaller/indivisible IN dims run as one whole block.
DEFAULT_BLOCK_IN = 1024

# Per-program VMEM budget for the adaptive block picker. Decode-shape
# profiling on the v5e showed per-program overhead dominating at the old
# 128x128x1024 blocks (a (8192, 3072) matvec = 192 programs of ~72KB of
# packed bytes each ran 8x off the bandwidth roofline) — so blocks grow
# until the q tile + its fp32 expansion scratch fill a healthy VMEM slice.
_VMEM_BUDGET_BYTES = 6 * 1024 * 1024


def pick_block_in(in_dim: int, cap: int = 8192) -> int:
    """IN block: the whole (unpartitioned) dim is always lane-legal and
    maximizes bytes per program; partition only when the dim is too large,
    in 1024-input steps (128 uint32 word lanes)."""
    if in_dim <= cap or in_dim % DEFAULT_BLOCK_IN:
        return in_dim
    best = DEFAULT_BLOCK_IN
    d = DEFAULT_BLOCK_IN
    while d <= cap:
        if in_dim % d == 0:
            best = d
        d += DEFAULT_BLOCK_IN
    return best


def pick_block_out(out_dim: int, words: int, block_m: int = 1, per_word: int = 8) -> int:
    """Largest divisor of OUT (a multiple of 128, or the whole dim) whose
    working set fits the per-program VMEM budget: per out row ~16 bytes per
    word lane (q 4 + s_w/b_w 8 + one nibble plane 4), plus the activation
    tile and accumulator scaling with block_m."""
    fixed = block_m * (words * per_word + words) * 4  # x_r tile + x_sum
    limit = max((_VMEM_BUDGET_BYTES - fixed) // (16 * words + 4 * block_m), 128)
    if out_dim <= limit:
        return out_dim
    best = None
    d = 128
    while d <= limit:
        if out_dim % d == 0:
            best = d
        d += 128
    return best if best is not None else min(out_dim, DEFAULT_BLOCK_OUT)


def _kernel(x_ref, q_ref, s_ref, b_ref, o_ref, acc_ref, *, bits, group_size):
    per_word = 32 // bits
    mask = (1 << bits) - 1
    bo, words = q_ref.shape
    gpb = s_ref.shape[-1]
    wpg = group_size // per_word  # words per quant group

    @pl.when(pl.program_id(2) == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # group→word lane expansion on the MXU: E[g, w] = [w // wpg == g]
    gi = jax.lax.broadcasted_iota(jnp.int32, (gpb, words), 0)
    wi = jax.lax.broadcasted_iota(jnp.int32, (gpb, words), 1)
    expand = (wi // wpg == gi).astype(jnp.float32)
    dot = functools.partial(
        jax.lax.dot_general, preferred_element_type=jnp.float32
    )
    contract_last = (((1,), (1,)), ((), ()))
    s_w = dot(s_ref[0].astype(jnp.float32), expand, (((1,), (0,)), ((), ())))
    b_w = dot(b_ref[0].astype(jnp.float32), expand, (((1,), (0,)), ((), ())))

    wq = q_ref[...]  # (bo, words) uint32
    acc = acc_ref[...]
    x_sum = jnp.zeros((x_ref.shape[0], words), jnp.float32)
    for j in range(per_word):
        # nibbles are 0..15: the int32 detour is exact (no uint32→f32 cast
        # exists in Mosaic)
        nib = ((wq >> (j * bits)) & mask).astype(jnp.int32).astype(jnp.float32)
        xj = x_ref[:, j, :].astype(jnp.float32)  # (bm, words)
        acc = acc + dot(xj, nib * s_w, contract_last)
        x_sum = x_sum + xj
    acc_ref[...] = acc + dot(x_sum, b_w, contract_last)

    @pl.when(pl.program_id(2) == pl.num_programs(2) - 1)
    def _done():
        o_ref[...] = acc_ref[...].astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("group_size", "bits", "block_m", "block_out", "block_in",
                     "interpret"),
)
def quant_matmul_pallas(
    x: jax.Array,  # (M, IN)
    q: jax.Array,  # (OUT, IN * bits / 32) uint32
    scales: jax.Array,  # (OUT, IN / group_size)
    biases: jax.Array,  # (OUT, IN / group_size)
    *,
    group_size: int = 64,
    bits: int = 4,
    block_m: int = DEFAULT_BLOCK_M,
    block_out: int | None = None,
    block_in: int | None = None,
    interpret: bool = False,
) -> jax.Array:
    """x @ dequant(q, scales, biases).T without materializing the dense
    weight. M and OUT must divide by their block sizes; IN by block_in."""
    m, in_dim = x.shape
    out_dim = q.shape[0]
    per_word = 32 // bits
    block_m = min(block_m, m)
    if block_in is None:
        block_in = pick_block_in(in_dim)
    block_in = min(block_in, in_dim)
    if block_out is None:
        block_out = pick_block_out(out_dim, block_in // per_word, block_m, per_word)
    block_out = min(block_out, out_dim)
    if block_in % group_size or block_in % per_word:
        raise ValueError(
            f"block_in {block_in} must be a multiple of group_size "
            f"{group_size} and {per_word}"
        )
    if m % block_m or out_dim % block_out or in_dim % block_in:
        raise ValueError(
            f"shapes (M={m}, OUT={out_dim}, IN={in_dim}) must divide block "
            f"sizes ({block_m}, {block_out}, {block_in})"
        )

    n_in = in_dim // block_in
    gpb = block_in // group_size
    words = block_in // per_word
    # (M, IN) → word-major planes: x_r[m, j, W] = x[m, 8W + j]
    x_r = x.reshape(m, in_dim // per_word, per_word).transpose(0, 2, 1)
    # (OUT, G) → (n_in, OUT, groups_per_block): gives every grid step a
    # statically-addressed scale block (lane dim = gpb, whole → legal)
    s3 = scales.reshape(out_dim, n_in, gpb).transpose(1, 0, 2)
    b3 = biases.reshape(out_dim, n_in, gpb).transpose(1, 0, 2)

    grid = (m // block_m, out_dim // block_out, n_in)
    return pl.pallas_call(
        functools.partial(_kernel, bits=bits, group_size=group_size),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_m, per_word, words), lambda mi, oi, ii: (mi, 0, ii)),
            pl.BlockSpec((block_out, words), lambda mi, oi, ii: (oi, ii)),
            pl.BlockSpec((1, block_out, gpb), lambda mi, oi, ii: (ii, oi, 0)),
            pl.BlockSpec((1, block_out, gpb), lambda mi, oi, ii: (ii, oi, 0)),
        ],
        out_specs=pl.BlockSpec((block_m, block_out), lambda mi, oi, ii: (mi, oi)),
        out_shape=jax.ShapeDtypeStruct((m, out_dim), x.dtype),
        scratch_shapes=[pltpu.VMEM((block_m, block_out), jnp.float32)],
        compiler_params=getattr(
            pltpu, "CompilerParams", getattr(pltpu, "TPUCompilerParams", None)
        )(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(x_r, q, s3, b3)


# ---------------------------------------------------------------------------
# Decode GEMV: manual double-buffered DMA pipeline over the IN reduction.
# ---------------------------------------------------------------------------

#: VMEM ceiling for the decode double buffers (both slots + accumulator +
#: per-plane temporaries must fit alongside Mosaic's own scratch)
_GEMV_VMEM_BUDGET_BYTES = 8 * 1024 * 1024

#: decode specialization bound: above this M the 3-D-grid kernel's M-tiling
#: amortizes per-program overhead better than the single-M GEMV
GEMV_MAX_M = 8


def pick_decode_block_in(in_dim: int) -> int:
    """IN block for the pipelined GEMV. Prefer ≥ 2 IN blocks (a 1-block
    run has nothing to overlap) of 128-word-lane-aligned size; an
    indivisible dim runs as one whole block (correct, unpipelined)."""
    for cand in (4096, 2048, DEFAULT_BLOCK_IN):
        if in_dim % cand == 0 and in_dim // cand >= 2:
            return cand
    return in_dim


def pick_decode_blocks(
    m: int, out_dim: int, in_dim: int, group_size: int = 64, bits: int = 4
) -> tuple[int, int]:
    """(block_out, block_in) heuristic for the decode GEMV: block_in from
    :func:`pick_decode_block_in`, then the largest 128-multiple divisor of
    OUT whose TWO buffer slots (packed words + scales + biases + activation
    planes) and unpack temporaries fit the VMEM budget."""
    per_word = 32 // bits
    block_in = pick_decode_block_in(in_dim)
    words = block_in // per_word
    gpb = block_in // group_size
    # per out row, both slots: q 2·4 + s/b 2·2·4 bytes-per-lane, plus ~8
    # bytes/word of nibble-plane and scale-expansion temporaries
    per_row = words * (2 * 4 + 8) + gpb * 16
    fixed = 2 * m * per_word * words * 4 + m * 128 * 4  # x slots + acc tile
    limit = max((_GEMV_VMEM_BUDGET_BYTES - fixed) // per_row, 128)
    if out_dim <= limit:
        return out_dim, block_in
    best = None
    d = 128
    while d <= limit:
        if out_dim % d == 0:
            best = d
        d += 128
    return (best if best is not None else min(out_dim, DEFAULT_BLOCK_OUT),
            block_in)


def _gemv_kernel(
    x_hbm,  # (M, per_word, W_total) — stays in HBM (memory_space=ANY)
    q_hbm,  # (OUT, W_total) uint32 — checkpoint layout, HBM
    s_hbm,  # (OUT, G_total) — checkpoint layout, HBM
    b_hbm,  # (OUT, G_total) — checkpoint layout, HBM
    o_ref,  # (M, block_out) output tile
    xbuf,  # (2, M, per_word, words) VMEM double buffer
    qbuf,  # (2, block_out, words) VMEM double buffer
    sbuf,  # (2, block_out, gpb) VMEM double buffer
    bbuf,  # (2, block_out, gpb) VMEM double buffer
    sems,  # (4, 2) DMA semaphores: one per (operand, slot)
    *,
    bits: int,
    group_size: int,
    n_in: int,
    block_out: int,
):
    per_word = 32 // bits
    mask = (1 << bits) - 1
    words = qbuf.shape[-1]
    gpb = sbuf.shape[-1]
    wpg = group_size // per_word
    m = x_hbm.shape[0]
    o0 = pl.program_id(0) * block_out

    def copies(i, slot):
        """The four HBM→VMEM DMAs that land IN-block ``i`` in ``slot`` —
        sliced straight from the checkpoint layouts (2-D strided DMA), no
        relayout of the weight stack ever happens."""
        return (
            pltpu.make_async_copy(
                x_hbm.at[:, :, pl.ds(i * words, words)],
                xbuf.at[slot], sems.at[0, slot],
            ),
            pltpu.make_async_copy(
                q_hbm.at[pl.ds(o0, block_out), pl.ds(i * words, words)],
                qbuf.at[slot], sems.at[1, slot],
            ),
            pltpu.make_async_copy(
                s_hbm.at[pl.ds(o0, block_out), pl.ds(i * gpb, gpb)],
                sbuf.at[slot], sems.at[2, slot],
            ),
            pltpu.make_async_copy(
                b_hbm.at[pl.ds(o0, block_out), pl.ds(i * gpb, gpb)],
                bbuf.at[slot], sems.at[3, slot],
            ),
        )

    # warm-up: block 0's fetch starts before any compute
    for c in copies(0, 0):
        c.start()

    # group→word lane expansion (identical for every IN block)
    gi = jax.lax.broadcasted_iota(jnp.int32, (gpb, words), 0)
    wi = jax.lax.broadcasted_iota(jnp.int32, (gpb, words), 1)
    expand = (wi // wpg == gi).astype(jnp.float32)
    dot = functools.partial(
        jax.lax.dot_general, preferred_element_type=jnp.float32
    )
    contract_last = (((1,), (1,)), ((), ()))
    expand_c = (((1,), (0,)), ((), ()))

    def step(i, acc):
        slot = jax.lax.rem(i, 2)

        @pl.when(i + 1 < n_in)
        def _prefetch():
            # next block's DMAs go out BEFORE this block's wait: the MXU
            # sub-dots below overlap the i+1 weight fetch
            for c in copies(i + 1, jax.lax.rem(i + 1, 2)):
                c.start()

        for c in copies(i, slot):
            c.wait()

        s_w = dot(sbuf[slot].astype(jnp.float32), expand, expand_c)
        b_w = dot(bbuf[slot].astype(jnp.float32), expand, expand_c)
        wq = qbuf[slot]  # (block_out, words) uint32
        x_sum = jnp.zeros((m, words), jnp.float32)
        for j in range(per_word):
            nib = (
                ((wq >> (j * bits)) & mask)
                .astype(jnp.int32).astype(jnp.float32)
            )
            xj = xbuf[slot][:, j, :].astype(jnp.float32)  # (m, words)
            acc = acc + dot(xj, nib * s_w, contract_last)
            x_sum = x_sum + xj
        return acc + dot(x_sum, b_w, contract_last)

    acc = jax.lax.fori_loop(
        0, n_in, step, jnp.zeros((m, block_out), jnp.float32)
    )
    o_ref[...] = acc.astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("group_size", "bits", "block_out", "block_in",
                     "interpret"),
)
def quant_gemv_pipelined(
    x: jax.Array,  # (M, IN), M ≤ GEMV_MAX_M
    q: jax.Array,  # (OUT, IN * bits / 32) uint32
    scales: jax.Array,  # (OUT, IN / group_size)
    biases: jax.Array,  # (OUT, IN / group_size)
    *,
    group_size: int = 64,
    bits: int = 4,
    block_out: int | None = None,
    block_in: int | None = None,
    interpret: bool = False,
) -> jax.Array:
    """Decode-shape ``x @ dequant(q, scales, biases).T``: one grid step per
    OUT tile, IN reduced through the manual double-buffered DMA pipeline.
    Same nibble-plane math (and so the same float rounding) as
    :func:`quant_matmul_pallas` with one IN-block-sized sub-dot chain."""
    m, in_dim = x.shape
    out_dim = q.shape[0]
    per_word = 32 // bits
    if block_out is None or block_in is None:
        bo, bi = get_gemv_blocks(m, out_dim, in_dim, group_size, bits)
        block_out = block_out if block_out is not None else bo
        block_in = block_in if block_in is not None else bi
    block_out = min(block_out, out_dim)
    block_in = min(block_in, in_dim)
    if block_in % group_size or block_in % per_word:
        raise ValueError(
            f"block_in {block_in} must be a multiple of group_size "
            f"{group_size} and {per_word}"
        )
    if out_dim % block_out or in_dim % block_in:
        raise ValueError(
            f"shapes (OUT={out_dim}, IN={in_dim}) must divide block sizes "
            f"({block_out}, {block_in})"
        )

    n_in = in_dim // block_in
    words = block_in // per_word
    gpb = block_in // group_size
    # only the activation is relayouted: (M, IN) → word-major planes
    x_r = x.reshape(m, in_dim // per_word, per_word).transpose(0, 2, 1)

    any_spec = pl.BlockSpec(memory_space=pltpu.ANY)
    return pl.pallas_call(
        functools.partial(
            _gemv_kernel, bits=bits, group_size=group_size, n_in=n_in,
            block_out=block_out,
        ),
        grid=(out_dim // block_out,),
        in_specs=[any_spec, any_spec, any_spec, any_spec],
        out_specs=pl.BlockSpec((m, block_out), lambda oi: (0, oi)),
        out_shape=jax.ShapeDtypeStruct((m, out_dim), x.dtype),
        scratch_shapes=[
            pltpu.VMEM((2, m, per_word, words), x_r.dtype),
            pltpu.VMEM((2, block_out, words), jnp.uint32),
            pltpu.VMEM((2, block_out, gpb), scales.dtype),
            pltpu.VMEM((2, block_out, gpb), biases.dtype),
            pltpu.SemaphoreType.DMA((4, 2)),
        ],
        compiler_params=getattr(
            pltpu, "CompilerParams", getattr(pltpu, "TPUCompilerParams", None)
        )(
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )(x_r, q, scales, biases)


# ---------------------------------------------------------------------------
# Shape-keyed block autotune: sweep once per (OUT, IN) at load, reuse
# across every same-shaped layer. Replaces trusting the static VMEM-budget
# heuristic on real chips — the heuristic stays as the cold/CPU fallback.
# ---------------------------------------------------------------------------

#: (m_bucket, out_dim, in_dim, group_size, bits) → (block_out, block_in)
_GEMV_AUTOTUNE: dict[tuple, tuple[int, int]] = {}


def _m_bucket(m: int) -> int:
    """Decode Ms bucket to 1 (single stream) or GEMV_MAX_M (batched slots):
    block choice is insensitive within a bucket, and bucketing keeps the
    sweep count per shape at two."""
    return 1 if m == 1 else GEMV_MAX_M


def get_gemv_blocks(
    m: int, out_dim: int, in_dim: int, group_size: int = 64, bits: int = 4
) -> tuple[int, int]:
    """Measured blocks when :func:`autotune_gemv` has swept this shape,
    else the heuristic. Pure lookup — safe at trace time."""
    hit = _GEMV_AUTOTUNE.get(
        (_m_bucket(m), out_dim, in_dim, group_size, bits)
    )
    if hit is not None:
        return hit
    return pick_decode_blocks(m, out_dim, in_dim, group_size, bits)


def _gemv_candidates(
    m: int, out_dim: int, in_dim: int, group_size: int, bits: int
) -> list[tuple[int, int]]:
    h_out, h_in = pick_decode_blocks(m, out_dim, in_dim, group_size, bits)
    outs = {h_out}
    for d in (h_out // 2, h_out * 2, out_dim):
        if d and d % 128 == 0 and out_dim % d == 0:
            outs.add(d)
    ins = {h_in}
    for d in (1024, 2048, 4096, in_dim):
        if d and d % group_size == 0 and d % (32 // bits) == 0 and in_dim % d == 0:
            ins.add(d)
    return [(bo, bi) for bo in sorted(outs) for bi in sorted(ins)]


def autotune_gemv(
    m: int, out_dim: int, in_dim: int, group_size: int = 64, bits: int = 4,
    dtype=jnp.bfloat16, repeats: int = 3,
) -> tuple[int, int] | None:
    """Sweep candidate (block_out, block_in) pairs on synthetic operands and
    cache the fastest for this shape key. Engines call this once per
    distinct packed-projection shape at load (PipelineEngine.__init__);
    the decode dispatch then reuses the winner for every layer.

    Measured on a real TPU backend only — timing interpret-mode or CPU
    runs would tune for the wrong machine; those stay on the heuristic.
    Returns the winning pair, or None when not swept (non-TPU backend or
    MST_QMM_AUTOTUNE=0)."""
    key = (_m_bucket(m), out_dim, in_dim, group_size, bits)
    if key in _GEMV_AUTOTUNE:
        return _GEMV_AUTOTUNE[key]
    if os.environ.get("MST_QMM_AUTOTUNE", "1") == "0":
        return None
    if jax.default_backend() != "tpu":
        return None
    mb = key[0]
    per_word = 32 // bits
    x = jnp.zeros((mb, in_dim), dtype)
    qw = jnp.zeros((out_dim, in_dim // per_word), jnp.uint32)
    s = jnp.ones((out_dim, in_dim // group_size), jnp.float32)
    b = jnp.zeros((out_dim, in_dim // group_size), jnp.float32)
    best, best_t = None, float("inf")
    for bo, bi in _gemv_candidates(mb, out_dim, in_dim, group_size, bits):
        try:
            run = functools.partial(
                quant_gemv_pipelined, x, qw, s, b, group_size=group_size,
                bits=bits, block_out=bo, block_in=bi,
            )
            run().block_until_ready()  # compile outside the timed window
            t0 = time.perf_counter()
            for _ in range(repeats):
                out = run()
            out.block_until_ready()
            elapsed = time.perf_counter() - t0
        except Exception:
            continue  # candidate rejected by Mosaic/VMEM: skip, keep going
        if elapsed < best_t:
            best, best_t = (bo, bi), elapsed
    if best is not None:
        _GEMV_AUTOTUNE[key] = best
    return best
