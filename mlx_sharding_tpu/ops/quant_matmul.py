"""Pallas fused dequant-matmul: 4-bit weights stay packed in HBM.

Round 1 dequantized MLX grouped-quant checkpoints to dense bf16 at load —
correct, but it forfeits the point of 4-bit weights on the decode path,
which is BANDWIDTH: decode is HBM-bound, and streaming 4-bit words + one
scale/bias pair per 64 weights moves ~4x fewer bytes than bf16 (SURVEY §7
"hard part (a)"; ROADMAP r1 queue item). This kernel keeps the packed
``{q, scales, biases}`` triple resident and fuses unpack → affine →
matmul inside VMEM:

- grid over (M tiles, OUT tiles); the reduction dim streams through a
  ``fori_loop`` in ``block_in`` slices,
- each slice loads (block_out, block_in/8) uint32 words, unpacks 8 nibbles
  per word with broadcasted shifts (VPU), applies ``q * scale + bias`` per
  ``group_size`` column group, and feeds the MXU dot,
- accumulation in fp32, output cast to the activation dtype.

Layout contract is exactly the checkpoint's (mlx.core.quantize,
ref shard/utils.py:54-65): ``q`` (out, in*bits/32) LSB-first nibbles,
``scales``/``biases`` (out, in/group_size) — validated bit-exactly by
tests/test_quant_golden.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_M = 128
DEFAULT_BLOCK_OUT = 128
DEFAULT_BLOCK_IN = 512


def _kernel(
    x_ref, q_ref, s_ref, b_ref, o_ref, *, bits, group_size, block_in, in_dim
):
    per_word = 32 // bits
    mask = (1 << bits) - 1
    words = block_in // per_word
    groups = block_in // group_size
    bo = q_ref.shape[0]
    shifts = jax.lax.broadcasted_iota(jnp.uint32, (1, 1, per_word), 2) * bits

    def body(ki, acc):
        xblk = x_ref[:, pl.ds(ki * block_in, block_in)].astype(jnp.float32)
        wq = q_ref[:, pl.ds(ki * words, words)]  # (bo, words) uint32
        nib = (wq[:, :, None] >> shifts) & mask  # (bo, words, per_word)
        w = nib.reshape(bo, block_in).astype(jnp.float32)
        s = s_ref[:, pl.ds(ki * groups, groups)].astype(jnp.float32)
        b = b_ref[:, pl.ds(ki * groups, groups)].astype(jnp.float32)
        s = jnp.repeat(s[:, :, None], group_size, axis=2).reshape(bo, block_in)
        b = jnp.repeat(b[:, :, None], group_size, axis=2).reshape(bo, block_in)
        w = w * s + b
        return acc + jax.lax.dot_general(
            xblk, w, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    acc0 = jnp.zeros((x_ref.shape[0], bo), jnp.float32)
    acc = jax.lax.fori_loop(0, in_dim // block_in, body, acc0)
    o_ref[...] = acc.astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("group_size", "bits", "block_m", "block_out", "block_in",
                     "interpret"),
)
def quant_matmul_pallas(
    x: jax.Array,  # (M, IN)
    q: jax.Array,  # (OUT, IN * bits / 32) uint32
    scales: jax.Array,  # (OUT, IN / group_size)
    biases: jax.Array,  # (OUT, IN / group_size)
    *,
    group_size: int = 64,
    bits: int = 4,
    block_m: int = DEFAULT_BLOCK_M,
    block_out: int = DEFAULT_BLOCK_OUT,
    block_in: int = DEFAULT_BLOCK_IN,
    interpret: bool = False,
) -> jax.Array:
    """x @ dequant(q, scales, biases).T without materializing the dense
    weight. M and OUT must divide by their block sizes; IN by block_in."""
    m, in_dim = x.shape
    out_dim = q.shape[0]
    per_word = 32 // bits
    block_m = min(block_m, m)
    block_out = min(block_out, out_dim)
    block_in = min(block_in, in_dim)
    if block_in % group_size or block_in % per_word:
        raise ValueError(
            f"block_in {block_in} must be a multiple of group_size "
            f"{group_size} and {per_word}"
        )
    if m % block_m or out_dim % block_out or in_dim % block_in:
        raise ValueError(
            f"shapes (M={m}, OUT={out_dim}, IN={in_dim}) must divide block "
            f"sizes ({block_m}, {block_out}, {block_in})"
        )

    grid = (m // block_m, out_dim // block_out)
    return pl.pallas_call(
        functools.partial(
            _kernel, bits=bits, group_size=group_size, block_in=block_in,
            in_dim=in_dim,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_m, in_dim), lambda mi, oi: (mi, 0)),
            pl.BlockSpec(
                (block_out, in_dim // per_word), lambda mi, oi: (oi, 0)
            ),
            pl.BlockSpec(
                (block_out, in_dim // group_size), lambda mi, oi: (oi, 0)
            ),
            pl.BlockSpec(
                (block_out, in_dim // group_size), lambda mi, oi: (oi, 0)
            ),
        ],
        out_specs=pl.BlockSpec((block_m, block_out), lambda mi, oi: (mi, oi)),
        out_shape=jax.ShapeDtypeStruct((m, out_dim), x.dtype),
        interpret=interpret,
    )(x, q, scales, biases)
