"""Normalization ops.

The reference gets RMSNorm from mlx ``nn.RMSNorm`` inside the borrowed
decoder blocks (SURVEY §2.2); here it is a plain fused-friendly jnp function.
Accumulation is in float32 regardless of activation dtype (XLA fuses the
casts into neighbouring ops).
"""

from __future__ import annotations

import jax.numpy as jnp


def rms_norm(x, weight, eps: float = 1e-5, *, offset: float = 0.0):
    """RMSNorm. ``offset=1.0`` gives Gemma-style ``(1 + w) * x_hat``."""
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    x_hat = x32 * jnp.reciprocal(jnp.sqrt(var + eps))
    out = x_hat * (weight.astype(jnp.float32) + offset)
    return out.astype(dtype)
