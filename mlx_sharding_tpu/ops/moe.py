"""Mixture-of-experts dispatch.

The reference keeps experts fused and stage-local — per-expert weights are
stacked into one ``switch_mlp`` tensor at load time
(ref: shard/server/model/deepseek_v2.py:101-112) and routing happens inside
the owning pipeline stage (SURVEY §2.3 "EP"). Same policy here, with two
TPU execution paths chosen by token count at trace time:

- **decode (few tokens)**: gather the top-k experts' weights per token and
  batch the tiny matmuls — HBM traffic is k/E of the expert weights, which
  is what decode is bound by;
- **prefill (many tokens)**: ``lax.scan`` over experts with masked
  accumulation — every matmul is a full-width MXU op with static shapes, no
  sorting, no capacity overflow. (A Pallas ragged-dispatch kernel is the
  planned upgrade for very large E.)

Routing is parameterized so Mixtral (softmax→topk→renorm) and DeepSeek-V2
(softmax scoring→greedy topk, optional renorm + scaling factor) share the
dispatch machinery.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

GATHER_PATH_MAX_TOKENS = 16


def mixtral_routing(x, router_w, k: int):
    """HF Mixtral semantics: softmax over ALL expert logits, take top-k,
    renormalize the kept mass. Returns (weights (N,K) f32, idx (N,K))."""
    logits = (x @ router_w).astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    topv, topi = jax.lax.top_k(probs, k)
    topv = topv / topv.sum(axis=-1, keepdims=True)
    return topv, topi


def deepseek_routing(
    x,
    router_w,
    k: int,
    *,
    norm_topk_prob: bool,
    routed_scaling_factor: float,
    topk_method: str = "greedy",
    n_group: int = 1,
    topk_group: int = 1,
):
    """DeepSeek-V2 gate: softmax scores in fp32, then 'greedy' top-k
    (V2-Lite) or 'group_limited_greedy' (V2/V2-Chat: keep only the
    topk_group expert groups with the highest per-group max score, then
    top-k within them), scaled by routed_scaling_factor."""
    logits = jnp.einsum(
        "nh,he->ne", x.astype(jnp.float32), router_w.astype(jnp.float32)
    )
    scores = jax.nn.softmax(logits, axis=-1)
    if topk_method == "group_limited_greedy":
        n, e = scores.shape
        group_scores = scores.reshape(n, n_group, e // n_group).max(axis=-1)
        _, group_idx = jax.lax.top_k(group_scores, topk_group)  # (N, topk_group)
        group_mask = jnp.zeros_like(group_scores).at[
            jnp.arange(n)[:, None], group_idx
        ].set(1.0)
        score_mask = jnp.repeat(group_mask, e // n_group, axis=-1)
        scores = scores * score_mask
    elif topk_method != "greedy":
        raise ValueError(f"unknown topk_method {topk_method!r}")
    topv, topi = jax.lax.top_k(scores, k)
    if norm_topk_prob:
        topv = topv / (topv.sum(axis=-1, keepdims=True) + 1e-20)
    return topv * routed_scaling_factor, topi


def apply_experts(
    x, weights, idx, w_gate, w_up, w_down, ep_axis=None,
    group_size: int = 64, bits: int = 4,
):
    """SwiGLU expert application. x (N, H); w_* stacked (E, H, I)/(E, I, H)
    dense, or packed ``{q, scales, biases}`` triples with MLX-orientation
    leaves (E, out, in*bits/32) — 4-bit expert stacks stay resident in HBM
    and dequantize on the fly (ref quant predicate: shard/utils.py:54-65).
    weights/idx (N, K). Returns (N, H).

    ``ep_axis``: inside shard_map with the expert stacks sharded over that
    mesh axis, each device holds E/ep experts whose GLOBAL ids start at
    ``axis_index * E_local``; routing (weights/idx, global ids) is replicated,
    each device accumulates only its residents' contribution, and one psum
    combines — no all-to-all, no capacity factor, no token dropping."""
    from mlx_sharding_tpu.ops.quant import is_quantized

    n = x.shape[0]
    e_local = (w_gate["q"] if is_quantized(w_gate) else w_gate).shape[0]
    if ep_axis is not None:
        base = jax.lax.axis_index(ep_axis) * e_local
        acc = _apply_scan(
            x, weights, idx - base, w_gate, w_up, w_down, group_size, bits
        )
        return jax.lax.psum(acc, ep_axis)
    if n <= GATHER_PATH_MAX_TOKENS:
        # decode path: HBM traffic is k/E of the stacks — and 4x less again
        # when they are packed (gather the packed leaves, dequantize the
        # gathered slice in-register)
        if is_quantized(w_gate):
            return _apply_gather_packed(
                x, weights, idx, w_gate, w_up, w_down, group_size, bits
            )
        return _apply_gather(x, weights, idx, w_gate, w_up, w_down)
    return _apply_scan(x, weights, idx, w_gate, w_up, w_down, group_size, bits)


def _apply_gather(x, weights, idx, w_gate, w_up, w_down):
    wg = w_gate[idx]  # (N, K, H, I)
    wu = w_up[idx]
    wd = w_down[idx]  # (N, K, I, H)
    g = jnp.einsum("nh,nkhi->nki", x, wg)
    u = jnp.einsum("nh,nkhi->nki", x, wu)
    y = jnp.einsum("nki,nkih->nkh", jax.nn.silu(g) * u, wd)
    return (y * weights[..., None].astype(y.dtype)).sum(axis=1).astype(x.dtype)


def _apply_gather_packed(x, weights, idx, w_gate, w_up, w_down, gs, bits):
    """Gather path over packed stacks: index the uint32/fp16 leaves by the
    top-k expert ids (reading k/E × 1/4 of the dense bytes), then dequantize
    just the gathered (N, K, out, in) slices. MLX orientation is (out, in),
    so the einsums contract the LAST dim."""
    from mlx_sharding_tpu.ops.quant import dequantize

    def gathered(w):  # → (N, K, out, in) dense in x.dtype
        return dequantize(
            w["q"][idx], w["scales"][idx], w["biases"][idx], gs, bits, x.dtype
        )

    g = jnp.einsum("nh,nkih->nki", x, gathered(w_gate))
    u = jnp.einsum("nh,nkih->nki", x, gathered(w_up))
    y = jnp.einsum("nki,nkhi->nkh", jax.nn.silu(g) * u, gathered(w_down))
    return (y * weights[..., None].astype(y.dtype)).sum(axis=1).astype(x.dtype)


def _apply_scan(x, weights, idx, w_gate, w_up, w_down, gs=64, bits=4):
    from mlx_sharding_tpu.ops.quant import is_quantized, linear

    num_experts = (w_gate["q"] if is_quantized(w_gate) else w_gate).shape[0]

    def body(acc, xs):
        wg, wu, wd, e = xs
        coef = ((idx == e) * weights).sum(axis=-1)  # (N,) routing mass for e
        # linear() serves dense (in, out) slices and packed (out, in)
        # triples alike — the prefill path streams every expert's packed
        # bytes once, full-width MXU matmuls, no sorting
        y = linear(jax.nn.silu(linear(x, wg, gs, bits)) * linear(x, wu, gs, bits), wd, gs, bits)
        return acc + coef[:, None].astype(y.dtype) * y, None

    acc0 = jnp.zeros_like(x)
    acc, _ = jax.lax.scan(
        body, acc0, (w_gate, w_up, w_down, jnp.arange(num_experts))
    )
    return acc
