from mlx_sharding_tpu.ops.norms import rms_norm
from mlx_sharding_tpu.ops.rope import apply_rope, rope_frequencies
from mlx_sharding_tpu.ops.attention import causal_attention

__all__ = ["rms_norm", "apply_rope", "rope_frequencies", "causal_attention"]
