"""Causal attention over a fixed-capacity KV cache.

Replaces two reference pieces at once:
- the dense additive causal mask the reference materializes per prefill
  (ref: shard/server/model/llama.py:48-53, gemma2.py:48-51) — here masking is
  computed inline from broadcasted iotas and fused by XLA, never stored;
- mlx's scaled_dot_product_attention inside the borrowed decoder blocks
  (SURVEY §2.2).

Inputs are the *full-capacity* cache buffers; validity is derived from the
cache offset, so the same compiled program serves prefill (T=prompt) and
decode (T=1) without recompiling on sequence position. Scores accumulate in
float32 on the MXU; GQA is handled by grouping query heads over KV heads
rather than repeating K/V (no HBM duplication).
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import jax.numpy as jnp


def _flash_eligible(q, k, v, logit_softcap, sliding_window, sinks) -> bool:
    """Use the Pallas kernel on TPU for standard causal GQA (no softcap/
    window/sinks): prefill chunks with T a multiple of 128, and — opt-in via
    MST_FLASH_DECODE=1 until measured on hardware — T=1 decode steps.

    Head dims need only 64-alignment (Mosaic pads sub-128 lane tails): this
    admits DeepSeek MLA's dk=192 full-mode and dk=rank+rope / dv=rank
    compressed-mode shapes, not just the 128-multiples of round 1. Opt out
    entirely with MST_FLASH=0."""
    if os.environ.get("MST_FLASH", "1") == "0":
        return False
    if logit_softcap is not None or sliding_window is not None or sinks is not None:
        return False
    b, t, hq, dk = q.shape
    s, dv = k.shape[1], v.shape[-1]
    t_ok = (t >= 128 and t % 128 == 0) or (
        t == 1 and os.environ.get("MST_FLASH_DECODE", "0") == "1"
    )
    return (
        jax.default_backend() == "tpu"
        and t_ok
        and s % 128 == 0
        and dk % 64 == 0
        and dv % 64 == 0
    )


def causal_attention(
    q: jax.Array,  # (B, T, Hq, Dk)
    k: jax.Array,  # (B, S, Hkv, Dk) — full cache buffer
    v: jax.Array,  # (B, S, Hkv, Dv)
    offset: jax.Array,  # scalar: first new position (query i sits at offset+i)
    scale: float,
    *,
    logit_softcap: Optional[float] = None,  # gemma2.py attn softcapping
    sliding_window=None,  # int or traced scalar — gemma-2 alternating layers
    sinks: Optional[jax.Array] = None,  # reserved for attention-sink variants
) -> jax.Array:
    """Returns (B, T, Hq, Dv). Keys at positions > query position (or outside
    the sliding window, or beyond the valid prefix) contribute nothing.

    Prefill chunks that qualify route to the Pallas flash kernel
    (ops/flash_attention.py); everything else takes the fused-XLA path below."""
    if _flash_eligible(q, k, v, logit_softcap, sliding_window, sinks):
        from mlx_sharding_tpu.ops.flash_attention import flash_attention

        return flash_attention(q, k, v, offset, scale)
    b, t, hq, dk = q.shape
    s, hkv = k.shape[1], k.shape[2]
    groups = hq // hkv

    qg = q.reshape(b, t, hkv, groups, dk)
    # (B, Hkv, G, T, S) — operands stay in their (bf16) dtype so the MXU runs
    # at native throughput; accumulation is fp32 via preferred_element_type.
    scores = jnp.einsum(
        "bthgd,bshd->bhgts", qg, k, preferred_element_type=jnp.float32
    )
    scores = scores * scale
    if logit_softcap is not None:
        scores = logit_softcap * jnp.tanh(scores / logit_softcap)

    q_pos = offset + jnp.arange(t)[:, None]  # (T, 1)
    k_pos = jnp.arange(s)[None, :]  # (1, S)
    allowed = k_pos <= q_pos
    if sliding_window is not None:
        allowed &= k_pos > q_pos - sliding_window
    scores = jnp.where(allowed[None, None, None], scores, -jnp.inf)

    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bhgts,bshd->bthgd",
        probs.astype(v.dtype),
        v,
        preferred_element_type=jnp.float32,
    )
    return out.reshape(b, t, hq, -1).astype(q.dtype)
