"""Rotary position embeddings.

The reference inherits RoPE from mlx_lm's decoder blocks (SURVEY §2.2). Here
it is explicit: frequencies are precomputed once (host-side, static), and
application is a pure jnp function over (B, T, H, D) tensors with a
position offset coming from the KV-cache counter — so decode steps at T=1
jit to a single fused kernel with no recompilation per position.

Conventions follow HF ``transformers`` (split-half rotation), which is what
the safetensors checkpoints we load assume.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np


def rope_frequencies(
    head_dim: int,
    theta: float = 10000.0,
    rope_scaling: dict | None = None,
) -> np.ndarray:
    """Per-pair inverse frequencies (head_dim // 2,), float32.

    Supports HF ``rope_scaling`` variants ``linear`` and ``llama3``.
    """
    inv_freq = 1.0 / (theta ** (np.arange(0, head_dim, 2, dtype=np.float64) / head_dim))
    if rope_scaling:
        rope_type = rope_scaling.get("rope_type", rope_scaling.get("type", "default"))
        if rope_type == "linear":
            inv_freq = inv_freq / float(rope_scaling["factor"])
        elif rope_type == "llama3":
            factor = float(rope_scaling["factor"])
            low = float(rope_scaling.get("low_freq_factor", 1.0))
            high = float(rope_scaling.get("high_freq_factor", 4.0))
            orig_max = float(
                rope_scaling.get("original_max_position_embeddings", 8192)
            )
            wavelen = 2 * math.pi / inv_freq
            # Low-frequency (long-wavelength) components get fully rescaled,
            # high-frequency ones are untouched, with a smooth ramp between.
            smooth = (orig_max / wavelen - low) / (high - low)
            smooth = np.clip(smooth, 0.0, 1.0)
            scaled = inv_freq / factor
            inv_freq = np.where(
                wavelen > orig_max / low,
                scaled,
                np.where(
                    wavelen < orig_max / high,
                    inv_freq,
                    (1 - smooth) * scaled + smooth * inv_freq,
                ),
            )
        elif rope_type in ("default", None):
            pass
        else:
            raise ValueError(f"Unsupported rope_scaling type: {rope_type!r}")
    return inv_freq.astype(np.float32)


def yarn_get_mscale(scale: float, mscale: float = 1.0) -> float:
    """DeepSeek's YaRN magnitude-scale helper (paper 2309.00071 §3.4).

    With ``rope_scaling.mscale_all_dim`` set, DeepSeek-V2 multiplies the
    attention softmax scale by ``yarn_get_mscale(factor, mscale_all_dim)**2``
    (mlx_lm DeepseekV2Attention / DeepSeek remote code) on top of the cos/sin
    attention factor — models must apply this or logits are ~1.59x too small
    at factor=40."""
    return 1.0 if scale <= 1 else 0.1 * mscale * math.log(scale) + 1.0


def yarn_frequencies(
    head_dim: int,
    theta: float,
    rope_scaling: dict,
    max_position_embeddings: int,
) -> tuple[np.ndarray, float]:
    """YaRN (NTK-by-parts) frequencies + attention scaling factor, following
    the published YaRN recipe (paper 2309.00071) with DeepSeek's
    mscale/mscale_all_dim attention-factor variant. Used by DeepSeek-V2
    checkpoints (rope_scaling.type == "yarn")."""
    dim = head_dim
    factor = float(rope_scaling["factor"])
    attention_factor = rope_scaling.get("attention_factor")
    mscale = rope_scaling.get("mscale")
    mscale_all_dim = rope_scaling.get("mscale_all_dim")
    orig_max = float(
        rope_scaling.get("original_max_position_embeddings")
        or max_position_embeddings
    )
    beta_fast = float(rope_scaling.get("beta_fast") or 32)
    beta_slow = float(rope_scaling.get("beta_slow") or 1)

    if attention_factor is None:
        # DeepSeek remote-code convention: unconditional ratio with defaults
        # mscale=1, mscale_all_dim=0 (and get_mscale(f, 0) == 1). This keeps
        # the cos/sin factor consistent with the model-side softmax-scale
        # correction (deepseek_v2.py), which fires whenever mscale_all_dim is
        # set — regardless of whether mscale is.
        attention_factor = yarn_get_mscale(
            factor, 1.0 if mscale is None else float(mscale)
        ) / yarn_get_mscale(
            factor, 0.0 if mscale_all_dim is None else float(mscale_all_dim)
        )

    def correction_dim(num_rotations):
        return (dim * math.log(orig_max / (num_rotations * 2 * math.pi))) / (
            2 * math.log(theta)
        )

    low = max(math.floor(correction_dim(beta_fast)), 0)
    high = min(math.ceil(correction_dim(beta_slow)), dim - 1)
    if low == high:
        high += 0.001

    pos_freqs = theta ** (np.arange(0, dim, 2, dtype=np.float64) / dim)
    extrapolation = 1.0 / pos_freqs
    interpolation = 1.0 / (factor * pos_freqs)
    ramp = np.clip(
        (np.arange(dim // 2, dtype=np.float64) - low) / (high - low), 0.0, 1.0
    )
    extrapolation_factor = 1.0 - ramp
    inv_freq = (
        interpolation * (1 - extrapolation_factor)
        + extrapolation * extrapolation_factor
    )
    return inv_freq.astype(np.float32), float(attention_factor)


def _rotate_half(x):
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([-x2, x1], axis=-1)


def apply_rope(x: jax.Array, inv_freq: jax.Array, offset) -> jax.Array:
    """Rotate ``x`` of shape (B, T, H, D) for absolute positions
    ``offset .. offset+T``. float32 trig, result in x.dtype. Split-half
    (HF rotate_half) convention. ``offset`` is a scalar, or a (B,) vector
    for per-row positions (the ragged paged-decode path, where every batch
    lane sits at its own sequence length)."""
    t = x.shape[1]
    off = jnp.asarray(offset, jnp.float32)
    positions = off[..., None] + jnp.arange(t, dtype=jnp.float32)  # (…, T)
    angles = positions[..., None] * inv_freq  # (…, T, D/2)
    angles = jnp.concatenate([angles, angles], axis=-1)  # (…, T, D)
    cos = jnp.cos(angles)[..., None, :]  # (T, 1, D) or (B, T, 1, D)
    sin = jnp.sin(angles)[..., None, :]
    x32 = x.astype(jnp.float32)
    out = x32 * cos + _rotate_half(x32) * sin
    return out.astype(x.dtype)


def apply_rope_interleaved(
    x: jax.Array, inv_freq: jax.Array, offset, scaling: float = 1.0
) -> jax.Array:
    """Complex-pair rotation: adjacent element pairs (2i, 2i+1) rotate
    together — DeepSeek-V2's convention (HF view_as_complex path), with the
    YaRN attention factor folded into the magnitude like HF's
    ``freqs_cis * attention_scaling``. ``offset``: scalar or (B,) vector
    (per-row positions, see :func:`apply_rope`)."""
    t = x.shape[1]
    off = jnp.asarray(offset, jnp.float32)
    positions = off[..., None] + jnp.arange(t, dtype=jnp.float32)  # (…, T)
    angles = positions[..., None] * inv_freq  # (…, T, D/2)
    cos = (jnp.cos(angles) * scaling)[..., None, :]
    sin = (jnp.sin(angles) * scaling)[..., None, :]
    x32 = x.astype(jnp.float32)
    x1, x2 = x32[..., 0::2], x32[..., 1::2]
    out1 = x1 * cos - x2 * sin
    out2 = x1 * sin + x2 * cos
    return jnp.stack([out1, out2], axis=-1).reshape(x.shape).astype(x.dtype)
