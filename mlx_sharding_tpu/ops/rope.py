"""Rotary position embeddings.

The reference inherits RoPE from mlx_lm's decoder blocks (SURVEY §2.2). Here
it is explicit: frequencies are precomputed once (host-side, static), and
application is a pure jnp function over (B, T, H, D) tensors with a
position offset coming from the KV-cache counter — so decode steps at T=1
jit to a single fused kernel with no recompilation per position.

Conventions follow HF ``transformers`` (split-half rotation), which is what
the safetensors checkpoints we load assume.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np


def rope_frequencies(
    head_dim: int,
    theta: float = 10000.0,
    rope_scaling: dict | None = None,
) -> np.ndarray:
    """Per-pair inverse frequencies (head_dim // 2,), float32.

    Supports HF ``rope_scaling`` variants ``linear`` and ``llama3``.
    """
    inv_freq = 1.0 / (theta ** (np.arange(0, head_dim, 2, dtype=np.float64) / head_dim))
    if rope_scaling:
        rope_type = rope_scaling.get("rope_type", rope_scaling.get("type", "default"))
        if rope_type == "linear":
            inv_freq = inv_freq / float(rope_scaling["factor"])
        elif rope_type == "llama3":
            factor = float(rope_scaling["factor"])
            low = float(rope_scaling.get("low_freq_factor", 1.0))
            high = float(rope_scaling.get("high_freq_factor", 4.0))
            orig_max = float(
                rope_scaling.get("original_max_position_embeddings", 8192)
            )
            wavelen = 2 * math.pi / inv_freq
            # Low-frequency (long-wavelength) components get fully rescaled,
            # high-frequency ones are untouched, with a smooth ramp between.
            smooth = (orig_max / wavelen - low) / (high - low)
            smooth = np.clip(smooth, 0.0, 1.0)
            scaled = inv_freq / factor
            inv_freq = np.where(
                wavelen > orig_max / low,
                scaled,
                np.where(
                    wavelen < orig_max / high,
                    inv_freq,
                    (1 - smooth) * scaled + smooth * inv_freq,
                ),
            )
        elif rope_type in ("default", None):
            pass
        else:
            raise ValueError(f"Unsupported rope_scaling type: {rope_type!r}")
    return inv_freq.astype(np.float32)


def _rotate_half(x):
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([-x2, x1], axis=-1)


def apply_rope(x: jax.Array, inv_freq: jax.Array, offset) -> jax.Array:
    """Rotate ``x`` of shape (B, T, H, D) for absolute positions
    ``offset .. offset+T``. float32 trig, result in x.dtype."""
    t = x.shape[1]
    positions = jnp.asarray(offset, jnp.float32) + jnp.arange(t, dtype=jnp.float32)
    angles = positions[:, None] * inv_freq[None, :]  # (T, D/2)
    angles = jnp.concatenate([angles, angles], axis=-1)  # (T, D)
    cos = jnp.cos(angles)[None, :, None, :]
    sin = jnp.sin(angles)[None, :, None, :]
    x32 = x.astype(jnp.float32)
    out = x32 * cos + _rotate_half(x32) * sin
    return out.astype(x.dtype)
