"""Ragged paged decode attention: attend over the KV page pool in place.

The paged continuous-batching decode path used to gather every slot's pages
into a dense (B, max_seq, H, D) view per tick (`_paged_read`) and scatter the
dirty page back (`_paged_writeback`) — the entire KV cache through HBM twice
per T=1 step, then attention over max_seq padding regardless of each slot's
true length. This module is the TPU-native fix (Ragged Paged Attention,
arxiv 2604.15464): a Pallas kernel that walks each slot's page-table row
directly, streaming only the pages a slot actually occupies and masking
FLOPs past its offset. No contiguous copy of the cache ever exists.

Two paths, selected the same way ops/flash_attention.py picks its path:

- the Pallas kernel (`_paged_kernel`): grid (slot, kv-head, page); the page
  to fetch is data-dependent, so the page table and lengths ride in as
  scalar-prefetch operands and the K/V BlockSpec index maps read them —
  Pallas double-buffers exactly the pages named by the table. A slot's
  scratch-page tail (table rows past its length all point at the same
  scratch id) collapses to one redundant fetch: consecutive grid steps with
  an identical block index skip the DMA. Online-softmax state (running max,
  normalizer, fp32 accumulator) lives in VMEM scratch across the page walk.
- a fused-XLA fallback for CPU / odd shapes / softcap / sliding-window /
  MLA latent-as-values, mirroring ops/attention.py's masking semantics but
  gathering only the slot's own table row (slot_pages × page rows), never
  a max_seq-dense buffer per layer stack.

Both are token-exact vs the gather path; tests/test_paged_attention.py holds
the parity matrix (uneven lengths, page-boundary offsets, empty slots, GQA/
MQA head counts, kernel-in-interpret vs XLA).
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def kernel_eligible(
    dk: int,
    dv: int,
    logit_softcap,
    sliding_window,
    values_from_k,
    interpret: bool,
) -> bool:
    """Pallas path: TPU backend (or interpret mode on any backend), standard
    GQA only — softcap/window/latent-values stay on the XLA path, like
    ops/attention.py's _flash_eligible. Head dims need 64-alignment on real
    hardware (Mosaic pads sub-128 lane tails); interpret mode takes any
    shape so CPU tests exercise the kernel logic itself. Opt out entirely
    with MST_PAGED_KERNEL=0."""
    if os.environ.get("MST_PAGED_KERNEL", "1") == "0":
        return False
    if (
        logit_softcap is not None
        or sliding_window is not None
        or values_from_k is not None
    ):
        return False
    if interpret:
        return True
    return jax.default_backend() == "tpu" and dk % 64 == 0 and dv % 64 == 0


def _kernel_body(
    tables_ref,  # (M, SPG) int32 — scalar-prefetch
    lens_ref,  # (M,) int32 — scalar-prefetch
    q_ref,  # (1, 1, G, Dk) block
    k_ref,  # (1, page, 1, Dk) block — the page named by tables[m, j]
    v_ref,  # (1, page, 1, Dv) block
    ks_ref,  # (1, page, 1, 1) per-row K scales (int8 pool) or None
    vs_ref,  # (1, page, 1, 1) per-row V scales (int8 pool) or None
    o_ref,  # (1, 1, G, Dv) block
    m_scr,  # (G, 128) f32 VMEM — running max, lane-replicated
    l_scr,  # (G, 128) f32 VMEM — running normalizer
    acc_scr,  # (G, Dv) f32 VMEM — unnormalized output accumulator
    *,
    scale: float,
    page_size: int,
    pages_per_slot: int,
):
    m = pl.program_id(0)
    j = pl.program_id(2)
    length = lens_ref[m]

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # pages entirely past this slot's length are scratch-table tails: skip
    # all compute (their DMA already collapsed to the repeated scratch id)
    @pl.when(j * page_size < length)
    def _attend():
        q = q_ref[0, 0].astype(jnp.float32)  # (G, Dk)
        kblk = k_ref[0, :, 0, :].astype(jnp.float32)  # (page, Dk)
        vblk = v_ref[0, :, 0, :].astype(jnp.float32)  # (page, Dv)
        if ks_ref is not None:
            # int8 pool: dequant fused into the page read — the pool's
            # HBM→VMEM traffic is the int8 bytes; the (page, 1) scale
            # broadcasts over the head dim in registers
            kblk = kblk * ks_ref[0, :, 0, :]
            vblk = vblk * vs_ref[0, :, 0, :]
        s = jax.lax.dot_general(
            q, kblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # (G, page)
        k_pos = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1
        )
        s = jnp.where(k_pos < length, s, NEG_INF)
        m_prev = m_scr[:, :1]  # (G, 1)
        l_prev = l_scr[:, :1]
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + p.sum(axis=-1, keepdims=True)
        acc_scr[...] = acc_scr[...] * corr + jax.lax.dot_general(
            p, vblk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == pages_per_slot - 1)
    def _finish():
        # empty slot (length 0, the garbage lane): l stays 0 → zeros out
        l = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0, 0] = (acc_scr[...] / l).astype(o_ref.dtype)


def _kernel(tables_ref, lens_ref, q_ref, k_ref, v_ref, o_ref,
            m_scr, l_scr, acc_scr, **kw):
    _kernel_body(tables_ref, lens_ref, q_ref, k_ref, v_ref, None, None,
                 o_ref, m_scr, l_scr, acc_scr, **kw)


def _kernel_int8(tables_ref, lens_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref,
                 o_ref, m_scr, l_scr, acc_scr, **kw):
    _kernel_body(tables_ref, lens_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref,
                 o_ref, m_scr, l_scr, acc_scr, **kw)


def _paged_attention_kernel(
    q, k_pool, v_pool, tables, lengths, scale, interpret,
    k_scale=None, v_scale=None,
):
    m, hq, dk = q.shape
    pages, page_size, hkv, dv = (
        k_pool.shape[0], k_pool.shape[1], k_pool.shape[2], v_pool.shape[-1],
    )
    spg = tables.shape[1]
    g = hq // hkv
    qg = q.reshape(m, hkv, g, dk)
    quant = k_scale is not None

    def page_spec(d):
        # data-dependent page fetch: the block index comes from the
        # prefetched table row — this is the whole point of the kernel
        return pl.BlockSpec(
            (1, page_size, 1, d),
            lambda mi, hi, ji, t, ln: (t[mi, ji], 0, hi, 0),
        )

    in_specs = [
        pl.BlockSpec((1, 1, g, dk), lambda mi, hi, ji, t, ln: (mi, hi, 0, 0)),
        page_spec(dk),
        page_spec(dv),
    ]
    operands = [qg, k_pool, v_pool]
    if quant:  # the scale planes ride the same table-indexed fetch
        in_specs += [page_spec(1), page_spec(1)]
        operands += [k_scale.astype(jnp.float32), v_scale.astype(jnp.float32)]

    spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(m, hkv, spg),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, 1, g, dv), lambda mi, hi, ji, t, ln: (mi, hi, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((g, 128), jnp.float32),
            pltpu.VMEM((g, 128), jnp.float32),
            pltpu.VMEM((g, dv), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _kernel_int8 if quant else _kernel,
            scale=scale, page_size=page_size, pages_per_slot=spg,
        ),
        grid_spec=spec,
        out_shape=jax.ShapeDtypeStruct((m, hkv, g, dv), q.dtype),
        interpret=interpret,
    )(tables.astype(jnp.int32), lengths.astype(jnp.int32), *operands)
    return out.reshape(m, hq, dv)


def _paged_attention_xla(
    q, k_pool, v_pool, tables, lengths, scale,
    logit_softcap, sliding_window, values_from_k,
    k_scale=None, v_scale=None,
):
    m, hq, dk = q.shape
    page_size, hkv = k_pool.shape[1], k_pool.shape[2]
    spg = tables.shape[1]
    g = hq // hkv

    def gathered(pool, scl):
        x = jnp.take(pool, tables, axis=0)  # (M, SPG, page, Hkv, D)
        x = x.reshape(m, spg * page_size, hkv, -1)
        if scl is not None:  # int8 pool: dequant the gathered rows only
            s = jnp.take(scl, tables, axis=0).reshape(
                m, spg * page_size, hkv, 1
            )
            x = x.astype(jnp.float32) * s
        return x

    k = gathered(k_pool, k_scale)
    if values_from_k is not None:
        v = k[..., :values_from_k]  # MLA: values are the latent prefix of k
    else:
        v = gathered(v_pool, v_scale)
    qg = q.reshape(m, hkv, g, dk)
    scores = jnp.einsum(
        "mhgd,mshd->mhgs", qg, k, preferred_element_type=jnp.float32
    ) * scale
    if logit_softcap is not None:
        scores = logit_softcap * jnp.tanh(scores / logit_softcap)
    k_pos = jnp.arange(spg * page_size)[None, :]  # (1, S_virt)
    allowed = k_pos < lengths[:, None]
    if sliding_window is not None:
        # the single query sits at position lengths-1
        allowed &= k_pos > (lengths[:, None] - 1) - sliding_window
    scores = jnp.where(allowed[:, None, None, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    # an all-masked row (length 0, an inactive slot) softmaxes to uniform
    # garbage, not zeros — clamp it so the contract matches the kernel
    probs = probs * allowed[:, None, None, :]
    out = jnp.einsum(
        "mhgs,mshd->mhgd",
        probs.astype(v.dtype),
        v,
        preferred_element_type=jnp.float32,
    )
    return out.reshape(m, hq, -1).astype(q.dtype)


def paged_attention(
    q: jax.Array,  # (M, Hq, Dk) — one query token per slot
    k_pool: jax.Array,  # (P+1, page, Hkv, Dk) — one layer's pool, scratch last
    v_pool: jax.Array,  # (P+1, page, Hkv, Dv)
    tables: jax.Array,  # (M, SPG) int32 pool-page ids (scratch id past length)
    lengths: jax.Array,  # (M,) int32 — valid positions incl. the new token
    scale: float,
    *,
    logit_softcap: Optional[float] = None,
    sliding_window=None,  # int or traced scalar
    values_from_k: Optional[int] = None,  # MLA latent-as-values
    k_scale: Optional[jax.Array] = None,  # (P+1, page, Hkv, 1) int8-pool scales
    v_scale: Optional[jax.Array] = None,
    interpret: bool = False,
) -> jax.Array:
    """Ragged decode attention over one layer's page pool. Returns
    (M, Hq, Dv). Row m attends to positions 0..lengths[m] of its own pages;
    lengths[m] == 0 (an inactive slot) yields zeros. The new token's K/V
    must already be written into the pool (the engine scatters the single
    row before calling this). With ``k_scale``/``v_scale`` the pools are
    int8 codes and dequant (code × per-row-per-head scale) fuses into the
    page reads — both paths stream the int8 bytes, never a dense bf16 copy
    of the pages."""
    dk, dv = q.shape[-1], v_pool.shape[-1]
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale and v_scale must be passed together")
    if kernel_eligible(
        dk, dv, logit_softcap, sliding_window, values_from_k, interpret
    ):
        return _paged_attention_kernel(
            q, k_pool, v_pool, tables, lengths, scale, interpret,
            k_scale, v_scale,
        )
    return _paged_attention_xla(
        q, k_pool, v_pool, tables, lengths, scale,
        logit_softcap, sliding_window, values_from_k, k_scale, v_scale,
    )
