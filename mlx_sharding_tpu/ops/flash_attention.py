"""Pallas flash-attention kernel for prefill.

The XLA path (ops/attention.py) materializes (B, H, T, S) scores in HBM for
prefill chunks; this kernel keeps everything in VMEM: each program owns one
(block_q × head) query tile, streams K/V blocks through the online-softmax
recurrence (running max / normalizer / accumulator in fp32), and writes one
output tile — no score matrix ever exists. Matmuls are MXU-shaped
(block_q × head_dim × block_k), masking is computed from broadcasted iotas
against the cache offset (same validity rule as ops/attention.py).

Scope: standard causal GQA attention (Llama/Mistral/Qwen2/Mixtral/DeepSeek).
Gemma-2's softcap + sliding-window layers stay on the XLA path. K/V arrive
as the full-capacity cache buffers; blocks entirely in the future of the
query tile are skipped without compute.

Real-chip status (BENCH_DETAIL.json kernels block, v5e): per-kernel timing
through the axon tunnel is NOISY — the committed record shows flash prefill
880.4 µs vs fused-XLA 338.3 µs and T=1 decode 951.5 vs 310.0 µs at the
bench shapes, while earlier runs of the same A/B showed the opposite
(397 vs 744 µs). Two consequences: (1) T=1 decode stays OFF by default
(the end-to-end A/B was also a loss: 97.6 vs 101.6 tok/s —
MST_FLASH_DECODE=1 to opt in); (2) prefill dispatch is decided by the
END-TO-END prompt-tps/TTFT A/B bench.py runs (decode_bf16_no_flash_prefill),
not the noisy per-kernel numbers. The adaptive VMEM-budget q-tile below
(round 5) attacks the same per-program-overhead failure mode the fixed
128-tile dequant-matmul had before its picker (8x off roofline —
ops/quant_matmul.py): every query tile of a head re-streams the whole
(S, Dk+Dv) K/V row, so fewer/larger tiles amortize it.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30

# Per-program VMEM budget for the adaptive q-tile picker (same sizing
# rationale as ops/quant_matmul.py's _VMEM_BUDGET_BYTES: leave headroom in
# the ~16MB VMEM for double-buffering and the compiler's own scratch).
_VMEM_BUDGET_BYTES = 6 * 1024 * 1024


def pick_block_q(t: int, s: int, dk: int, dv: int, itemsize: int,
                 block_k: int = DEFAULT_BLOCK_K) -> int:
    """Largest 128-multiple divisor of T (or T itself, when it fits) whose
    per-program working set stays inside the VMEM budget. The whole-S K/V
    row is a FIXED per-program cost re-paid by every query tile of a head;
    growing the tile divides that cost across more queries — the lever that
    took quant_matmul from 8x off its roofline to a 2.2x win."""
    fixed = s * (dk + dv) * itemsize  # K/V rows resident per program
    # per query row: q/o tile bytes, fp32 softmax state (m, l, acc), and the
    # kernel's two (block_q, block_k) fp32 intermediates (scores s, probs p)
    per_q = (
        (dk + dv) * itemsize + (dv + 2 + dk) * 4 + 2 * block_k * 4
    )
    limit = max((_VMEM_BUDGET_BYTES - fixed) // per_q, 128)
    if t <= limit:
        return t
    best = None
    d = 128
    while d <= limit:
        if t % d == 0:
            best = d
        d += 128
    return best if best is not None else min(t, DEFAULT_BLOCK_Q)


def _kernel(off_ref, q_ref, k_ref, v_ref, o_ref, *, scale, block_q, block_k, s_len):
    q = q_ref[0, 0].astype(jnp.float32)  # (bq, dk)
    offset = off_ref[0]
    iq = pl.program_id(2)
    dv = v_ref.shape[-1]

    q_pos = offset + iq * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, 1), 0
    )  # (bq, 1)
    num_k_blocks = s_len // block_k

    def body(ik, carry):
        m, l, acc = carry

        def attend(carry):
            m, l, acc = carry
            kblk = k_ref[0, 0, pl.ds(ik * block_k, block_k), :].astype(jnp.float32)
            vblk = v_ref[0, 0, pl.ds(ik * block_k, block_k), :].astype(jnp.float32)
            s = jax.lax.dot_general(
                q, kblk, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale  # (bq, bk)
            k_pos = ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (1, block_k), 1
            )
            s = jnp.where(k_pos <= q_pos, s, NEG_INF)
            m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)
            corr = jnp.exp(m - m_new)
            l = l * corr + p.sum(axis=-1, keepdims=True)
            acc = acc * corr + jax.lax.dot_general(
                p, vblk, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            return m_new, l, acc

        # skip K blocks entirely beyond this query tile's last position
        last_q_pos = offset + (iq + 1) * block_q - 1
        return jax.lax.cond(
            ik * block_k <= last_q_pos, attend, lambda c: c, (m, l, acc)
        )

    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, dv), jnp.float32)
    _, l, acc = jax.lax.fori_loop(0, num_k_blocks, body, (m0, l0, acc0))
    o_ref[0, 0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("scale", "block_q", "block_k", "interpret")
)
def flash_attention(
    q: jax.Array,  # (B, T, Hq, Dk)
    k: jax.Array,  # (B, S, Hkv, Dk) — full cache buffer
    v: jax.Array,  # (B, S, Hkv, Dv)
    offset: jax.Array,  # scalar int32
    scale: float,
    *,
    block_q: int | None = None,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: bool = False,
) -> jax.Array:
    """Drop-in for ops.attention.causal_attention on the standard causal/GQA
    case. T must divide block_q*n and S must divide block_k*n (the callers'
    chunked-prefill invariants guarantee this for multiples of 128).
    ``block_q=None`` → the adaptive VMEM-budget picker."""
    b, t, hq, dk = q.shape
    s, hkv, dv = k.shape[1], k.shape[2], v.shape[-1]
    groups = hq // hkv
    block_k = min(block_k, s)
    if block_q is None:
        block_q = pick_block_q(t, s, dk, dv, q.dtype.itemsize, block_k)
    block_q = min(block_q, t)
    if t % block_q or s % block_k:
        raise ValueError(f"T={t} and S={s} must be multiples of the block sizes")

    qt = q.transpose(0, 2, 1, 3)  # (B, Hq, T, Dk)
    kt = k.transpose(0, 2, 1, 3)  # (B, Hkv, S, Dk)
    vt = v.transpose(0, 2, 1, 3)

    grid = (b, hq, t // block_q)
    out = pl.pallas_call(
        functools.partial(
            _kernel, scale=scale, block_q=block_q, block_k=block_k, s_len=s
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # offset
            pl.BlockSpec(
                (1, 1, block_q, dk), lambda bi, hi, qi: (bi, hi, qi, 0)
            ),
            pl.BlockSpec(
                (1, 1, s, dk), lambda bi, hi, qi, g=groups: (bi, hi // g, 0, 0)
            ),
            pl.BlockSpec(
                (1, 1, s, dv), lambda bi, hi, qi, g=groups: (bi, hi // g, 0, 0)
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, block_q, dv), lambda bi, hi, qi: (bi, hi, qi, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((b, hq, t, dv), q.dtype),
        interpret=interpret,
    )(jnp.asarray(offset, jnp.int32).reshape(1), qt, kt, vt)
    return out.transpose(0, 2, 1, 3)  # (B, T, Hq, Dv)
