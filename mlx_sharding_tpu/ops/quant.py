"""MLX grouped-affine quantization compatibility.

The published ``*-4bit-mlx`` checkpoints the reference loads store each linear
as a triple ``{weight, scales, biases}`` (ref: shard/utils.py:54-65 applies
``nn.quantize`` when config.json carries a ``quantization`` dict, with the
``"{path}.scales" in weights`` predicate). Layout (mlx.core.quantize):

- ``weight``: uint32, shape (out, in * bits / 32); each uint32 packs
  ``32/bits`` consecutive input-dim elements, least-significant bits first.
- ``scales``/``biases``: (out, in / group_size); element value is
  ``q * scale + bias`` per group.

SURVEY §7 hard-part (a): this must be decoded bit-exactly or outputs diverge.
Round 1 dequantizes on load to bf16 (weights then live in HBM dense); a
Pallas fused dequant-matmul is the follow-up optimization path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def dequantize(
    w_q: jax.Array | np.ndarray,
    scales: jax.Array | np.ndarray,
    biases: jax.Array | np.ndarray,
    group_size: int = 64,
    bits: int = 4,
    dtype=jnp.bfloat16,
) -> jax.Array:
    """(…, out, in*bits/32) packed uint32 → (…, out, in) dense. Leading
    dims carry stacked layers / expert stacks / gathered top-k experts."""
    w_q = jnp.asarray(w_q)
    if w_q.dtype != jnp.uint32:
        raise ValueError(f"packed weight must be uint32, got {w_q.dtype}")
    lead = w_q.shape[:-1]
    per_word = 32 // bits
    shifts = jnp.arange(per_word, dtype=jnp.uint32) * bits
    # (…, out, in/per_word, per_word) → (…, out, in)
    vals = (w_q[..., None] >> shifts) & ((1 << bits) - 1)
    vals = vals.reshape(*lead, -1).astype(jnp.float32)
    in_dim = vals.shape[-1]
    scales = jnp.asarray(scales, jnp.float32).reshape(*lead, in_dim // group_size, 1)
    biases = jnp.asarray(biases, jnp.float32).reshape(*lead, in_dim // group_size, 1)
    grouped = vals.reshape(*lead, in_dim // group_size, group_size)
    return (grouped * scales + biases).reshape(*lead, in_dim).astype(dtype)


def is_quantized(w) -> bool:
    """True for a packed ``{q, scales, biases}`` param (kept-packed load
    mode); False for a dense array."""
    return isinstance(w, dict) and "q" in w


def fuse_packed(parts):
    """Concatenate packed triples that share an IN dimension along OUT
    (axis -2 of every leaf in the MLX layout) into one packed param.

    Build-time only: the fused param serves N projections (QKV, gate+up)
    with a single kernel invocation, so the activation planes are read
    once instead of N times and decode issues one launch where it issued
    N. Per output row the fused GEMV computes the exact same sub-dot
    sequence as the separate calls, so results are bit-identical."""
    if not all(is_quantized(p) for p in parts):
        raise ValueError("fuse_packed expects packed {q, scales, biases} triples")
    return {
        leaf: jnp.concatenate([p[leaf] for p in parts], axis=-2)
        for leaf in ("q", "scales", "biases")
    }


def linear(x: jax.Array, w, group_size: int = 64, bits: int = 4) -> jax.Array:
    """``x @ w`` that transparently serves packed params.

    Dense path: ``w`` is the usual (in, out) array. Packed path: ``w`` is an
    MLX-layout triple (``q`` (out, in*bits/32) uint32, ``scales``/``biases``
    (out, in/group_size)) and the product routes through the fused Pallas
    dequant-matmul on TPU (XLA dequant+matmul elsewhere) — the dense weight
    never exists in HBM."""
    if not is_quantized(w):
        return x @ w
    lead = x.shape[:-1]
    in_dim = x.shape[-1]
    x2 = x.reshape(-1, in_dim)
    out = _quant_matmul(x2, w["q"], w["scales"], w["biases"], group_size, bits)
    return out.reshape(*lead, -1)


def _pallas_ok(m, in_dim, out_dim, group_size, bits) -> bool:
    import os

    if os.environ.get("MST_QMM", "1") == "0":
        return False
    # single source of truth for the dispatch contract: the kernel's own
    # block defaults and min() clamping
    from mlx_sharding_tpu.ops.quant_matmul import (
        DEFAULT_BLOCK_M,
        pick_block_in,
        pick_block_out,
    )

    per_word = 32 // bits
    block_in = min(pick_block_in(in_dim), in_dim)
    block_out = pick_block_out(out_dim, block_in // per_word, min(DEFAULT_BLOCK_M, m), per_word)
    return (
        jax.default_backend() == "tpu"
        and m % min(DEFAULT_BLOCK_M, m) == 0
        and out_dim % block_out == 0
        and in_dim % block_in == 0
        and block_in % group_size == 0
        and block_in % per_word == 0
    )


def _gemv_ok(m, in_dim, out_dim, group_size, bits) -> bool:
    """Decode shapes route to the pipelined GEMV: M ≤ 8, TPU backend (or
    MST_QMM_GEMV=interpret, which forces the kernel in interpret mode for
    end-to-end parity tests on CPU), blocks dividing cleanly with
    128-aligned word lanes (Mosaic's DMA tiling)."""
    import os

    mode = os.environ.get("MST_QMM_GEMV", "1")
    if mode == "0" or os.environ.get("MST_QMM", "1") == "0":
        return False
    from mlx_sharding_tpu.ops.quant_matmul import GEMV_MAX_M, get_gemv_blocks

    if m > GEMV_MAX_M:
        return False
    if mode != "interpret" and jax.default_backend() != "tpu":
        return False
    per_word = 32 // bits
    block_out, block_in = get_gemv_blocks(m, out_dim, in_dim, group_size, bits)
    words_ok = mode == "interpret" or (
        (block_in // per_word) % 128 == 0 and block_out % 128 == 0
    )
    return (
        out_dim % block_out == 0
        and in_dim % block_in == 0
        and block_in % group_size == 0
        and block_in % per_word == 0
        and words_ok
    )


def _quant_matmul(x2, q, scales, biases, group_size, bits):
    import os

    m, in_dim = x2.shape
    out_dim = q.shape[0]
    if _gemv_ok(m, in_dim, out_dim, group_size, bits):
        from mlx_sharding_tpu.ops.quant_matmul import quant_gemv_pipelined

        return quant_gemv_pipelined(
            x2, q, scales, biases, group_size=group_size, bits=bits,
            interpret=os.environ.get("MST_QMM_GEMV") == "interpret",
        )
    if _pallas_ok(m, in_dim, out_dim, group_size, bits):
        from mlx_sharding_tpu.ops.quant_matmul import quant_matmul_pallas

        return quant_matmul_pallas(
            x2, q, scales, biases, group_size=group_size, bits=bits
        )
    # Guarded XLA fallback: only shapes/backends no kernel serves reach it.
    # mst: allow(MST105): dense tile is transient inside this one matmul
    w = dequantize(q, scales, biases, group_size, bits, jnp.float32)
    return (x2 @ w.astype(x2.dtype).T).astype(x2.dtype)


def quantize_jax(w: jax.Array, group_size: int = 64, bits: int = 4):
    """Device-side mlx-layout packer: (…, out, in) → (q (…, out, in*bits/32)
    uint32, scales, biases (…, out, in/group_size) f32). Same math as
    :func:`quantize`, jittable — benchmarks quantize multi-GB weight stacks
    in place on the chip instead of round-tripping them to host."""
    w = jnp.asarray(w, jnp.float32)
    *lead, out_dim, in_dim = w.shape
    if in_dim % group_size:
        raise ValueError(f"in_dim {in_dim} not divisible by group_size {group_size}")
    grouped = w.reshape(*lead, out_dim, in_dim // group_size, group_size)
    w_max = grouped.max(axis=-1, keepdims=True)
    w_min = grouped.min(axis=-1, keepdims=True)
    n_levels = (1 << bits) - 1
    scale = jnp.maximum((w_max - w_min) / n_levels, 1e-8)
    q = jnp.clip(jnp.round((grouped - w_min) / scale), 0, n_levels).astype(jnp.uint32)
    q = q.reshape(*lead, out_dim, in_dim)
    per_word = 32 // bits
    # (…, out, in/per_word, per_word): LSB-first nibbles within each word
    q = q.reshape(*lead, out_dim, in_dim // per_word, per_word)
    shifts = jnp.arange(per_word, dtype=jnp.uint32) * bits
    packed = (q << shifts).sum(axis=-1, dtype=jnp.uint32)
    return (
        packed,
        scale[..., 0].astype(jnp.float32),
        w_min[..., 0].astype(jnp.float32),
    )


def quantize(w: np.ndarray, group_size: int = 64, bits: int = 4):
    """Inverse of :func:`dequantize` — mlx-compatible packer. Used by the
    shard-writer tool and round-trip tests; numpy (host, offline)."""
    w = np.asarray(w, np.float32)
    out_dim, in_dim = w.shape
    if in_dim % group_size:
        raise ValueError(f"in_dim {in_dim} not divisible by group_size {group_size}")
    grouped = w.reshape(out_dim, in_dim // group_size, group_size)
    w_max = grouped.max(axis=-1, keepdims=True)
    w_min = grouped.min(axis=-1, keepdims=True)
    n_levels = (1 << bits) - 1
    scale = np.maximum((w_max - w_min) / n_levels, 1e-8)
    q = np.clip(np.round((grouped - w_min) / scale), 0, n_levels).astype(np.uint32)
    q = q.reshape(out_dim, in_dim)
    per_word = 32 // bits
    packed = np.zeros((out_dim, in_dim // per_word), np.uint32)
    for j in range(per_word):
        packed |= q[:, j::per_word] << np.uint32(j * bits)
    return packed, scale[..., 0].astype(np.float16), w_min[..., 0].astype(np.float16)
