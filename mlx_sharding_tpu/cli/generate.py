"""CLI text generation — the reference's ``generate.py`` driver re-imagined.

Same operator surface (model path, prompt, sampling knobs, chat-template
application, streamed output, prompt/generation tok/s report —
ref: generate.py:12-20, 25-29, 90-122) but the execution underneath is the
TPU stack: single-chip jitted decode or the SPMD pipeline via
``--num-stages`` (which replaces the reference's ``--server-address`` list of
gRPC shard endpoints, ref generate.py:17 — stages are mesh slices here, not
remote processes). TTFT is reported explicitly, which the reference only
measures implicitly (SURVEY §6).
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None):
    parser = argparse.ArgumentParser(description="Generate text with mlx_sharding_tpu")
    parser.add_argument("--model", required=True, help="model path or HF repo")
    parser.add_argument("--prompt", default="hello")
    parser.add_argument("--max-tokens", type=int, default=100)
    parser.add_argument("--temperature", type=float, default=0.0)
    parser.add_argument("--top-p", type=float, default=1.0)
    parser.add_argument("--repetition-penalty", type=float, default=None)
    parser.add_argument("--seed", type=int, default=None)
    parser.add_argument("--max-seq", type=int, default=4096)
    parser.add_argument("--prefill-chunk", type=int, default=256)
    parser.add_argument("--start-layer", type=int, default=None)
    parser.add_argument("--end-layer", type=int, default=None)
    parser.add_argument("--num-stages", type=int, default=None,
                        help="run the model as an N-stage fused SPMD pipeline on the local mesh")
    parser.add_argument("--stage-bounds", default=None,
                        help="pipeline stage bounds, e.g. '0-14,14-27' "
                        "(uneven splits and MoE/dense mixes allowed)")
    parser.add_argument("--engine", choices=("fused", "chained"), default="fused",
                        help="pipeline engine for --stage-bounds: 'fused' runs all "
                        "stages as one SPMD program per token (default); 'chained' "
                        "uses per-stage programs with D2D hand-off")
    parser.add_argument("--tp", type=int, default=1,
                        help="tensor-parallel width within each pipeline "
                        "stage (Llama family)")
    parser.add_argument("--ep", type=int, default=1,
                        help="expert-parallel width within each pipeline "
                        "stage (MoE models)")
    parser.add_argument("--sp", type=int, default=None,
                        help="sequence-parallel prefill over N devices (ring "
                        "attention); prompts longer than one prefill chunk "
                        "shard their sequence dim")
    parser.add_argument("--sp-decode", action="store_true",
                        help="with --sp: keep the KV cache sequence-sharded "
                        "for the whole generation (distributed decode "
                        "attention) — capacity scales with the mesh instead "
                        "of one chip's HBM")
    parser.add_argument("--draft-model", default=None,
                        help="speculative decoding: a small draft model "
                        "proposes --spec-k tokens per round, the target "
                        "verifies them in one forward — greedy streams are "
                        "token-exact; sampled requests use rejection "
                        "sampling (distribution-exact)")
    parser.add_argument("--spec-k", type=int, default=4,
                        help="speculation window (with --draft-model)")
    parser.add_argument("--draft",
                        choices=("auto", "off", "ngram", "engine"),
                        default="auto",
                        help="draft source for speculative decoding: "
                        "'engine' is the two-model path (--draft-model), "
                        "'ngram' drafts by prompt-lookup — n-gram matches "
                        "against the prompt+history propose the window, no "
                        "second checkpoint and no draft KV; 'auto' picks "
                        "'engine' when --draft-model is given, else 'off'")
    parser.add_argument("--spec-window-max", type=int, default=None,
                        help="adaptive speculation ceiling (>= 2): per-round "
                        "acceptance (EWMA) resizes the window in {0,2,4,8} "
                        "up to this cap and disables drafting when it never "
                        "pays; with --draft ngram defaults to 8")
    parser.add_argument("--paged-attention",
                        choices=("auto", "ragged", "gather"), default="auto",
                        help="decode-attention path for paged pipeline "
                        "engines: 'ragged' attends over the KV page pool in "
                        "place (needs a pool — the engine validates), "
                        "'gather' keeps the contiguous per-slot view, 'auto' "
                        "picks ragged where supported; forwarded to the "
                        "engine, a no-op on dense single-stream runs")
    parser.add_argument("--async-sched",
                        choices=("on", "off", "auto"), default="auto",
                        help="tick pipelining for the continuous batcher: "
                        "dispatch decode block t+1 before harvesting block "
                        "t's tokens so host scheduling overlaps device "
                        "compute ('auto' enables it for plain decode, "
                        "disables it when a draft engine is attached); "
                        "accepted here for flag parity with the server — "
                        "the single-stream CLI path always harvests "
                        "synchronously, so this is a no-op")
    parser.add_argument("--kv-dtype", choices=("bf16", "int8"), default=None,
                        help="KV-pool storage dtype; accepted for flag "
                        "parity with the server. 'int8' needs a paged pool "
                        "(server --concurrent/--paged-pool) — the "
                        "single-stream CLI allocates dense caches, so only "
                        "'bf16' is valid here")
    parser.add_argument("--keep-quantized", action="store_true",
                        help="keep 4-bit decoder weights packed in HBM "
                        "(fused dequant-matmul) instead of dequantizing at "
                        "load")
    parser.add_argument("--no-chat-template", action="store_true")
    args = parser.parse_args(argv)
    if args.engine == "chained" and not args.stage_bounds:
        parser.error("--engine chained requires --stage-bounds")
    if (args.tp > 1 or args.ep > 1) and args.engine == "chained":
        parser.error("--tp/--ep require the fused engine")
    if args.sp and (args.stage_bounds or args.num_stages or args.tp > 1 or args.ep > 1):
        parser.error("--sp applies to the single-stage generator only")
    if args.sp_decode and not (args.sp and args.sp > 1):
        parser.error("--sp-decode requires --sp N (N > 1)")
    if args.draft_model and (args.sp or args.stage_bounds or args.num_stages
                             or args.tp > 1 or args.ep > 1):
        parser.error("--draft-model applies to the single-chip generator")
    if args.draft == "engine" and not args.draft_model:
        parser.error("--draft engine requires --draft-model")
    if args.draft in ("off", "ngram") and args.draft_model:
        parser.error(f"--draft {args.draft} conflicts with --draft-model "
                     "(drop one: 'engine' is the two-model path)")
    if args.draft == "ngram" and (args.sp or args.stage_bounds
                                  or args.num_stages or args.tp > 1
                                  or args.ep > 1):
        parser.error("--draft ngram applies to the single-chip generator")
    if args.spec_window_max is not None:
        if args.spec_window_max < 2:
            parser.error("--spec-window-max must be >= 2 (a 1-token window "
                         "is plain decode; use --draft off)")
        if args.draft not in ("ngram", "engine") and not args.draft_model:
            parser.error("--spec-window-max needs a draft source "
                         "(--draft ngram or --draft-model)")
    if args.kv_dtype == "int8":
        parser.error("--kv-dtype int8 requires a paged KV pool; serve with "
                     "--concurrent N --paged-pool P instead")

    import jax.numpy as jnp

    from mlx_sharding_tpu.generate import Generator, stream_generate
    from mlx_sharding_tpu.loading import get_model_path, load_model

    if args.stage_bounds and args.engine == "chained":
        from mlx_sharding_tpu.parallel.chained import load_chained_pipeline

        bounds = [
            tuple(int(x) for x in part.split("-"))
            for part in args.stage_bounds.split(",")
        ]
        generator = load_chained_pipeline(
            args.model, bounds, max_seq=args.max_seq,
            prefill_chunk=args.prefill_chunk,
            keep_quantized=args.keep_quantized,
        )
    elif args.stage_bounds or (args.num_stages and args.num_stages > 1) or args.tp > 1 or args.ep > 1:
        from mlx_sharding_tpu.parallel.mesh import make_mesh
        from mlx_sharding_tpu.parallel.pipeline import PipelineEngine

        bounds = None
        if args.stage_bounds:
            bounds = [
                tuple(int(x) for x in part.split("-"))
                for part in args.stage_bounds.split(",")
            ]
        model, params = load_model(
            args.model, args.start_layer, args.end_layer,
            keep_quantized=args.keep_quantized,
        )
        generator = PipelineEngine(
            model, params,
            make_mesh(pp=len(bounds) if bounds else (args.num_stages or 1),
                      tp=args.tp, ep=args.ep),
            stage_bounds=bounds,
            max_seq=args.max_seq, prefill_chunk=args.prefill_chunk,
            paged_attention=args.paged_attention,
            kv_dtype=args.kv_dtype,
        )
    else:
        model, params = load_model(
            args.model, args.start_layer, args.end_layer,
            keep_quantized=args.keep_quantized,
        )
        sp_mesh = None
        if args.sp and args.sp > 1:
            from mlx_sharding_tpu.parallel.mesh import make_mesh

            sp_mesh = make_mesh(sp=args.sp)
        if args.draft == "ngram":
            from mlx_sharding_tpu.speculative import NgramSpeculativeGenerator

            generator = NgramSpeculativeGenerator(
                model, params,
                spec_window_max=args.spec_window_max or 8,
                max_seq=args.max_seq,
                prefill_chunk=args.prefill_chunk,
            )
        elif args.draft_model:
            from mlx_sharding_tpu.speculative import SpeculativeGenerator

            draft_model, draft_params = load_model(args.draft_model)
            generator = SpeculativeGenerator(
                model, params, draft_model, draft_params,
                spec_k=args.spec_k, max_seq=args.max_seq,
                prefill_chunk=args.prefill_chunk,
            )
        else:
            generator = Generator(
                model, params, max_seq=args.max_seq,
                prefill_chunk=args.prefill_chunk, sp_mesh=sp_mesh,
                sp_decode=args.sp_decode,
            )

    from transformers import AutoTokenizer

    tokenizer = AutoTokenizer.from_pretrained(str(get_model_path(args.model)))
    if getattr(tokenizer, "chat_template", None) and not args.no_chat_template:
        prompt_ids = tokenizer.apply_chat_template(
            [{"role": "user", "content": args.prompt}],
            tokenize=True, add_generation_prompt=True,
        )
    else:
        prompt_ids = tokenizer.encode(args.prompt)

    stats = None
    for chunk in stream_generate(
        generator, tokenizer, list(prompt_ids),
        max_tokens=args.max_tokens,
        temperature=args.temperature,
        top_p=args.top_p,
        repetition_penalty=args.repetition_penalty,
        seed=args.seed,
    ):
        if chunk.text:
            print(chunk.text, end="", flush=True)
        if chunk.finish_reason is not None:
            stats = chunk
    print()
    # same instrumentation the reference prints (ref generate.py:115-122)
    print("=" * 10, file=sys.stderr)
    print(
        f"Prompt: {stats.prompt_tokens} tokens, {stats.prompt_tps:.3f} tokens-per-sec",
        file=sys.stderr,
    )
    print(
        f"Generation: {stats.generation_tokens} tokens, "
        f"{stats.generation_tps:.3f} tokens-per-sec",
        file=sys.stderr,
    )
    print(f"TTFT: {stats.ttft * 1000:.1f} ms", file=sys.stderr)


if __name__ == "__main__":
    main()
