"""Offline low-rank KV calibration (TPLA-style, arXiv:2508.15881) — emit
a compress-map artifact for ``--kv-compress-map``.

One dense prefill per calibration prompt, the resulting KV buffers
flattened to per-layer row matrices ``(tokens, H*D)``, and a truncated
SVD per layer: the top-``r`` right-singular vectors become the down/up
projection pair the serving codec (kv_compress.py) applies at every
KV-transport boundary — spill flushes, prefix-store demotions, disagg
handoffs, pod-federation blobs. The artifact stamps the per-layer
relative reconstruction error over the calibration set: that number IS
the documented parity tolerance for the lossy path (MLA-native models
need no artifact; their latent export is exact).

When the serving pool runs under a KV share map (``--kv-share-map``),
pass the SAME artifact here: the pool stores one buffer per share group
(written by the group's owner layer), so calibration fits one projection
per GROUP over the owner layer's rows and stamps the share map's hash —
kv_compress.build_codec refuses a compress map whose ``share_hash``
doesn't match the live pool, so the two calibrations compose or neither
loads.

Calibration is OFFLINE by design: dense prefills and whole-buffer
host marshalling are exactly the traffic mstcheck MST115/MST116 keep out
of the serving tick.

Usage::

    python -m mlx_sharding_tpu.cli.kv_compress_calibrate \
        --model path/or/hf-repo --rank 32 \
        --prompts-file calib.txt --output compress_map.npz
"""

from __future__ import annotations

import argparse
import sys


def calibrate_model(model, params, prompts_ids, *, rank: int,
                    share_map=None, cache_dtype=None, meta=None):
    """Core calibration over already-tokenized prompts: one dense prefill
    each, KV rows concatenated along the sequence axis, one per-layer SVD
    map out. Importable so tests can calibrate a tiny model without the
    CLI's checkpoint loading. ``share_map`` (a kv_share.KVShareMap)
    reduces the layer axis to group owners and stamps ``share_hash``."""
    import jax.numpy as jnp
    import numpy as np

    from mlx_sharding_tpu.kv_compress import (
        CompressError,
        calibrate_compress_map,
    )

    if cache_dtype is None:
        cache_dtype = jnp.float32
    ks, vs = [], []
    total_tokens = 0
    for ids in prompts_ids:
        ids = np.asarray(ids, np.int32)
        if ids.ndim != 1 or ids.size < 2:
            raise CompressError(
                "calibration prompts need >= 2 tokens each"
            )
        n = int(ids.size)
        cache = model.make_cache(1, n, cache_dtype)
        _, cache = model(params, jnp.asarray(ids)[None, :], cache,
                         n_valid=jnp.asarray(n, jnp.int32))
        ks.append(np.asarray(cache.k, np.float32)[:, :, :n])
        vs.append(np.asarray(cache.v, np.float32)[:, :, :n])
        total_tokens += n
    k = np.concatenate(ks, axis=2)
    v = np.concatenate(vs, axis=2)
    share_hash = None
    if share_map is not None and not share_map.is_identity:
        share_map.validate_for(k.shape[0])
        owners = list(share_map.owner_layers())
        # the grouped pool holds the owner layer's KV for every member of
        # its group — fit the projection on what the pool will contain
        k, v = k[owners], v[owners]
        share_hash = share_map.share_hash
    info = dict(meta or {})
    info.update({
        "calibration_prompts": len(ks),
        "calibration_tokens": total_tokens,
    })
    return calibrate_compress_map(
        k, v, rank=rank, share_hash=share_hash, meta=info
    )


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Calibrate a low-rank KV compress map (kv_compress)"
    )
    parser.add_argument("--model", required=True,
                        help="model path or HF repo (same as generate)")
    parser.add_argument("--rank", type=int, required=True,
                        help="latent rank r: exported blocks ship "
                        "(tokens, r) coefficients instead of (tokens, "
                        "H*D) rows — bytes scale ~ r/(H*D)")
    parser.add_argument("--kv-share-map", default=None, metavar="PATH",
                        help="the share-map artifact the serving pool "
                        "runs under, if any: calibrates per share GROUP "
                        "and stamps its hash so the artifacts compose")
    parser.add_argument("--prompts-file", default=None,
                        help="calibration prompts, one per line (default: "
                        "a small built-in English mix)")
    parser.add_argument("--max-prompt-tokens", type=int, default=512)
    parser.add_argument("--output", required=True,
                        help="where to write the compress-map .npz "
                        "artifact")
    args = parser.parse_args(argv)

    from transformers import AutoTokenizer

    from mlx_sharding_tpu.kv_share import load_share_map
    from mlx_sharding_tpu.loading import get_model_path, load_model

    if args.prompts_file:
        with open(args.prompts_file) as f:
            prompts = [ln.strip() for ln in f if ln.strip()]
    else:
        prompts = [
            "The quick brown fox jumps over the lazy dog.",
            "In a distant galaxy, explorers charted unknown worlds.",
            "Summarize the quarterly report in three bullet points.",
        ]
    if not prompts:
        print("no calibration prompts", file=sys.stderr)
        return 2

    model_path = get_model_path(args.model)
    model, params = load_model(model_path)
    tokenizer = AutoTokenizer.from_pretrained(str(model_path))
    ids = [
        tokenizer.encode(p)[: args.max_prompt_tokens] for p in prompts
    ]
    m = calibrate_model(
        model, params, [i for i in ids if len(i) >= 2],
        rank=args.rank, share_map=load_share_map(args.kv_share_map),
        meta={"model": str(args.model)},
    )
    m.save(args.output)
    cal = m.meta["calibration"]
    print(
        f"wrote {args.output}: {m.num_layers} layers, rank {m.rank} over "
        f"{m.num_heads}x({m.head_dim_k},{m.head_dim_v}) rows, "
        f"max_rel_err={cal['max_rel_err']:.2e}, "
        f"compress_hash={m.compress_hash}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
