"""Offline KVSharer calibration (arXiv:2410.18517) — emit a share-map
artifact for ``--kv-share-map``.

One dense prefill per calibration prompt, per-layer KV signatures off the
resulting cache, every layer pair ranked by dissimilarity (1 − cosine;
KVSharer's counterintuitive finding is that the MOST dissimilar pairs are
the safe ones to share), then a greedy merge of the top ``--num-share``
pairs under the ``--max-group`` cap. The resulting
``mst-kv-share-map-v1`` JSON (kv_share.py) is what the server, bench, and
CLI load with ``--kv-share-map PATH``; its ``share_hash`` joins the
``KVPageBlock`` export/import fingerprint so a pool can never scatter a
block laid out under a different map.

Calibration is OFFLINE by design: it runs dense prefills and marshals
whole KV buffers to host numpy — exactly the traffic mstcheck MST115
keeps out of the serving tick.

Usage::

    python -m mlx_sharding_tpu.cli.kv_share_calibrate \
        --model path/or/hf-repo --num-share 8 \
        --prompts-file calib.txt --output share_map.json
"""

from __future__ import annotations

import argparse
import sys


def calibrate_model(model, params, prompts_ids, *, num_share: int,
                    max_group: int = 2, cache_dtype=None, meta=None):
    """Core calibration over already-tokenized prompts: one dense prefill
    each, signatures concatenated along the sequence axis, one greedy
    share map out. Importable so tests (and notebooks) can calibrate a
    tiny model without the CLI's checkpoint loading."""
    import jax.numpy as jnp
    import numpy as np

    from mlx_sharding_tpu.kv_share import ShareMapError, calibrate_share_map

    if cache_dtype is None:
        cache_dtype = jnp.float32
    ks, vs = [], []
    total_tokens = 0
    for ids in prompts_ids:
        ids = np.asarray(ids, np.int32)
        if ids.ndim != 1 or ids.size < 2:
            raise ShareMapError(
                "calibration prompts need >= 2 tokens each"
            )
        n = int(ids.size)
        cache = model.make_cache(1, n, cache_dtype)
        _, cache = model(params, jnp.asarray(ids)[None, :], cache,
                         n_valid=jnp.asarray(n, jnp.int32))
        ks.append(np.asarray(cache.k, np.float32)[:, :, :n])
        vs.append(np.asarray(cache.v, np.float32)[:, :, :n])
        total_tokens += n
    k = np.concatenate(ks, axis=2)
    v = np.concatenate(vs, axis=2)
    info = dict(meta or {})
    info.update({
        "calibration_prompts": len(ks),
        "calibration_tokens": total_tokens,
    })
    return calibrate_share_map(
        k, v, num_share=num_share, max_group=max_group, meta=info
    )


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Calibrate a layer-wise KV share map (KVSharer)"
    )
    parser.add_argument("--model", required=True,
                        help="model path or HF repo (same as generate)")
    parser.add_argument("--num-share", type=int, required=True,
                        help="how many layer pairs to merge; each merged "
                        "pair removes one layer's KV pool bytes")
    parser.add_argument("--max-group", type=int, default=2,
                        help="cap on layers per shared group (the paper "
                        "shares pairs; >2 compounds quality loss)")
    parser.add_argument("--prompts-file", default=None,
                        help="calibration prompts, one per line (default: "
                        "a small built-in English mix)")
    parser.add_argument("--max-prompt-tokens", type=int, default=512)
    parser.add_argument("--output", required=True,
                        help="where to write the share-map JSON artifact")
    args = parser.parse_args(argv)

    from transformers import AutoTokenizer

    from mlx_sharding_tpu.loading import get_model_path, load_model

    if args.prompts_file:
        with open(args.prompts_file) as f:
            prompts = [ln.strip() for ln in f if ln.strip()]
    else:
        prompts = [
            "The quick brown fox jumps over the lazy dog.",
            "In a distant galaxy, explorers charted unknown worlds.",
            "Summarize the quarterly report in three bullet points.",
        ]
    if not prompts:
        print("no calibration prompts", file=sys.stderr)
        return 2

    model_path = get_model_path(args.model)
    model, params = load_model(model_path)
    tokenizer = AutoTokenizer.from_pretrained(str(model_path))
    ids = [
        tokenizer.encode(p)[: args.max_prompt_tokens] for p in prompts
    ]
    m = calibrate_model(
        model, params, [i for i in ids if len(i) >= 2],
        num_share=args.num_share, max_group=args.max_group,
        meta={"model": str(args.model)},
    )
    m.save(args.output)
    print(
        f"wrote {args.output}: {m.num_layers} layers -> {m.num_groups} "
        f"groups ({m.bytes_saved_fraction():.1%} KV pool bytes saved), "
        f"share_hash={m.share_hash}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
