"""Native checkpoints — Orbax save/restore of the scan-ready param pytree.

The safetensors path (loading.py / shard_tool.py) exists for checkpoint
compatibility with the reference's ecosystem; this module is the TPU-native
format: the *already stacked, already transposed* parameter pytree lands on
disk via Orbax, so a stage restore is a straight async read into (sharded)
device buffers with zero name-remapping or per-tensor transposes — the
"per-stage checkpoint emission" upgrade SURVEY §5 (checkpoint/resume) calls
for. The model config (with its baked stage bounds, same idea as
sharding_weight.py:48-60) rides alongside as JSON.
"""

from __future__ import annotations

import json
from pathlib import Path

NATIVE_MARKER = "native_checkpoint.json"


def save_native_checkpoint(path: str | Path, params: dict, config) -> Path:
    """Write params (Orbax) + config (JSON). ``config`` is a BaseConfig or a
    plain dict."""
    import orbax.checkpoint as ocp

    path = Path(path).resolve()
    path.mkdir(parents=True, exist_ok=True)
    config_dict = config if isinstance(config, dict) else config.to_dict()
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path / "params", params, force=True)
    (path / NATIVE_MARKER).write_text(json.dumps(config_dict, indent=2))
    return path


def is_native_checkpoint(path: str | Path) -> bool:
    return (Path(path) / NATIVE_MARKER).is_file()


def load_native_checkpoint(
    path: str | Path,
    start_layer: int | None = None,
    end_layer: int | None = None,
    dtype=None,
):
    """Returns (model, params). Stage bounds may be overridden only to the
    bounds the checkpoint actually contains (native checkpoints are already
    stage-filtered). ``dtype`` requests the floating-point dtype of the
    restored params (matching the safetensors path's contract).

    Restore goes through an abstract target pytree (shapes/dtypes from
    ``model.init_params`` under ``eval_shape``) so Orbax can read directly
    into buffers of the requested dtype rather than materializing host numpy
    first; a plain restore + cast is the fallback for structure drift."""
    import jax
    import jax.numpy as jnp
    import orbax.checkpoint as ocp

    from mlx_sharding_tpu.models import build_model

    path = Path(path).resolve()
    config_dict = json.loads((path / NATIVE_MARKER).read_text())
    if start_layer is not None or end_layer is not None:
        baked = (config_dict.get("start_layer", 0), config_dict.get("end_layer"))
        wanted = (
            start_layer if start_layer is not None else baked[0],
            end_layer if end_layer is not None else baked[1],
        )
        if wanted != baked:
            raise ValueError(
                f"native checkpoint holds layers {baked}, cannot re-slice to "
                f"{wanted}; re-shard from the source checkpoint instead"
            )
    model, config = build_model(config_dict)
    if not (path / "params").exists():
        raise FileNotFoundError(
            f"native checkpoint at {path} has its marker but no params/ "
            "payload — re-emit it (shard_tool --emit-native) or check the "
            "download included params/**"
        )
    dtype = dtype or jnp.bfloat16
    try:
        abstract = jax.eval_shape(
            lambda: model.init_params(jax.random.PRNGKey(0), dtype)
        )
        with ocp.StandardCheckpointer() as ckptr:
            params = ckptr.restore(path / "params", abstract)
    except Exception:
        with ocp.StandardCheckpointer() as ckptr:
            params = ckptr.restore(path / "params")
        params = jax.tree.map(
            lambda x: x.astype(dtype)
            if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
            else x,
            params,
        )
    return model, params
