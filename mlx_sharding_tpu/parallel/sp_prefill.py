"""Sequence-parallel prefill: long prompts sharded over the ``sp`` axis.

The reference's long-context story is "none" — the whole prompt goes through
every stage in one call with a dense T×T mask (SURVEY §5). The framework's
chunked prefill already bounds memory; this module adds the scaling axis the
reference never had: the prompt's SEQUENCE dim is sharded over ``sp``
devices, each device projects Q/K/V for its local T/S tokens (RoPE at global
positions), attention runs as ring attention (K/V blocks rotate over ICI
with a streaming-softmax accumulator — exact, no T×T anything), and the MLP
halves stay local. One program prefills the entire prompt with per-device
activation memory O(T/S).

The resulting per-layer K/V (already rotated) either all-gathers into the
standard decode cache (default: generation continues on the ordinary
single-device/pipeline decode path) or — ``keep_sharded`` — stays
sequence-sharded and feeds ``parallel.sp_decode``'s distributed decode,
which removes the single-chip KV bound entirely. Contract: bit-compatible
logits with the dense prefill (tested sp=4 vs sp=1 in
tests/test_sp_prefill.py; decode parity in tests/test_sp_decode.py).

Wired through the model-level ``sp_layer``/``sp_groups`` hooks: the Llama
family (default hook pair), Gemma-2 (per-layer sliding/global windows +
logit softcap, window-aware ring block skipping) and DeepSeek-V2 (MLA —
compressed-latent MQA with values_from_k, grouped dense/moe scan).
Architectures without ``supports_sp`` keep the chunked path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mlx_sharding_tpu.cache import KVCache
from mlx_sharding_tpu.parallel.mesh import AXIS_SP, shard_map
from mlx_sharding_tpu.parallel.ring_attention import ring_attention_local


def supports_sp_prefill(model) -> bool:
    cfg = model.config
    return (
        getattr(model, "supports_sp", False)
        and cfg.is_first_stage
        and cfg.is_last_stage  # needs embed + head in-params
    )


def sp_ring_attn_fn(model):
    """The prefill-side attention injected into ``model.sp_layer``: exact
    ring attention over the sp axis, honoring the model's per-layer options
    (Gemma-2 softcap/window; MLA's values-live-in-keys)."""

    def attn_fn(q, k, v, logit_softcap=None, sliding_window=None,
                values_from_k=None):
        # values_from_k passes straight through: the ring then rotates ONLY
        # the key blocks and slices values per step (half the ICI bytes)
        return ring_attention_local(
            q, k, v, model.scale,
            logit_softcap=logit_softcap, sliding_window=sliding_window,
            values_from_k=values_from_k,
        )

    return attn_fn


def build_sp_prefill(model, mesh: Mesh, gather: bool = True):
    """Returns ``fn(params, tokens (B, T_padded), n_valid) -> (logits (B,V),
    ks, vs)`` where ks/vs are (L, B, T_padded, Hkv, D) K/V — all-gathered
    when ``gather`` (single-device decode cache) or left sequence-sharded
    over sp (``parallel.sp_decode`` keeps them sharded for the whole
    generation). T_padded must divide by the sp size; positions >= n_valid
    are padding (their K/V land in cache rows the decode loop
    overwrites/never attends).
    """

    attn_fn = sp_ring_attn_fn(model)

    def body(params, tokens, n_valid):
        idx = jax.lax.axis_index(AXIS_SP)
        t_local = tokens.shape[1]
        offset = idx * t_local  # global position of this device's first token

        h = model.embed(params, tokens)

        # one scan per structurally distinct layer group (DeepSeek's
        # dense/moe split; [None] = the whole homogeneous stack), cache
        # rows concatenated back in layer order
        ks_groups, vs_groups = [], []
        for g in model.sp_groups():
            stack = params["layers"] if g is None else params["layers"][g]

            def layer_body(h, p, _g=g):
                h, k, v = model.sp_layer(p, h, offset, attn_fn, group=_g)
                return h, (k, v)

            h, (ks, vs) = jax.lax.scan(layer_body, h, stack)
            ks_groups.append(ks)
            vs_groups.append(vs)
        ks = (
            jnp.concatenate(ks_groups, axis=0)
            if len(ks_groups) > 1 else ks_groups[0]
        )
        vs = (
            jnp.concatenate(vs_groups, axis=0)
            if len(vs_groups) > 1 else vs_groups[0]
        )

        # last REAL position lives on device (n_valid-1) // t_local
        local_last = jnp.clip(n_valid - 1 - offset, 0, t_local - 1)
        last = jax.lax.dynamic_index_in_dim(h, local_last, 1, keepdims=False)
        logits = model.apply_head(params, last).astype(jnp.float32)
        owner = (n_valid - 1) // t_local == idx
        logits = jax.lax.psum(jnp.where(owner, logits, 0.0), AXIS_SP)

        if gather:
            # (L, B, T_local, H, D) -> full (L, B, T, H, D) for the decode cache
            ks = jax.lax.all_gather(ks, AXIS_SP, axis=2, tiled=True)
            vs = jax.lax.all_gather(vs, AXIS_SP, axis=2, tiled=True)
        return logits, ks, vs

    seq_spec = P(None, AXIS_SP)
    rep = P()
    kv_out = rep if gather else P(None, None, AXIS_SP)

    def make(params_tree):
        return jax.jit(
            shard_map(
                body,
                mesh=mesh,
                in_specs=(jax.tree.map(lambda _: rep, params_tree), seq_spec, rep),
                out_specs=(rep, kv_out, kv_out),
                check_vma=False,
            )
        )

    return make


class SpPrefill:
    """Compiled, reusable sequence-parallel prefill for one (model, mesh).

    Built once per Generator (mirrors how ``_prefill`` is jitted once).
    Prompt lengths are bucketed to multiples of ``sp_size * prefill_chunk``
    so the number of distinct compiled shapes stays bounded. Params are
    replicated over the sp mesh ONCE at construction — every sp device needs
    the full weights anyway; the cost is one extra replica on the default
    device next to the generator's own copy.
    """

    def __init__(self, model, params, mesh: Mesh, prefill_chunk: int,
                 keep_sharded: bool = False):
        self.model = model
        self.mesh = mesh
        self.size = mesh.shape[AXIS_SP]
        self.quantum = self.size * prefill_chunk
        self.keep_sharded = keep_sharded
        self._make = build_sp_prefill(model, mesh, gather=not keep_sharded)
        self._fn = None  # shape-polymorphic jit; compiles per T_pad bucket
        self._rep = NamedSharding(mesh, P())
        self._seq = NamedSharding(mesh, P(None, AXIS_SP))
        self.params = jax.device_put(params, self._rep)

        def write(cache, ks, vs, n_valid):
            zero = jnp.zeros((), jnp.int32)
            k = jax.lax.dynamic_update_slice(
                cache.k, ks.astype(cache.k.dtype), (zero,) * cache.k.ndim
            )
            v = jax.lax.dynamic_update_slice(
                cache.v, vs.astype(cache.v.dtype), (zero,) * cache.v.ndim
            )
            return KVCache(k=k, v=v, offset=n_valid)

        self._write = jax.jit(write, donate_argnums=(0,))

    def padded_len(self, t: int) -> int:
        return -(-t // self.quantum) * self.quantum

    def prefill_sharded(self, prompt: np.ndarray):
        """Sharded-mode prefill: returns (logits (B, V) replicated, ks, vs
        (L, B, T_pad, H, D) sequence-sharded over sp). The caller installs
        ks/vs into an sp-sharded decode cache (SpDecode.write_prefill)."""
        t = prompt.shape[1]
        tokens = np.pad(prompt, ((0, 0), (0, self.padded_len(t) - t)))
        if self._fn is None:
            self._fn = self._make(self.params)
        return self._fn(
            self.params,
            jax.device_put(jnp.asarray(tokens), self._seq),
            jax.device_put(jnp.asarray(t, jnp.int32), self._rep),
        )

    def __call__(self, prompt: np.ndarray, cache: KVCache):
        """Prefill ``prompt`` (B, T) into ``cache``; returns (logits, cache).
        Padded K/V rows sit beyond ``offset`` and are never attended (causal
        masking by offset) before being overwritten by decode."""
        t = prompt.shape[1]
        t_pad = self.padded_len(t)
        if t_pad > cache.max_seq:
            raise ValueError(
                f"sp prefill needs {t_pad} cache rows, capacity {cache.max_seq}"
            )
        tokens = np.pad(prompt, ((0, 0), (0, t_pad - t)))
        if self._fn is None:
            self._fn = self._make(self.params)
        logits, ks, vs = self._fn(
            self.params,
            jax.device_put(jnp.asarray(tokens), self._seq),
            jax.device_put(jnp.asarray(t, jnp.int32), self._rep),
        )
        # the gathered K/V is replicated over sp; hand the default device's
        # copy to the single-device decode cache without a host round-trip
        dev = jax.devices()[0]
        cache = self._write(
            cache,
            jax.device_put(ks, dev),
            jax.device_put(vs, dev),
            jax.device_put(jnp.asarray(t, jnp.int32), dev),
        )
        return jax.device_put(logits, dev), cache
