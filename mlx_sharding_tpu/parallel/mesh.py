"""Device-mesh construction.

This is the framework's replacement for the reference's process topology —
where the reference identifies a "shard" with a gRPC server process at an IP
(ref: generate.py:17, shard/openai_api.py:621-627), here a stage is a slice
of a ``jax.sharding.Mesh`` and topology is declared once, not dialed.

Axis conventions (the names the rest of the codebase shards against):
  dp — data / batch replication
  pp — pipeline stages (the reference's only axis, §2.3)
  sp — sequence/context parallelism (ring attention)
  tp — tensor parallelism within a stage
  ep — expert parallelism rides on tp for MoE layers

Multi-host: callers run ``jax.distributed.initialize()`` first (DCN), then
``make_mesh`` over ``jax.devices()`` spans hosts; mesh-axis order puts tp/sp
innermost so their collectives ride ICI, pp/dp outermost so stage hops and
gradient syncs cross DCN only when they must (scaling-book recipe).
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh

AXIS_DP = "dp"
AXIS_PP = "pp"
AXIS_SP = "sp"
AXIS_EP = "ep"
AXIS_TP = "tp"

# outermost → innermost; innermost axes get the fastest interconnect links
MESH_AXIS_ORDER = (AXIS_DP, AXIS_PP, AXIS_SP, AXIS_EP, AXIS_TP)


def make_mesh(
    dp: int = 1, pp: int = 1, sp: int = 1, tp: int = 1, ep: int = 1, devices=None
) -> Mesh:
    if devices is None:
        devices = jax.devices()
    n = dp * pp * sp * ep * tp
    if n > len(devices):
        raise ValueError(
            f"mesh dp={dp} pp={pp} sp={sp} ep={ep} tp={tp} needs {n} devices, "
            f"have {len(devices)}"
        )
    grid = np.asarray(devices[:n]).reshape(dp, pp, sp, ep, tp)
    return Mesh(grid, MESH_AXIS_ORDER)


def mesh_fingerprint(mesh: Mesh) -> str:
    """Stable identity of a mesh's placement: axis geometry plus the exact
    device grid, in order. This is the placement half of a
    ``weights.WeightKey`` — resident arrays are device-addressed, so WHERE
    a weight tree lives is part of WHAT it is, and two replicas may alias
    one tree only when their meshes print the same fingerprint."""
    axes = ",".join(f"{k}={v}" for k, v in mesh.shape.items())
    devs = ",".join(str(d.id) for d in mesh.devices.flat)
    return f"{axes}|{devs}"


def same_mesh_devices(a: Mesh, b: Mesh) -> bool:
    """True when two meshes span identical device grids — same axis sizes,
    same devices, same order. That is the condition for arrays placed
    against one mesh to feed programs shard_mapped over the other without
    a cross-device transfer (jit rejects a device-set mismatch outright),
    i.e. for a ``ResidentWeights`` built on ``a`` to be aliased by an
    engine running on ``b``."""
    return (
        dict(a.shape) == dict(b.shape)
        and [d.id for d in a.devices.flat] == [d.id for d in b.devices.flat]
    )


def pipeline_mesh(num_stages: int, devices=None) -> Mesh:
    """1-D pipeline mesh — the parity topology (reference §2.3: PP is the
    only strategy)."""
    return make_mesh(pp=num_stages, devices=devices)


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
    """``jax.shard_map`` with a fallback for jax installs that predate its
    promotion out of ``jax.experimental`` (where the replication-check
    kwarg was still called ``check_rep``)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )


def axis_size(axis_name) -> int:
    """Static mesh-axis size inside a shard_map body. ``jax.lax.axis_size``
    only exists on newer jax; older installs expose the same integer via
    ``jax.core.axis_frame`` (an int there, a frame object elsewhere)."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    import jax.core as _core

    frame = _core.axis_frame(axis_name)
    return frame if isinstance(frame, int) else frame.size
