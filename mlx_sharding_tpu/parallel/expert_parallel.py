"""Expert parallelism: experts sharded over the ``ep`` mesh axis.

The reference keeps MoE experts fused inside the owning pipeline stage
(SURVEY §2.3 "EP: NO — fused and replicated within the owning stage"), and
that remains this framework's default (ops/moe.py). This module is the
scale-out path the reference never had: the expert stacks (E, …) shard over
``ep``, every device computes only its resident experts' contribution for
ALL tokens (masked accumulation, static shapes), and one ``psum`` over
``ep`` combines — routing stays replicated so there is no all-to-all, just
the single reduction riding ICI. Token counts per expert never need to be
known at compile time, so there is no capacity factor and no dropping.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mlx_sharding_tpu.parallel.mesh import AXIS_EP, shard_map


def expert_parallel_apply(
    x: jax.Array,  # (N, H) tokens
    weights: jax.Array,  # (N, K) routing weights
    idx: jax.Array,  # (N, K) expert ids (global)
    w_gate: jax.Array,  # (E, H, I)
    w_up: jax.Array,  # (E, H, I)
    w_down: jax.Array,  # (E, I, H)
    mesh: Mesh,
    axis_name: str = AXIS_EP,
) -> jax.Array:
    """SwiGLU expert application with experts sharded over ``axis_name``.
    Exactly matches ops.moe.apply_experts run on one device."""
    size = mesh.shape[axis_name]
    num_experts = w_gate.shape[0]
    if num_experts % size:
        raise ValueError(f"{num_experts} experts not divisible over ep={size}")

    def local(x, weights, idx, w_gate, w_up, w_down):
        # local expert block e_local corresponds to global id base + e_local
        base = jax.lax.axis_index(axis_name) * (num_experts // size)

        def body(acc, xs):
            wg, wu, wd, e_local = xs
            coef = ((idx == base + e_local) * weights).sum(axis=-1)  # (N,)
            y = (jax.nn.silu(x @ wg) * (x @ wu)) @ wd
            return acc + coef[:, None].astype(y.dtype) * y, None

        acc0 = jnp.zeros_like(x)
        acc, _ = jax.lax.scan(
            body, acc0,
            (w_gate, w_up, w_down, jnp.arange(num_experts // size)),
        )
        return jax.lax.psum(acc, axis_name)

    expert_spec = P(axis_name)
    rep = P()
    f = shard_map(
        local,
        mesh=mesh,
        in_specs=(rep, rep, rep, expert_spec, expert_spec, expert_spec),
        out_specs=rep,
        check_vma=False,
    )
    shard = NamedSharding(mesh, expert_spec)
    repl = NamedSharding(mesh, rep)
    return f(
        jax.device_put(x, repl),
        jax.device_put(weights, repl),
        jax.device_put(idx, repl),
        jax.device_put(w_gate, shard),
        jax.device_put(w_up, shard),
        jax.device_put(w_down, shard),
    )
