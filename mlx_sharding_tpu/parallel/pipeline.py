"""SPMD collective pipeline — the framework's core.

This module replaces the reference's entire distributed execution model. The
reference chains pipeline stages with one blocking gRPC round-trip per stage
per token — serialize, TCP, Python-deserialize (ref: shard/utils.py:162-164,
shard/server/server.py:27-57; cost analysis SURVEY §3.5). Here the whole
multi-stage token step is ONE compiled XLA program on a ``pp`` mesh axis:
every stage's layers run where their weights live, and the activation hand-off
is a ``lax.ppermute`` hop over ICI — HBM-to-HBM, zero host involvement.

Schedule (GPipe-style collective pipeline): with S stages and M microbatches,
the program runs ``S+M-1`` ticks inside a ``lax.scan``. At tick ``t`` device
``s`` processes microbatch ``m = t - s`` (real iff ``0 <= m < M``); stage 0
injects embedded tokens, the last stage banks logits, and a single ``psum``
at the end replicates the (M, B, V) logits to every device so sampling can
run redundantly-deterministically on all of them — the sampled token is the
only thing that ever leaves the device. M=1 gives the reference's
single-request decode; M>1 fills the pipeline bubble for batch serving
(BASELINE.json config #5: microbatched decode).

Correctness of garbage ticks: devices compute every tick, but
- cache writes on non-real ticks are routed to a scratch microbatch slice
  (index M in an (M+1)-slot cache axis), so they can never corrupt state;
- logits writes on non-real ticks land on microbatch 0 strictly *before*
  its real write (t < S-1 implies writes precede the real tick S-1);
- the shared cache offset advances once per step outside the tick loop, so
  garbage ticks cannot desynchronize positions.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mlx_sharding_tpu.cache import (
    KVCache,
    dequantize_kv,
    quantize_kv_rows,
)
from mlx_sharding_tpu.ops.quant import dequantize, is_quantized
from mlx_sharding_tpu.parallel.mesh import (
    AXIS_EP,
    AXIS_PP,
    AXIS_TP,
    same_mesh_devices,
    shard_map,
)
from mlx_sharding_tpu.weights import ResidentWeights
from mlx_sharding_tpu.sample import (
    SamplerParams,
    init_recent_tokens,
    make_sampler_params,
    nucleus_logits_batched,
    sample_token,
    sample_token_batched,
    transform_logits_batched,
    update_recent_tokens,
)


def put_global(tree, shardings):
    """``jax.device_put`` that is safe across processes. Single-process it IS
    device_put. Multi-process, ``device_put`` of host data onto a
    process-spanning sharding first broadcasts the whole tree through the
    control plane to assert every rank passed identical values — for model
    params and cache zeros that is pure overhead (every rank loaded the same
    checkpoint / computes the same zeros), it is the slowest possible way to
    place a model, and gloo-backed CPU ranks crash outright on large
    payloads. Build each global array from the local copy instead: no
    cross-host value traffic at all. ``shardings`` is a matching pytree of
    shardings or a single sharding applied to every leaf."""
    if jax.process_count() == 1:
        return jax.device_put(tree, shardings)

    def put(x, s):
        x = np.asarray(x)
        return jax.make_array_from_callback(
            x.shape, s, lambda idx, _x=x: _x[idx]
        )

    if isinstance(shardings, jax.sharding.Sharding):
        return jax.tree.map(lambda x: put(x, shardings), tree)
    return jax.tree.map(put, tree, shardings)


def balanced_stage_bounds(num_layers: int, num_stages: int) -> list[tuple[int, int]]:
    """Contiguous near-equal ``[start, end)`` bounds (larger stages first),
    the default when the caller gives no explicit split."""
    base, extra = divmod(num_layers, num_stages)
    bounds, start = [], 0
    for s in range(num_stages):
        size = base + (1 if s < extra else 0)
        bounds.append((start, start + size))
        start += size
    return bounds


def split_stage_stacks(model, layer_params: dict, stage_bounds) -> tuple[dict, dict, int]:
    """Split a full model's stacked layer params into per-stage uniform
    stacks for the fused SPMD engine, supporting uneven bounds and
    heterogeneous layer groups (DeepSeek's dense prefix + MoE suffix).

    Every stage gets the SAME structure — for each layer group, ``slots =
    max(layers of that group on any stage)`` rows, zero-padded — so the
    arrays stack to (S, slots, …) and shard over ``pp``. A bool mask marks
    the real rows; ``scan_layers`` turns padding slots into no-ops. This is
    how one SPMD program serves the reference's arbitrary ``[start, end)``
    splits (e.g. the BASELINE DeepSeek 0-14/14-27 config,
    /root/reference/shard/utils.py:36-39) without per-stage programs.

    Returns ``(stacked_params, masks, total_slots)`` where ``masks`` mirrors
    the group structure of ``stacked_params`` ((S, slots) bool arrays) and
    ``total_slots`` is the per-stage KV-cache layer count.
    """
    stage_bounds = list(stage_bounds)
    S = len(stage_bounds)
    if stage_bounds[0][0] != 0 or stage_bounds[-1][1] != model.config.num_hidden_layers:
        raise ValueError(f"stage bounds {stage_bounds} must cover all layers")
    for (a0, a1), (b0, b1) in zip(stage_bounds, stage_bounds[1:]):
        if a1 != b0:
            raise ValueError(f"stage bounds {stage_bounds} must be contiguous")
    if any(e <= s for s, e in stage_bounds):
        raise ValueError(f"stage bounds {stage_bounds} contain an empty stage")

    ranges = model.layer_group_ranges()

    def split_group(stack: dict, g0: int, g1: int):
        rows_per_stage = [
            (min(max(s, g0), g1) - g0, min(max(e, g0), g1) - g0)
            for s, e in stage_bounds
        ]
        slots = max(hi - lo for lo, hi in rows_per_stage)

        def split_leaf(w):
            rows = []
            for lo, hi in rows_per_stage:
                part = w[lo:hi]
                if hi - lo < slots:
                    pad = [(0, slots - (hi - lo))] + [(0, 0)] * (w.ndim - 1)
                    part = jnp.pad(part, pad)
                rows.append(part)
            return jnp.stack(rows)

        # tree-map: plain arrays and packed {q, scales, biases} triples alike
        stacked = {
            name: jax.tree.map(split_leaf, w) for name, w in stack.items()
        }
        mask = np.zeros((S, slots), bool)
        for si, (lo, hi) in enumerate(rows_per_stage):
            mask[si, : hi - lo] = True
        return stacked, jnp.asarray(mask), slots

    if list(ranges) == [None]:
        stacked, mask, slots = split_group(layer_params, *ranges[None])
        return stacked, mask, slots
    stacked_all, masks_all, total = {}, {}, 0
    for key, (g0, g1) in ranges.items():
        stacked, mask, slots = split_group(layer_params[key], g0, g1)
        stacked_all[key] = stacked
        masks_all[key] = mask
        total += slots
    return stacked_all, masks_all, total


def stack_stage_params(stage_param_list: list[dict]) -> dict:
    """Per-stage loaded checkpoints ({name: (L, …)} each) → {name: (S, L, …)}.
    Lets per-stage checkpoints emitted by shard_tool feed the mesh directly."""
    names = stage_param_list[0].keys()
    return {n: jnp.stack([p[n] for p in stage_param_list]) for n in names}


def place_weights(model, params, mesh, *, stage_bounds=None) -> ResidentWeights:
    """Materialize a model's device-resident weight tree on ``mesh``: split
    the stacked layer params per pipeline stage, apply build-time projection
    fusion and the GEMV autotune sweep, derive per-name PartitionSpecs over
    pp/tp/ep, place everything with ``put_global``, and vocab-shard the
    embedding/head over pp. This is the entire per-replica spawn cost that
    ISN'T slot/cache setup — which is why it is a free function: the
    ``weights.WeightStore`` runs it once per key and every data-parallel
    replica constructs its ``PipelineEngine`` against the returned
    ``ResidentWeights`` (``weights=`` kwarg), aliasing the same arrays
    instead of re-uploading W bytes per replica."""
    cfg = model.config
    S = mesh.shape[AXIS_PP]
    tp = mesh.shape.get(AXIS_TP, 1)
    ep = mesh.shape.get(AXIS_EP, 1)
    stage_sharding = NamedSharding(mesh, P(AXIS_PP))
    replicated = NamedSharding(mesh, P())

    if stage_bounds is None:
        stage_bounds = balanced_stage_bounds(cfg.num_hidden_layers, S)
    elif len(stage_bounds) != S:
        raise ValueError(
            f"{len(stage_bounds)} stage bounds for a {S}-stage pp mesh"
        )
    stage_bounds = [tuple(b) for b in stage_bounds]
    split, masks, slots = split_stage_stacks(model, params["layers"], stage_bounds)

    # Build-time projection fusion (keep-quantized loads): concatenate
    # each declared group's packed triples along OUT so decode runs QKV
    # (and gate+up) as ONE fused-GEMV launch sharing a single pass over
    # the activation planes. tp == 1 only — the fused OUT axis
    # interleaves the group's rows, which the column-parallel slicing
    # wouldn't split correctly. Forward code dispatches on the fused
    # name's presence in the layer pytree (models/llama.py).
    fused_projections: list[str] = []
    if tp == 1 and os.environ.get("MST_FUSE_PROJ", "1") != "0":
        from mlx_sharding_tpu.models.base import apply_projection_fusion

        fused_projections = apply_projection_fusion(model, split)

    # Shape-keyed GEMV autotune: sweep candidate block sizes once per
    # distinct packed (OUT, IN) at load time (quant_matmul caches the
    # winner; every layer with that shape reuses it). No-op off-TPU.
    if os.environ.get("MST_QMM_AUTOTUNE", "1") != "0":
        from mlx_sharding_tpu.ops.quant_matmul import autotune_gemv

        gs_a, bits_a = model._quant_args()
        seen_shapes: set = set()

        def _sweep(stack):
            for w in stack.values():
                if isinstance(w, dict) and not is_quantized(w):
                    _sweep(w)
                elif is_quantized(w):
                    out_dim = int(w["q"].shape[-2])
                    in_dim = int(w["scales"].shape[-1]) * gs_a
                    if (out_dim, in_dim) not in seen_shapes:
                        seen_shapes.add((out_dim, in_dim))
                        autotune_gemv(1, out_dim, in_dim, gs_a, bits_a)

        _sweep(split)

    # Per-name shard axes: tp (heads/MLP columns) and ep (expert stacks).
    # Models declare flat maps (homogeneous stacks) or nested
    # {group: {name: dim}} maps (DeepSeek's moe group). Values are
    # (per-layer dim, mesh axis name).
    def _merge(out, axes_map, axis_name):
        for n, ax in axes_map.items():
            if isinstance(ax, dict):
                out.setdefault(n, {})
                _merge(out[n], ax, axis_name)
            elif ax is not None:
                out[n] = (ax, axis_name)

    axes_by_name: dict = {}
    if tp > 1:
        _merge(axes_by_name, model.tp_layer_axes(), AXIS_TP)
    if ep > 1:
        _merge(axes_by_name, model.ep_layer_axes(), AXIS_EP)

    def _check_div(name, w, ax, axis_name):
        if w.shape[2 + ax] % mesh.shape[axis_name]:
            raise ValueError(
                f"{name} dim {w.shape[2 + ax]} not divisible over "
                f"{axis_name}={mesh.shape[axis_name]}"
            )
        dims = [AXIS_PP, None] + [None] * (w.ndim - 2)
        dims[2 + ax] = axis_name
        return P(*dims)

    def param_spec(entry, name, w):
        # (S, L, …) array → the model-declared per-layer dim shards over
        # its mesh axis, offset by the two leading stack axes
        if entry is None:
            return P(AXIS_PP)
        ax, axis_name = entry
        return _check_div(name, w, ax, axis_name)

    def quant_spec(entry, name, w):
        """Packed triples under TP/EP. The model declares axes in the
        DENSE orientation — trailing (…, in, out) matmul dims, any
        leading stack dims (the expert E axis) before them — but packed
        leaves keep those two trailing dims in MLX's (out, X) layout:
        q (out, in/8), scales/biases (out, in/group). Leading stack dims
        are layout-identical (EP's E axis shards as declared); within
        the matmul pair the dim flips: column-parallel (dense out)
        shards packed dim -2, row-parallel (dense in) shards packed
        dim -1. Per-leaf divisibility checks double as nibble-word and
        quant-group alignment guards (scales' in/group dim dividing the
        mesh axis ⇔ the in split lands on group boundaries)."""
        if entry is None:
            spec = P(AXIS_PP)
            return jax.tree.map(lambda _: spec, w)
        ax, axis_name = entry
        ndims = {a.ndim for a in w.values()}
        if len(ndims) != 1:
            raise ValueError(f"ragged packed leaves for {name}")
        nd = ndims.pop() - 2  # per-layer dims (drop the S, L stack axes)
        if ax < nd - 2:
            axq = ax  # leading stack dim (expert E): same position packed
        elif ax == nd - 1:
            axq = nd - 2  # dense out (column-parallel) → packed out dim
        else:
            axq = nd - 1  # dense in (row-parallel) → packed in/X dim
        return {
            leaf: _check_div(f"{name}.{leaf}", arr, axq, axis_name)
            for leaf, arr in w.items()
        }

    def build_specs(stack, axes):
        out = {}
        for name, w in stack.items():
            entry = axes.get(name)
            if isinstance(w, dict) and not is_quantized(w):
                out[name] = build_specs(w, entry or {})
            elif is_quantized(w):
                out[name] = quant_spec(entry, name, w)
            else:
                out[name] = param_spec(entry, name, w)
        return out

    if not axes_by_name:
        layer_specs = jax.tree.map(lambda _: P(AXIS_PP), split)
    else:
        layer_specs = build_specs(split, axes_by_name)
    layer_params = put_global(
        split,
        jax.tree.map(
            lambda s: NamedSharding(mesh, s), layer_specs,
            is_leaf=lambda x: isinstance(x, P),
        ),
    )
    layer_masks = put_global(masks, stage_sharding)

    # Vocab-shard the embedding table and LM head over pp: each device
    # holds vocab/S rows instead of a full replica (Llama-3 vocab in bf16
    # is ~1 GB/device replicated). Embedding rows are re-assembled with a
    # tiny (B,T,H) psum per tick; logits are computed per vocab shard
    # post-scan and all-gathered — (S-1)/S x V bytes/device vs the full-V
    # psum before, with head FLOPs divided by S.
    head_tied = model.head_is_tied()
    Vs = -(-cfg.vocab_size // S)
    table = params["embed"]["weight"]
    if is_quantized(table):
        # the vocab-sharded embed/head machinery is dense; a packed
        # table (keep-quantized load) dequantizes once at build — each
        # device still holds only its V/S rows afterwards
        gs, bits = model._quant_args()
        table = dequantize(
            table["q"], table["scales"], table["biases"], gs, bits,
            model.compute_dtype,
        )
    table = jnp.pad(table, ((0, Vs * S - table.shape[0]), (0, 0)))
    vparts = [table.reshape(S, Vs, -1)]
    if not head_tied:
        head = params["lm_head"]["weight"]  # (H, V)
        if is_quantized(head):
            gs, bits = model._quant_args()
            head = dequantize(
                head["q"], head["scales"], head["biases"], gs, bits,
                model.compute_dtype,
            ).T  # packed is MLX (V, H); the engine wants (H, V)
        head = jnp.pad(head, ((0, 0), (0, Vs * S - head.shape[1])))
        # (S, H, Vs) so each device's slice is its vocab shard
        vparts.append(head.reshape(-1, S, Vs).transpose(1, 0, 2))
    vocab_parts = put_global(tuple(vparts), stage_sharding)
    shared_params = put_global(
        {
            k: v for k, v in params.items()
            if k not in ("layers", "embed", "lm_head")
        },
        replicated,
    )

    # total weight bytes one decode tick streams from HBM (every param
    # leaf is read once per forward) — numerator of the
    # mst_decode_hbm_bytes_per_token{kind="weights"} gauge. Packed
    # triples count their actual packed bytes: this is where 4-bit shows
    # up as 4x less traffic than dense bf16.
    weight_bytes = sum(
        leaf.nbytes
        for leaf in jax.tree.leaves((layer_params, vocab_parts, shared_params))
    )
    return ResidentWeights(
        mesh=mesh,
        stage_bounds=stage_bounds,
        layer_specs=layer_specs,
        layer_params=layer_params,
        layer_masks=layer_masks,
        layers_per_stage=slots,
        fused_projections=fused_projections,
        vocab_size=cfg.vocab_size,
        head_tied=head_tied,
        vocab_parts=vocab_parts,
        shared_params=shared_params,
        weight_bytes=weight_bytes,
    )


class PipelineEngine:
    """Runs a full (unsharded-config) model across a ``pp`` mesh axis.

    ``params`` is the full model's pytree (stacked layers over ALL layers);
    layer stacks are split per stage and placed with a ``P('pp')`` sharding.
    The embedding table and LM head are vocab-sharded over pp (each device
    holds vocab/S rows; see the collectives in ``_vs_embed``/``_vs_head``);
    only the final norm stays replicated. The KV cache is one global array
    sharded on its leading stage axis — stage-local in HBM, exactly the
    reference's "KV stays on the shard" invariant (shard/server/server.py:9-10)
    without the process.
    """

    def __init__(
        self,
        model,
        params: dict,
        mesh: Mesh,
        *,
        stage_bounds=None,
        microbatches: int = 1,
        batch: int = 1,
        max_seq: int = 4096,
        cache_dtype=jnp.bfloat16,
        prefill_chunk: int = 256,
        decode_block: int = 16,
        pool_pages: Optional[int] = None,
        page_size: Optional[int] = None,
        paged_attention: str = "auto",
        kv_dtype: Optional[str] = None,
        kv_share_map=None,
        kv_compress_map=None,
        weights: Optional[ResidentWeights] = None,
    ):
        cfg = model.config
        if not (cfg.is_first_stage and cfg.is_last_stage):
            raise ValueError("PipelineEngine wants the full model config")
        self.model = model
        self.mesh = mesh
        self.num_stages = mesh.shape[AXIS_PP]
        self.tp = mesh.shape.get(AXIS_TP, 1)
        self.microbatches = microbatches
        self.batch = batch
        # chunk-multiple capacity: padded prefill writes stay in bounds
        self.max_seq = -(-max_seq // prefill_chunk) * prefill_chunk
        self.cache_dtype = cache_dtype
        self.prefill_chunk = prefill_chunk
        self.decode_block = decode_block

        # Paged KV (continuous-batching only): slots address up to
        # max_seq/page_size pages out of a SHARED pool of ``pool_pages``
        # physical pages per stage, instead of each owning a dense max_seq
        # allocation. The scheduler reserves pages at admission — mixed-
        # length workloads pack the pool far tighter than M x max_seq.
        self.paged = pool_pages is not None
        self.page_size = page_size or prefill_chunk
        self.pool_pages = pool_pages or 0
        if self.paged:
            if self.page_size % prefill_chunk:
                raise ValueError(
                    f"page_size {self.page_size} must be a multiple of the "
                    f"prefill chunk {prefill_chunk} (chunk writes must stay "
                    "inside one page)"
                )
            if self.max_seq % self.page_size:
                raise ValueError(
                    f"page_size {self.page_size} must divide max_seq "
                    f"{self.max_seq}"
                )
        self.slot_pages = self.max_seq // self.page_size  # table width

        # int8 paged KV: pool leaves become {d: int8 data, s: f32 per-row-
        # per-head scale (trailing dim 1)} dicts — halves KV bytes per
        # ragged-attention tick and ~doubles the slots a fixed pool holds.
        if kv_dtype is None and self.paged:
            # checkpoint may pin it (config.kv_cache_dtype); dense engines
            # ignore the pin rather than erroring on int8-tagged checkpoints
            kv_dtype = getattr(model.config, "kv_cache_dtype", None)
        if kv_dtype not in (None, "bf16", "bfloat16", "int8"):
            raise ValueError(f"kv_dtype={kv_dtype!r}: want int8 or bf16")
        self.kv_quant = kv_dtype == "int8"
        if self.kv_quant and not self.paged:
            raise ValueError(
                "kv_dtype='int8' requires a paged engine (pool_pages)"
            )

        # KVSharer layer-wise KV sharing (kv_share.KVShareMap): the pool
        # allocates one physical (k, v) buffer per share-GROUP and every
        # layer reads/writes through the group indirection. The identity
        # map keeps the unshared fast paths selected (and hashes to None
        # so legacy exported blocks compose). Validation against the
        # engine's LOCAL layer count happens below, once the resident
        # weights resolve the stage split.
        if kv_share_map is not None:
            if not self.paged:
                raise ValueError(
                    "kv_share_map requires a paged engine (pool_pages)"
                )
            if self.num_stages != 1:
                raise ValueError(
                    "kv_share_map requires a pp=1 engine: share groups "
                    "span the full layer stack, which a stage split cuts"
                )
        self.kv_share = kv_share_map
        self.kv_share_hash = (
            kv_share_map.share_hash if kv_share_map is not None else None
        )
        self._share_active = (
            kv_share_map is not None and not kv_share_map.is_identity
        )
        self.kv_share_bytes_saved = 0  # filled by init_cache_paged

        tp_axes = model.tp_layer_axes()
        if self.tp > 1:
            if not tp_axes:
                raise ValueError(
                    f"tensor parallelism is not wired for {type(model).__name__}"
                )
            if (
                not model.cache_tp_replicated()
                and model.cache_num_heads() % self.tp
            ):
                raise ValueError(
                    f"tp={self.tp} must divide the {model.cache_num_heads()} "
                    "KV heads"
                )
        self.ep = mesh.shape.get(AXIS_EP, 1)
        if self.ep > 1 and not model.ep_layer_axes():
            raise ValueError(
                f"expert parallelism is not wired for {type(model).__name__}"
            )

        # Paged T=1 decode attention path: "ragged" attends over the page
        # pool in place (ops/paged_attention.py — no per-tick gather/
        # scatter); "gather" keeps the _paged_read contiguous view;
        # "auto" picks ragged whenever the wiring supports it. The ragged
        # body rides the sp_layer hook (injected attention), which has no
        # tp/ep plumbing, and the S==1 vectorized shape.
        if paged_attention not in ("auto", "ragged", "gather"):
            raise ValueError(
                f"paged_attention={paged_attention!r}: want auto|ragged|gather"
            )
        ragged_ok = (
            self.paged
            and self.num_stages == 1
            and self.tp == 1
            and self.ep == 1
            and self.batch == 1
            and getattr(model, "supports_sp", False)
        )
        if paged_attention == "ragged" and not ragged_ok:
            raise ValueError(
                "paged_attention='ragged' needs a paged (pool_pages) pp=1 "
                "engine with tp=ep=1, batch=1, and a model with supports_sp"
            )
        self.paged_attention = (
            "ragged" if paged_attention in ("auto", "ragged") and ragged_ok
            else "gather"
        )
        # run_layers parallelism kwargs, shared by every step body
        self._rl_kwargs = {}
        if self.tp > 1:
            self._rl_kwargs["tp_axis"] = AXIS_TP
        if self.ep > 1:
            self._rl_kwargs["ep_axis"] = AXIS_EP

        # under TP the KV heads axis is sharded too: each (pp, tp) device
        # holds its stage's cache for its own heads only. A head-count-
        # independent cache (model.cache_tp_replicated: DeepSeek's compressed
        # shared latent) replicates over tp instead, every tp device
        # computing identical writes from the replicated latent projections.
        self._kv_spec = (
            P(AXIS_PP, None, None, None, None, AXIS_TP)
            if self.tp > 1 and not model.cache_tp_replicated() else P(AXIS_PP)
        )

        # Weight residency. Private path: build this engine's own
        # device-resident tree (the full W-byte upload — split, fuse,
        # autotune, place). Aliased path (``weights=``): a
        # ``weights.WeightStore`` lease already holds the resident tree for
        # this exact placement, and N data-parallel replicas execute
        # against the SAME arrays — constructing the engine costs
        # slot/cache setup only. The caller owns the lease and wires its
        # release through ``on_close()``.
        if weights is None:
            weights = place_weights(
                model, params, mesh, stage_bounds=stage_bounds
            )
            self.weights_shared = False
        else:
            if not same_mesh_devices(weights.mesh, mesh):
                raise ValueError(
                    "resident weights were placed on a different device "
                    "grid than this engine's mesh — aliased construction "
                    "needs identical placement (same devices, same axis "
                    "layout)"
                )
            if stage_bounds is not None and [
                tuple(b) for b in stage_bounds
            ] != list(weights.stage_bounds):
                raise ValueError(
                    f"stage_bounds {list(stage_bounds)} disagree with the "
                    f"resident tree's split {list(weights.stage_bounds)}"
                )
            # adopt the resident tree's Mesh OBJECT, not just an equal
            # grid: shard_map programs closed over the same mesh share
            # trace caches across aliased replicas
            self.mesh = mesh = weights.mesh
            self.weights_shared = True
        self.resident = weights
        self.stage_bounds = list(weights.stage_bounds)
        self.layer_specs = weights.layer_specs
        self.layer_params = weights.layer_params
        self.layer_masks = weights.layer_masks
        self.layers_per_stage = weights.layers_per_stage
        self.fused_projections = list(weights.fused_projections)
        self.vocab_size = weights.vocab_size
        self._head_tied = weights.head_tied
        self.vocab_parts = weights.vocab_parts
        self.shared_params = weights.shared_params
        self.weight_stream_bytes = weights.weight_bytes
        if self.kv_share is not None:
            # the map must cover exactly this engine's local layer stack
            # (padding from uneven heterogeneous splits counts — reject
            # rather than guess which stacked slots are real)
            self.kv_share.validate_for(self.layers_per_stage)
        # Compressed-latent KV transport (kv_compress.py): MLA-native
        # pools get the exact latent codec automatically; a calibrated
        # map opts a GQA pool into bounded-error lowrank. The codec rides
        # every KVPageBlock export so spill flushes, prefix demotions,
        # federation blobs, and handoff wires all move the compact form.
        from mlx_sharding_tpu.kv_compress import build_codec

        pool_layers = (
            self.kv_share.num_groups if self._share_active
            else self.layers_per_stage
        )
        self.kv_codec = build_codec(
            model,
            paged=self.paged,
            kv_quant=self.kv_quant,
            num_stages=self.num_stages,
            pool_layers=pool_layers,
            share_hash=self.kv_share_hash,
            compress_map=kv_compress_map,
        )
        self.kv_compress_hash = (
            self.kv_codec.compress_hash if self.kv_codec is not None else None
        )
        # resources the engine holds beyond its own arrays (today: the
        # shared-weight lease release) — close() runs each exactly once
        self._close_hooks: list = []

        self._decode = self._build_step(t_len=1, with_sampling=True)
        self._prefill = self._build_step(t_len=prefill_chunk, with_sampling=False)
        self._sample = jax.jit(self._sample_fn, donate_argnums=(1,))
        # continuous-batching programs, built on first use by the scheduler
        self._decode_cb = None
        self._prefill_slot = None
        self._decode_blocks: dict = {}  # (k_steps, want_lp) → jitted block
        self._spec_progs: dict = {}  # ("propose"|"verify", K) → jitted prog

    def on_close(self, cb):
        """Register a teardown callback (run once, from close()). The
        shared-weights spawn path hangs the store lease's release here, so
        drain/retire/hot-swap teardown — which all funnel through
        ``close()`` — decrement the refcount and the LAST engine frees the
        tree."""
        self._close_hooks.append(cb)

    def close(self):
        """Release resources held beyond the engine's own arrays.
        Idempotent: hooks run exactly once, so the drain→retire→fleet-close
        sequence (each of which closes the replica) releases a shared
        weight lease once, not thrice."""
        hooks, self._close_hooks = self._close_hooks, []
        for cb in hooks:
            cb()

    def decode_cb(self):
        if self._decode_cb is None:
            self._decode_cb = self._build_decode_cb()
        return self._decode_cb

    def decode_block_prog(self, k_steps: int, want_lp: bool):
        """K single-token decode steps scanned into ONE program — the host
        pulls tokens once per block instead of once per token (see
        generate.Generator: over a network-attached chip the per-token host
        pull dominates the device step). Logprob summaries (chosen + top-10
        via lax.top_k) are computed inside the scan when requested."""
        cache_key = (k_steps, want_lp)
        if cache_key not in self._decode_blocks:
            step, M, B = self._decode, self.microbatches, self.batch
            one = jnp.asarray(1, jnp.int32)

            def block(layer_params, masks, vparts, shared, tok, cache, recent, key, sp):
                def body(carry, _):
                    tok, cache, recent, key = carry
                    tok, logprobs, cache, recent, key = step(
                        layer_params, masks, vparts, shared, tok[..., None],
                        cache, recent, key, sp, one,
                    )
                    if want_lp:
                        from mlx_sharding_tpu.generate import block_lp_outputs

                        out = (tok, *block_lp_outputs(tok.reshape(M * B), logprobs))
                    else:
                        out = (tok,)
                    return (tok, cache, recent, key), out

                (tok, cache, recent, key), outs = jax.lax.scan(
                    body, (tok, cache, recent, key), None, length=k_steps
                )
                return outs, tok, cache, recent, key

            self._decode_blocks[cache_key] = jax.jit(block, donate_argnums=(5, 6))
        return self._decode_blocks[cache_key]

    def prefill_slot(self):
        if self._prefill_slot is None:
            self._prefill_slot = self._build_prefill_slot()
        return self._prefill_slot

    # ------------------------------------------------------------------
    def init_cache(self) -> KVCache:
        cfg = self.model.config
        hd = self.model.cache_head_dim()
        k_dim, v_dim = (hd, hd) if not isinstance(hd, (tuple, list)) else hd
        S, L, M, B = (
            self.num_stages,
            self.layers_per_stage,
            self.microbatches,
            self.batch,
        )
        shape = (S, L, M + 1, B, self.max_seq, self.model.cache_num_heads())
        sharding = NamedSharding(self.mesh, self._kv_spec)
        # offset is PER MICROBATCH SLOT: continuous batching runs a different
        # request (at a different sequence position) in every slot
        return KVCache(
            k=put_global(jnp.zeros((*shape, k_dim), self.cache_dtype), sharding),
            v=put_global(jnp.zeros((*shape, v_dim), self.cache_dtype), sharding),
            offset=put_global(
                jnp.zeros((M,), jnp.int32), NamedSharding(self.mesh, P())
            ),
        )

    def init_cache_paged(self) -> tuple[KVCache, jax.Array]:
        """Shared page pool + per-slot page table for continuous batching.

        Pool: (S, L, pool_pages+1, B, page, H, D) per stage — the last page
        is scratch: every unallocated table entry points there, so writes
        from inactive ticks and past-a-request's-reservation overshoot land
        harmlessly (the dense layout's scratch-slice trick, per page).
        Table: (M+1, slot_pages) int32 — row M is the all-scratch row
        garbage ticks route to. Table entries are POOL page ids; position p
        of slot m lives at pool page table[m][p // page_size], row
        p % page_size."""
        if not self.paged:
            raise ValueError("engine built without pool_pages")
        cfg = self.model.config
        hd = self.model.cache_head_dim()
        k_dim, v_dim = (hd, hd) if not isinstance(hd, (tuple, list)) else hd
        S, L, M, B = (
            self.num_stages, self.layers_per_stage, self.microbatches,
            self.batch,
        )
        # KVSharer: the pool's layer axis shrinks to the share-GROUP count —
        # one physical buffer per group, every layer a logical view
        L_pool = self.kv_share.num_groups if self._share_active else L
        shape = (
            S, L_pool, self.pool_pages + 1, B, self.page_size,
            self.model.cache_num_heads(),
        )
        sharding = NamedSharding(self.mesh, self._kv_spec)

        def pool(dim):
            if not self.kv_quant:
                return jnp.zeros((*shape, dim), self.cache_dtype)
            # int8 pool: data + per-row-per-head scale (trailing dim 1
            # broadcasts over head_dim) — D+4 bytes per row-head vs 2D bf16
            return {
                "d": jnp.zeros((*shape, dim), jnp.int8),
                "s": jnp.zeros((*shape, 1), jnp.float32),
            }

        cache = KVCache(
            k=put_global(pool(k_dim), sharding),
            v=put_global(pool(v_dim), sharding),
            offset=put_global(
                jnp.zeros((M,), jnp.int32), NamedSharding(self.mesh, P())
            ),
        )
        table = put_global(
            jnp.full((M + 1, self.slot_pages), self.pool_pages, jnp.int32),
            NamedSharding(self.mesh, P()),
        )
        if self._share_active:
            # the allocation that DIDN'T happen: an unshared pool would be
            # L/G times these leaves (dtype/scale structure identical)
            pool_bytes = sum(
                leaf.nbytes for leaf in jax.tree.leaves((cache.k, cache.v))
            )
            self.kv_share_bytes_saved = int(
                pool_bytes * (L - L_pool) / L_pool
            )
        return cache, table

    def kv_share_stats(self) -> dict:
        """Observability surface for the ``mst_kv_share_*`` family."""
        m = self.kv_share
        return {
            "enabled": bool(self._share_active),
            "groups": m.num_groups if m is not None else self.layers_per_stage,
            "layers": self.layers_per_stage,
            "bytes_saved": int(self.kv_share_bytes_saved),
            "share_hash": self.kv_share_hash,
        }

    def kv_compress_stats(self) -> Optional[dict]:
        """Observability surface for the ``mst_kv_compress_*`` family —
        None when no codec is active (flag off, non-MLA model)."""
        return self.kv_codec.stats() if self.kv_codec is not None else None

    # ----------------------------------------------------- vocab sharding
    def _vs_embed(self, s, vparts, ids):
        """Embedding lookup against this device's vocab shard + psum to
        assemble full rows (only the owner contributes non-zeros)."""
        table = vparts[0]  # (Vs, H)
        Vs = table.shape[0]
        lo = s * Vs
        rows = jnp.take(table, jnp.clip(ids - lo, 0, Vs - 1), axis=0)
        owned = (ids >= lo) & (ids < lo + Vs)
        rows = jnp.where(owned[..., None], rows, jnp.zeros((), rows.dtype))
        return self.model.embed_transform(jax.lax.psum(rows, AXIS_PP))

    def _vs_head(self, shared, vparts, h):
        """Final norm + per-shard vocab projection + all-gather. ``h`` must
        already be replicated (post-psum of the banked hidden states)."""
        model = self.model
        hn = model.head_input(shared, h)
        if self._head_tied:
            w = vparts[0]  # (Vs, H) — the embedding shard, transposed in-op
            logits = jnp.einsum("...h,vh->...v", hn, w)
        else:
            logits = hn @ vparts[1]  # (H, Vs)
        logits = model.head_transform(logits)
        full = jax.lax.all_gather(logits, AXIS_PP, axis=logits.ndim - 1, tiled=True)
        return full[..., : self.vocab_size].astype(jnp.float32)

    # ------------------------------------------------------------------
    def _paged_read(self, k, v, table_row):
        """Gather one slot's pages into the contiguous (L, B, S_virt, H, D)
        view run_layers expects. k/v: local pool (L, P+1, B, page, H, D) —
        or the int8 ``{d, s}`` pair, which dequantizes AFTER the gather so
        the pool→registers traffic is the int8 bytes, not the dense view.
        Under a KV share map the pool's leading axis is the GROUP count;
        the group rows expand to the per-layer view post-dequantize, so
        pool→registers traffic stays the G-sized bytes."""

        def gather(pool):
            g = jnp.take(pool, table_row, axis=1)  # (L, SPG, B, page, H, D)
            g = jnp.moveaxis(g, 1, 2)  # (L, B, SPG, page, H, D)
            return g.reshape(*g.shape[:2], -1, *g.shape[4:])

        out = tuple(
            dequantize_kv(jax.tree.map(gather, pool), self.cache_dtype)
            for pool in (k, v)
        )
        if self._share_active:
            gids = jnp.asarray(self.kv_share.group_of, jnp.int32)
            out = tuple(jnp.take(x, gids, axis=0) for x in out)
        return out

    def _paged_writeback(self, pool, buf, table_row, offset, n_pages=1):
        """Scatter the dirty page(s) of a slot's contiguous buffer back into
        the pool, starting at the page containing ``offset``. Chunk writes
        never straddle pages (page_size % prefill_chunk == 0 and offsets are
        chunk-aligned), so prefill and T=1 decode pass n_pages=1; a T=K
        speculative verify writes K rows at an arbitrary offset and passes
        the worst-case straddle count. Writing back a page the step didn't
        touch is idempotent (it holds exactly what the gather read — for the
        int8 pool, requantizing a dequantized row reproduces the same codes
        because the stored max element sits exactly at ±127, pinning the
        recomputed scale)."""
        quant = isinstance(pool, dict)
        if self._share_active:
            # only the owner layer's rows persist: reduce the expanded
            # (L, …) view back to the pool's (G, …) axis before scatter —
            # non-owner layers attended over the owner's history plus their
            # own current-tick rows, which are discarded here by design
            buf = jnp.take(
                buf, jnp.asarray(self.kv_share.owner_layers, jnp.int32),
                axis=0,
            )
        l, b = buf.shape[:2]
        page = self.page_size
        buf6 = buf.reshape(l, b, self.slot_pages, page, *buf.shape[3:])
        for i in range(n_pages):
            # out-of-range pidx clamps (dynamic_index semantics) to the last
            # buffer page and its table entry — an idempotent re-write
            pidx = jnp.minimum(offset // page + i, self.slot_pages - 1)
            dirty = jax.lax.dynamic_index_in_dim(buf6, pidx, 2, keepdims=False)
            if quant:  # quantize-on-writeback: the dense page never lands
                dirty = quantize_kv_rows(dirty)
                pool = jax.tree.map(
                    lambda p, d: jax.lax.dynamic_update_index_in_dim(
                        p, d.astype(p.dtype), table_row[pidx], 1
                    ),
                    pool, dirty,
                )
            else:
                pool = jax.lax.dynamic_update_index_in_dim(
                    pool, dirty.astype(pool.dtype), table_row[pidx], 1
                )
        return pool

    def _kv_read(self, paged, k, v, table, m_write):
        """One slot's contiguous KV view: page-table gather (paged) or
        slot-axis index (dense). Returns (k_m, v_m, table_row)."""
        if paged:
            row = table[m_write]
            k_m, v_m = self._paged_read(k, v, row)
            return k_m, v_m, row
        k_m = jax.lax.dynamic_index_in_dim(k, m_write, 1, keepdims=False)
        v_m = jax.lax.dynamic_index_in_dim(v, m_write, 1, keepdims=False)
        return k_m, v_m, None

    def _kv_write(self, paged, k, v, k_m, v_m, row, m_write, offset, n_pages=1):
        """Inverse of _kv_read: scatter the dirty page(s) back (paged) or
        update the slot slice (dense)."""
        if paged:
            return (
                self._paged_writeback(k, k_m, row, offset, n_pages),
                self._paged_writeback(v, v_m, row, offset, n_pages),
            )
        return (
            jax.lax.dynamic_update_index_in_dim(k, k_m, m_write, 1),
            jax.lax.dynamic_update_index_in_dim(v, v_m, m_write, 1),
        )

    def _build_step(self, t_len: int, with_sampling: bool):
        smapped = self._build_smapped(t_len)
        return self._finish_step(smapped, t_len, with_sampling)

    def _build_smapped(self, t_len: int, paged: bool = False,
                       keep_all: bool = False):
        """``keep_all`` banks logits for EVERY position instead of only the
        last valid one — the T=K speculative verify needs all K scores. Only
        the S == 1 vectorized body supports it (speculative continuous
        batching is gated to pp=1)."""
        model, S, M, B = self.model, self.num_stages, self.microbatches, self.batch
        rl_kwargs = self._rl_kwargs
        if keep_all and S != 1:
            raise ValueError("keep_all logits need the S == 1 vectorized body")
        # int8 pools are {d, s} dicts: index/stack per leaf, and take the
        # compute dtype from the engine instead of the storage leaf
        cdt = self.cache_dtype
        unstack = lambda t: jax.tree.map(lambda x: x[0], t)  # noqa: E731
        restack = lambda t: jax.tree.map(lambda x: x[None], t)  # noqa: E731

        def body(layer_params, masks, vparts, shared, tokens, k, v, offsets, active, n_valid, table):
            # Per-device views: layer_params (1, L, …) → (L, …); k/v
            # (1, L, M+1, B, seq, H, D) → (L, M+1, …). ``offsets`` is (M,) —
            # each slot's sequence position — and ``active`` (M,) bool marks
            # slots holding a live request (inactive slots' compute is routed
            # to the scratch cache slice and their logits are garbage the
            # scheduler ignores).
            layer_params = jax.tree.map(lambda x: x[0], layer_params)
            masks = jax.tree.map(lambda x: x[0], masks)
            vparts = jax.tree.map(lambda x: x[0], vparts)
            k, v = unstack(k), unstack(v)
            s = jax.lax.axis_index(AXIS_PP)
            h0 = jnp.zeros((B, t_len, model.config.hidden_size), cdt)
            # bank HIDDEN states, not logits: the vocab projection runs once
            # post-scan against this device's vocab shard
            out0 = jnp.zeros((M, B, model.config.hidden_size), cdt)
            offsets_pad = jnp.concatenate([offsets, jnp.zeros((1,), jnp.int32)])

            def tick(carry, t):
                h_buf, k, v, out = carry
                m = jnp.clip(t - s, 0, M - 1)
                is_real = (t >= s) & (t - s < M) & active[m]

                tok_m = jax.lax.dynamic_index_in_dim(
                    tokens, jnp.clip(t, 0, M - 1), 0, keepdims=False
                )  # (B, T)
                h_first = self._vs_embed(s, vparts, tok_m).astype(h_buf.dtype)
                h_in = jnp.where(s == 0, h_first, h_buf)

                # scratch slice M swallows non-real writes (paged mode:
                # table row M routes every page to the scratch pool page)
                m_write = jnp.where(is_real, m, M)
                offset = offsets_pad[m_write]
                k_m, v_m, row = self._kv_read(paged, k, v, table, m_write)
                h_out, k_m, v_m = model.run_layers(
                    layer_params, h_in, k_m, v_m, offset, mask=masks,
                    **rl_kwargs,
                )
                k, v = self._kv_write(paged, k, v, k_m, v_m, row, m_write, offset)

                # bank the last-valid-position hidden state on the final stage
                last = jax.lax.dynamic_index_in_dim(h_out, n_valid - 1, 1, keepdims=False)
                is_real_out = is_real & (s == S - 1)
                m_out = jnp.clip(t - (S - 1), 0, M - 1)
                out = jax.lax.dynamic_update_index_in_dim(
                    out, jnp.where(is_real_out, last.astype(out.dtype), out[m_out]),
                    m_out, 0,
                )

                h_next = jax.lax.ppermute(
                    h_out, AXIS_PP, [(i, (i + 1) % S) for i in range(S)]
                )
                return (h_next, k, v, out), None

            (h_buf, k, v, out), _ = jax.lax.scan(
                tick, (h0, k, v, out0), jnp.arange(S + M - 1)
            )
            out = jax.lax.psum(out, AXIS_PP)  # only stage S-1 contributed
            logits = self._vs_head(shared, vparts, out)  # (M, B, V) f32
            return logits, restack(k), restack(v)

        def body_s1(layer_params, masks, vparts, shared, tokens, k, v,
                    offsets, active, n_valid, table):
            """S == 1 fast path: every microbatch is resident on the one
            stage, so the tick rotation above — which would run M sequential
            forwards, streaming the weights M times — collapses to ONE
            vmapped forward. XLA batches each layer's matmuls over the M
            lanes, so the M-slot continuous-batching step streams the
            weights once: aggregate decode throughput scales with slots
            instead of dividing by them. Per-lane KV views are gathered
            up front (the same reads the tick path does) and the dirty
            slices written back in a short sequential loop — lanes only
            ever collide on the scratch slice, where order is garbage
            anyway."""
            layer_params = jax.tree.map(lambda x: x[0], layer_params)
            masks = jax.tree.map(lambda x: x[0], masks)
            vparts = jax.tree.map(lambda x: x[0], vparts)
            k, v = unstack(k), unstack(v)
            s = jax.lax.axis_index(AXIS_PP)
            offsets_pad = jnp.concatenate([offsets, jnp.zeros((1,), jnp.int32)])
            m_write = jnp.where(active, jnp.arange(M), M)  # inactive → scratch
            offset_m = offsets_pad[m_write]

            if tokens.ndim == 2:
                # the continuous-batching step passes (M, B) single tokens
                # (the tick body relied on where() broadcasting them up)
                tokens = tokens[..., None]
            h_all = self._vs_embed(s, vparts, tokens).astype(cdt)  # (M, B, T, H)

            def read(mw):
                k_m, v_m, row = self._kv_read(paged, k, v, table, mw)
                return (k_m, v_m, row) if paged else (k_m, v_m, mw)

            k_ms, v_ms, rows = jax.vmap(read)(m_write)

            def micro(h_m, k_m, v_m, off):
                return model.run_layers(
                    layer_params, h_m, k_m, v_m, off, mask=masks, **rl_kwargs
                )

            h_outs, k_ms, v_ms = jax.vmap(micro)(h_all, k_ms, v_ms, offset_m)

            # T=K writes at a decode (non-chunk-aligned) offset can straddle
            # pages; prefill/decode offsets never do (page % chunk == 0)
            wb = (
                (t_len + self.page_size - 2) // self.page_size + 1
                if paged and keep_all else 1
            )

            def wr(i, kv):
                k, v = kv
                return self._kv_write(
                    paged, k, v, k_ms[i], v_ms[i],
                    rows[i] if paged else None, m_write[i], offset_m[i], wb,
                )

            k, v = jax.lax.fori_loop(0, M, wr, (k, v))
            if keep_all:
                out = jnp.where(
                    active[:, None, None, None], h_outs, 0
                ).astype(cdt)  # (M, B, T, H) — every position's hidden
            else:
                out = jax.lax.dynamic_index_in_dim(
                    h_outs, n_valid - 1, 2, keepdims=False
                )  # (M, B, H)
                out = jnp.where(active[:, None, None], out, 0).astype(cdt)
            out = jax.lax.psum(out, AXIS_PP)  # identity at S=1; keeps the
            # body shape identical to the rotated one
            logits = self._vs_head(shared, vparts, out)
            return logits, restack(k), restack(v)

        if S == 1:
            body = body_s1

        spec_stage, spec_rep = P(AXIS_PP), P()
        inner = shard_map(
            body,
            mesh=self.mesh,
            in_specs=(
                self.layer_specs,
                jax.tree.map(lambda _: spec_stage, self.layer_masks),
                jax.tree.map(lambda _: spec_stage, self.vocab_parts),
                jax.tree.map(lambda _: spec_rep, self.shared_params),
                spec_rep,  # tokens
                self._kv_spec,  # k
                self._kv_spec,  # v
                spec_rep,  # offsets (M,)
                spec_rep,  # active (M,)
                spec_rep,  # n_valid
                spec_rep,  # page table (paged mode; dummy otherwise)
            ),
            out_specs=(spec_rep, self._kv_spec, self._kv_spec),
            check_vma=False,
        )
        if paged:
            return inner
        dummy_table = jnp.zeros((1, 1), jnp.int32)

        def smapped(layer_params, masks, vparts, shared, tokens, k, v, offsets,
                    active, n_valid):
            return inner(
                layer_params, masks, vparts, shared, tokens, k, v, offsets,
                active, n_valid, dummy_table,
            )

        if t_len == 1 and not keep_all:
            self._smapped_decode = smapped  # shared by the continuous-batching step
        return smapped

    def _scan_layers_shared(self, layer_fn, h, layer_params, k_pool, v_pool,
                            gids, own, mask=None):
        """Share-map variant of ``models.base.scan_layers`` for the ragged
        body: the pool stays GROUP-sized in the scan *carry* (an L-sized
        xs/ys pool would materialize the very transient the share map
        exists to avoid). Each layer dynamic-indexes its group's buffer
        out of the carry; after the layer runs, only the group OWNER's
        writes persist — a non-owner layer attends over the owner's
        history plus its own current-tick rows and then discards them,
        and a masked-out padding layer persists nothing."""

        def body(carry, xs):
            h, k_pool, v_pool = carry
            if mask is None:
                p, gid, keep = xs
                m_l = None
            else:
                p, gid, keep, m_l = xs
                keep = keep & m_l
            idx = lambda pool: jax.tree.map(  # noqa: E731
                lambda x: jax.lax.dynamic_index_in_dim(
                    x, gid, 0, keepdims=False
                ),
                pool,
            )
            k_buf, v_buf = idx(k_pool), idx(v_pool)
            h2, k2, v2 = layer_fn(h, p, k_buf, v_buf)
            if m_l is not None:
                h2 = jnp.where(m_l, h2, h)
            put = lambda pool, new, old: jax.tree.map(  # noqa: E731
                lambda x, n, o: jax.lax.dynamic_update_index_in_dim(
                    x, jnp.where(keep, n, o), gid, 0
                ),
                pool, new, old,
            )
            return (h2, put(k_pool, k2, k_buf), put(v_pool, v2, v_buf)), None

        xs = (
            (layer_params, gids, own) if mask is None
            else (layer_params, gids, own, mask)
        )
        (h, k_pool, v_pool), _ = jax.lax.scan(body, (h, k_pool, v_pool), xs)
        return h, k_pool, v_pool

    def _build_smapped_ragged(self):
        """T=1 paged decode body attending over the page pool IN PLACE
        (ops/paged_attention.py). Where the gather body materializes every
        live slot's full (max_seq) KV view and scatters the dirty page back
        each tick, this body scatters only the M new K/V rows into their
        pool pages and hands the pool itself to the ragged attention op —
        per-tick KV traffic drops from the whole cache (twice) to the pages
        slots actually occupy, and no FLOPs run past each slot's offset.

        Rides the sp_layer injected-attention hook with M as the batch dim
        (offsets become an (M,)-vector — apply_rope's per-row form), so one
        forward streams the weights once across all slots, like body_s1.
        Gated to S==1/tp=1/ep=1/B==1/supports_sp by the constructor."""
        model, M, B = self.model, self.microbatches, self.batch
        page = self.page_size
        cdt, kv_quant = self.cache_dtype, self.kv_quant
        from mlx_sharding_tpu.models.base import scan_layers
        from mlx_sharding_tpu.ops.paged_attention import paged_attention

        def body(layer_params, masks, vparts, shared, tokens, k, v,
                 offsets, active, n_valid, table):
            layer_params = jax.tree.map(lambda x: x[0], layer_params)
            masks = jax.tree.map(lambda x: x[0], masks)
            vparts = jax.tree.map(lambda x: x[0], vparts)
            # (L, P+1, B, page, H, D) — int8 pools are {d, s} leaf pairs
            k = jax.tree.map(lambda x: x[0], k)
            v = jax.tree.map(lambda x: x[0], v)
            s = jax.lax.axis_index(AXIS_PP)

            offsets_pad = jnp.concatenate([offsets, jnp.zeros((1,), jnp.int32)])
            m_write = jnp.where(active, jnp.arange(M), M)  # inactive → scratch
            offset_m = offsets_pad[m_write]  # (M,)
            rows = table[m_write]  # (M, SPG) — inactive rows all-scratch
            page_ids = jnp.take_along_axis(
                rows, (offset_m // page)[:, None], axis=1
            )[:, 0]  # (M,) pool page holding each slot's write position
            row_pos = offset_m % page
            # valid prefix incl. the row written this tick; 0 zeroes the
            # garbage lanes' attention outright
            lengths = jnp.where(active, offset_m + 1, 0).astype(jnp.int32)

            # B == 1: treat the slot axis as the batch axis, (M, 1) tokens
            # embed straight to (M, T=1, hidden)
            h = self._vs_embed(s, vparts, tokens).astype(cdt)

            def make_layer(g):
                def layer(h, p, k_buf, v_buf):
                    # scatter the M new rows, attend over the pool in place;
                    # updated pool escapes through ``done`` as the scan ys
                    # (sp_decode.py's closure idiom)
                    done = {}

                    def attn_fn(q, k_new, v_new, logit_softcap=None,
                                sliding_window=None, values_from_k=None):
                        # drop the B == 1 axis per leaf → (P+1, page, H, D)
                        kl = jax.tree.map(lambda x: x[:, 0], k_buf)
                        vl = jax.tree.map(lambda x: x[:, 0], v_buf)

                        def put(pool, new):
                            if kv_quant:  # quantize the M rows, scatter both
                                new = quantize_kv_rows(new)
                            return jax.tree.map(
                                lambda p, n: p.at[page_ids, row_pos].set(
                                    n.astype(p.dtype)
                                ),
                                pool, new,
                            )

                        kl = put(kl, k_new[:, 0])
                        vl = put(vl, v_new[:, 0])
                        done["k"] = jax.tree.map(lambda x: x[:, None], kl)
                        done["v"] = jax.tree.map(lambda x: x[:, None], vl)
                        out = paged_attention(
                            q[:, 0],
                            kl["d"] if kv_quant else kl,
                            vl["d"] if kv_quant else vl,
                            rows, lengths, model.scale,
                            logit_softcap=logit_softcap,
                            sliding_window=sliding_window,
                            values_from_k=values_from_k,
                            k_scale=kl["s"] if kv_quant else None,
                            v_scale=vl["s"] if kv_quant else None,
                        )
                        return out[:, None]  # (M, T=1, Hq, Dv)

                    h2, _, _ = model.sp_layer(p, h, offset_m, attn_fn, group=g)
                    return h2, done["k"], done["v"]

                return layer

            # per-group scans over the stacked layer sub-trees: unshared,
            # the pool slices to each group's layer range (run_layers'
            # layout, pool as scan xs/ys); under a share map the G-sized
            # pool rides the scan carry instead and layers dynamic-index
            # their share-group's buffer out of it
            share = self._share_active
            if share:
                gids_all = jnp.asarray(self.kv_share.group_of, jnp.int32)
                own_all = jnp.asarray(self.kv_share.owner_mask)
            lo = 0
            k_parts, v_parts = [], []
            for g in model.sp_groups():
                if g is not None and g not in layer_params:
                    continue
                stack = layer_params if g is None else layer_params[g]
                mask_g = masks if g is None else masks[g]
                n_g = jax.tree.leaves(stack)[0].shape[0]
                if share:
                    h, k, v = self._scan_layers_shared(
                        make_layer(g), h, stack, k, v,
                        gids_all[lo : lo + n_g], own_all[lo : lo + n_g],
                        mask_g,
                    )
                else:
                    h, k_g, v_g = scan_layers(
                        make_layer(g), h, stack,
                        jax.tree.map(lambda x: x[lo : lo + n_g], k),
                        jax.tree.map(lambda x: x[lo : lo + n_g], v),
                        mask_g,
                    )
                    k_parts.append(k_g)
                    v_parts.append(v_g)
                lo += n_g
            if not share:
                cat = lambda *xs: (  # noqa: E731
                    jnp.concatenate(xs, axis=0) if len(xs) > 1 else xs[0]
                )
                k = jax.tree.map(cat, *k_parts)
                v = jax.tree.map(cat, *v_parts)

            out = jnp.where(active[:, None, None], h, 0).astype(cdt)
            out = jax.lax.psum(out, AXIS_PP)  # identity at S=1; keeps the
            # body shape identical to the gather one
            logits = self._vs_head(shared, vparts, out)  # (M, B, V) f32
            return (
                logits,
                jax.tree.map(lambda x: x[None], k),
                jax.tree.map(lambda x: x[None], v),
            )

        spec_stage, spec_rep = P(AXIS_PP), P()
        return shard_map(
            body,
            mesh=self.mesh,
            in_specs=(
                self.layer_specs,
                jax.tree.map(lambda _: spec_stage, self.layer_masks),
                jax.tree.map(lambda _: spec_stage, self.vocab_parts),
                jax.tree.map(lambda _: spec_rep, self.shared_params),
                spec_rep,  # tokens
                self._kv_spec,  # k
                self._kv_spec,  # v
                spec_rep,  # offsets (M,)
                spec_rep,  # active (M,)
                spec_rep,  # n_valid
                spec_rep,  # page table
            ),
            out_specs=(spec_rep, self._kv_spec, self._kv_spec),
            check_vma=False,
        )

    def _finish_step(self, smapped, t_len: int, with_sampling: bool):
        M, B = self.microbatches, self.batch
        all_active = jnp.ones((M,), bool)

        if with_sampling:

            def step(layer_params, masks, vparts, shared, tokens, cache, recent, key, sp, n_valid):
                logits, k, v = smapped(
                    layer_params, masks, vparts, shared, tokens, cache.k, cache.v,
                    cache.offset, all_active, n_valid,
                )
                key, sub = jax.random.split(key)
                flat = logits.reshape(M * B, -1)
                tok, logprobs = sample_token(sub, flat, sp, recent)
                recent = update_recent_tokens(recent, tok)
                new_cache = KVCache(k=k, v=v, offset=cache.offset + n_valid)
                return tok.reshape(M, B), logprobs, new_cache, recent, key

            return jax.jit(step, donate_argnums=(5, 6))

        def step(layer_params, masks, vparts, shared, tokens, cache, n_valid):
            logits, k, v = smapped(
                layer_params, masks, vparts, shared, tokens, cache.k, cache.v,
                cache.offset, all_active, n_valid,
            )
            new_cache = KVCache(k=k, v=v, offset=cache.offset + n_valid)
            return logits, new_cache

        return jax.jit(step, donate_argnums=(5,))

    # ---------------------------------------------------- continuous batching
    def _build_decode_cb(self):
        """Decode step for continuous batching: per-slot offsets advance only
        on active slots, per-slot sampler params and PRNG keys (each slot
        reproduces the solo request with that seed), logits of inactive slots
        sampled-but-ignored. Reuses the same shard_map body as the uniform
        decode; only the host-visible wrapper differs. In paged mode the
        step takes the page table as an extra trailing argument."""
        M, B = self.microbatches, self.batch
        if B != 1:
            raise ValueError("continuous batching expects batch=1 per slot")
        if self.paged:
            # ragged (default where supported): attend over the page pool in
            # place; gather: the contiguous _paged_read view. Prefill and the
            # T=K speculative verify always keep the gather path — chunked
            # writes want the contiguous buffer.
            if self.paged_attention == "ragged":
                inner = self._build_smapped_ragged()
            else:
                inner = self._build_smapped(t_len=1, paged=True)
        else:
            if self._smapped_decode is None:
                self._build_step(t_len=1, with_sampling=True)
            dense = self._smapped_decode
            inner = lambda *args: dense(*args[:-1])  # drop the table arg

        def step(
            layer_params, masks, vparts, shared, tokens, cache, active, recent,
            keys, sp, rep_sizes, table,
        ):
            one = jnp.asarray(1, jnp.int32)
            logits, k, v = inner(
                layer_params, masks, vparts, shared, tokens, cache.k, cache.v,
                cache.offset, active, one, table,
            )
            split = jax.vmap(jax.random.split)(keys)  # (M, 2, 2)
            keys, subs = split[:, 0], split[:, 1]
            # per-slot effective repetition window: only the last rep_sizes[m]
            # entries of the fixed-width buffer participate, so each slot's
            # penalty semantics match a solo run with that context size
            W = recent.shape[1]
            valid = jnp.arange(W)[None, :] >= (W - rep_sizes)[:, None]
            tok, logprobs = sample_token_batched(
                subs, logits.reshape(M, -1), sp, jnp.where(valid, recent, -1)
            )
            recent = update_recent_tokens(recent, tok)
            new_cache = KVCache(
                k=k, v=v, offset=cache.offset + active.astype(jnp.int32)
            )
            return tok.reshape(M, B), logprobs, new_cache, recent, keys

        return jax.jit(step, donate_argnums=(5, 7, 8))

    # ------------------------------------ speculative continuous batching
    def spec_propose_cb(self, K: int):
        """K draft proposals for every continuous-batching slot in ONE
        program — the draft side of speculative x continuous batching,
        running on the DRAFT engine. Greedy slots (temperature == 0) draft
        with plain argmax (transforms live on the verify side, where
        exactness is decided — speculative.py draft_block_fn); sampled slots
        draft from their fully-transformed per-slot distribution and record
        its log-probs q, the rejection-sampling denominator
        (speculative.py draft_sampled_fn), each evolving a LOCAL copy of its
        repetition window with its own proposals. Returns a jitted
        ``prog(layer_params, masks, vparts, shared, tok, cache, active,
        recent, dkeys, sp, rep_sizes) -> (drafts (K, M), q_logprobs
        (K, M, V), cache)``."""
        key = ("propose", K)
        if key not in self._spec_progs:
            M, B = self.microbatches, self.batch
            if self.num_stages != 1:
                raise ValueError(
                    "speculative continuous batching needs a pp=1 engine"
                )
            if B != 1:
                raise ValueError("continuous batching expects batch=1 per slot")
            if self.paged:
                raise ValueError("the draft engine must be dense (no pool_pages)")
            if self._smapped_decode is None:
                self._build_step(t_len=1, with_sampling=True)
            dense = self._smapped_decode
            one = jnp.asarray(1, jnp.int32)

            def prog(layer_params, masks, vparts, shared, tok, cache, active,
                     recent, dkeys, sp, rep_sizes):
                W = recent.shape[1]
                valid = jnp.arange(W)[None, :] >= (W - rep_sizes)[:, None]

                def step(carry, _):
                    tok, k, v, offsets, recent, dkeys = carry
                    logits, k, v = dense(
                        layer_params, masks, vparts, shared, tok, k, v,
                        offsets, active, one,
                    )
                    flat = logits.reshape(M, -1)
                    split = jax.vmap(jax.random.split)(dkeys)
                    dkeys, subs = split[:, 0], split[:, 1]
                    f = nucleus_logits_batched(
                        transform_logits_batched(
                            flat, jnp.where(valid, recent, -1), sp
                        ),
                        sp,
                    )
                    qlp = jax.nn.log_softmax(f, axis=-1)
                    drawn = jax.vmap(
                        lambda kk, lo: jax.random.categorical(kk, lo)
                    )(subs, f)
                    tok = jnp.where(
                        sp.temperature > 0, drawn, jnp.argmax(flat, axis=-1)
                    ).astype(jnp.int32)
                    recent = update_recent_tokens(recent, tok)
                    offsets = offsets + active.astype(jnp.int32)
                    return (tok.reshape(M, B), k, v, offsets, recent, dkeys), (
                        tok, qlp,
                    )

                (tok, k, v, offsets, _, _), (drafts, qlps) = jax.lax.scan(
                    step,
                    (tok, cache.k, cache.v, cache.offset, recent, dkeys),
                    None, length=K,
                )
                return drafts, qlps, KVCache(k=k, v=v, offset=offsets)

            self._spec_progs[key] = jax.jit(prog, donate_argnums=(5,))
        return self._spec_progs[key]

    def spec_verify_cb(self, K: int):
        """One T=K target forward over ``[t0, d1..d_{K-1}]`` per slot scores
        every draft position for all M slots at once (keep_all logits body);
        acceptance per slot is the exact greedy agreement prefix
        (temperature 0 — every emitted token is what plain decode would
        produce) or Leviathan rejection sampling with the slot's own PRNG
        key (sampled — emitted tokens distributed exactly as the slot's
        transformed target distribution). The rollback is one per-slot
        scalar: offset += count keeps exactly the verified prefix
        (speculative.py verify_fn/verify_sampled_fn vectorized over slots).
        ``wcap`` (M,) is the per-slot adaptive window cap: ``m`` is clamped
        to ``wcap - 1`` INSIDE the program, before any acceptance is
        committed — truncating to a prefix of properly-accepted positions
        is exactly window-wcap speculation (greedy rows are the target's
        own tokens; sampled prefixes are rejection-sampling-exact at every
        length), and cache offset / next-token / replay all derive from the
        capped m. Legacy fixed-K callers pass wcap == K (a no-op clamp).
        Returns a jitted ``prog(layer_params, masks, vparts, shared, tok,
        drafts, qlps, cache, active, recent, vkeys, sp, rep_sizes, wcap,
        table) -> (gs (K, M), count (M,), next_tok (M, 1), cache,
        recent)``."""
        cache_key = ("verify", K)
        if cache_key not in self._spec_progs:
            self._spec_progs[cache_key] = jax.jit(
                self._spec_verify_fn(K), donate_argnums=(7, 9)
            )
        return self._spec_progs[cache_key]

    def spec_verify_ngram_cb(self, K: int):
        """The :meth:`spec_verify_cb` program for DETERMINISTIC (n-gram
        prompt-lookup) proposals: q is the one-hot distribution on the
        proposed token, built in-jit from the (K, M) draft ids — the host
        never ships a (K, M, V) array and there is no draft engine or
        draft KV at all. Returns a jitted ``prog(layer_params, masks,
        vparts, shared, tok, drafts, cache, active, recent, vkeys, sp,
        rep_sizes, wcap, table) -> (gs, count, next_tok, cache, recent)``."""
        cache_key = ("verify_ngram", K)
        if cache_key not in self._spec_progs:
            from mlx_sharding_tpu.speculative import one_hot_draft_logprobs

            raw = self._spec_verify_fn(K)
            vocab = self.vocab_size

            def prog(layer_params, masks, vparts, shared, tok, drafts,
                     cache, active, recent, vkeys, sp, rep_sizes, wcap,
                     table):
                qlps = one_hot_draft_logprobs(drafts, vocab)
                return raw(layer_params, masks, vparts, shared, tok, drafts,
                           qlps, cache, active, recent, vkeys, sp, rep_sizes,
                           wcap, table)

            self._spec_progs[cache_key] = jax.jit(
                prog, donate_argnums=(6, 8)
            )
        return self._spec_progs[cache_key]

    def _spec_verify_fn(self, K: int):
        """The raw (unjitted) verify program shared by the draft-engine and
        n-gram entry points (see :meth:`spec_verify_cb` for semantics)."""
        from mlx_sharding_tpu.speculative import rejection_round

        M, B = self.microbatches, self.batch
        if B != 1:
            raise ValueError("continuous batching expects batch=1 per slot")
        inner = self._build_smapped(t_len=K, paged=self.paged, keep_all=True)
        if not self.paged:
            dense = inner
            inner = lambda *args: dense(*args[:-1])  # drop the table arg
        n_valid = jnp.asarray(K, jnp.int32)

        def prog(layer_params, masks, vparts, shared, tok, drafts, qlps,
                 cache, active, recent, vkeys, sp, rep_sizes, wcap, table):
            x = jnp.concatenate([tok, drafts[:-1].T], axis=1)  # (M, K)
            off0 = cache.offset
            logits_all, k, v = inner(
                layer_params, masks, vparts, shared, x[:, None, :],
                cache.k, cache.v, off0, active, n_valid, table,
            )  # (M, 1, K, V)
            logits_all = logits_all.reshape(M, K, -1)
            W = recent.shape[1]
            valid = jnp.arange(W)[None, :] >= (W - rep_sizes)[:, None]
            sampled = sp.temperature > 0  # (M,)

            def score(rec, i):
                tl = transform_logits_batched(
                    logits_all[:, i], jnp.where(valid, rec, -1), sp
                )
                g = jnp.argmax(tl, axis=-1).astype(jnp.int32)
                plp = jax.nn.log_softmax(
                    nucleus_logits_batched(tl, sp), axis=-1
                )
                # the token consumed at position i+1: the draft's
                # proposal (sampled — exact on the accepted prefix,
                # discarded past it) or the greedy verdict
                rec = update_recent_tokens(
                    rec, jnp.where(sampled, drafts[i], g)
                )
                return rec, (g, plp)

            _, (gs_g, plps) = jax.lax.scan(score, recent, jnp.arange(K))
            # greedy: longest agreement prefix, then the correction token
            mism = gs_g != drafts
            any_m = mism.any(axis=0)
            m_g = jnp.where(any_m, jnp.argmax(mism, axis=0), K - 1)

            # rejection sampling, one vmapped lane per slot
            def rr(key_s, d, q, p):
                gs, m, _ = rejection_round(
                    key_s, d[:, None], q[:, None], p[:, None]
                )
                return gs[:, 0], m[0]

            gs_s, m_s = jax.vmap(rr, in_axes=(0, 1, 1, 1), out_axes=(1, 0))(
                vkeys, drafts, qlps, plps
            )
            gs = jnp.where(sampled[None, :], gs_s, gs_g)
            m = jnp.where(sampled, m_s, m_g)
            # per-slot adaptive window: clamp BEFORE anything commits
            m = jnp.minimum(m, wcap - 1)
            count = jnp.where(active, m + 1, 0).astype(jnp.int32)

            # replay ONLY the emitted tokens into the pre-round window
            # (the score scan's evolution was provisional)
            def replay(rec, i):
                upd = update_recent_tokens(rec, gs[i])
                keep = (i <= m) & active
                return jnp.where(keep[:, None], upd, rec), None

            recent, _ = jax.lax.scan(replay, recent, jnp.arange(K))
            nxt = jnp.take_along_axis(gs, m[None, :], axis=0)[0]  # (M,)
            next_tok = jnp.where(active, nxt, tok[:, 0])[:, None]
            return gs, count, next_tok, KVCache(
                k=k, v=v, offset=off0 + count
            ), recent

        return prog

    def spec_replay_cb(self, K: int):
        """Replay ``K`` recorded tokens through the dense decode body to
        advance the KV cache WITHOUT sampling — the scheduler uses this on a
        draft engine after a tick that fell back to plain (non-speculative)
        decode: the target advanced K positions, so the draft must ingest the
        same K tokens or its later proposals attend to stale KV and
        acceptance silently collapses. Logits are discarded; PRNG keys and
        repetition windows are untouched (the fallback block already
        consumed the slot's key chain on the target side). Returns a jitted
        ``prog(layer_params, masks, vparts, shared, toks (K, M, B), cache,
        active) -> cache``."""
        key = ("replay", K)
        if key not in self._spec_progs:
            if self.num_stages != 1:
                raise ValueError(
                    "speculative continuous batching needs a pp=1 engine"
                )
            if self.batch != 1:
                raise ValueError("continuous batching expects batch=1 per slot")
            if self.paged:
                raise ValueError("the draft engine must be dense (no pool_pages)")
            if self._smapped_decode is None:
                self._build_step(t_len=1, with_sampling=True)
            dense = self._smapped_decode
            one = jnp.asarray(1, jnp.int32)

            def prog(layer_params, masks, vparts, shared, toks, cache, active):
                def step(carry, tok):
                    k, v, offsets = carry
                    _, k, v = dense(
                        layer_params, masks, vparts, shared, tok, k, v,
                        offsets, active, one,
                    )
                    return (k, v, offsets + active.astype(jnp.int32)), None

                (k, v, offsets), _ = jax.lax.scan(
                    step, (cache.k, cache.v, cache.offset), toks
                )
                return KVCache(k=k, v=v, offset=offsets)

            self._spec_progs[key] = jax.jit(prog, donate_argnums=(5,))
        return self._spec_progs[key]

    def _build_prefill_slot(self):
        """Prefill one chunk of ONE slot's request while other slots' state
        stays untouched — the admit path of continuous batching. S ticks
        (single microbatch): stage s processes at tick s, cache writes land in
        slice ``slot`` at that slot's offset, last stage banks the
        last-valid-position logits."""
        model, S, M, B = self.model, self.num_stages, self.microbatches, self.batch
        rl_kwargs = self._rl_kwargs
        t_len = self.prefill_chunk

        paged = self.paged

        def body(layer_params, masks, vparts, shared, tokens, slot, k, v, offsets, n_valid, table):
            layer_params = jax.tree.map(lambda x: x[0], layer_params)
            masks = jax.tree.map(lambda x: x[0], masks)
            vparts = jax.tree.map(lambda x: x[0], vparts)
            k = jax.tree.map(lambda x: x[0], k)
            v = jax.tree.map(lambda x: x[0], v)
            s = jax.lax.axis_index(AXIS_PP)
            h0 = jnp.zeros((B, t_len, model.config.hidden_size), self.cache_dtype)
            out0 = jnp.zeros((B, model.config.hidden_size), self.cache_dtype)
            offsets_pad = jnp.concatenate([offsets, jnp.zeros((1,), jnp.int32)])

            def tick(carry, t):
                h_buf, k, v, out = carry
                is_real = t == s
                h_first = self._vs_embed(s, vparts, tokens).astype(h_buf.dtype)
                h_in = jnp.where(s == 0, h_first, h_buf)
                m_write = jnp.where(is_real, slot, M)
                offset = offsets_pad[m_write]
                k_m, v_m, row = self._kv_read(paged, k, v, table, m_write)
                h_out, k_m, v_m = model.run_layers(
                    layer_params, h_in, k_m, v_m, offset, mask=masks,
                    **rl_kwargs,
                )
                k, v = self._kv_write(paged, k, v, k_m, v_m, row, m_write, offset)

                last = jax.lax.dynamic_index_in_dim(h_out, n_valid - 1, 1, keepdims=False)
                out = jnp.where(
                    is_real & (s == S - 1), last.astype(out.dtype), out
                )

                h_next = jax.lax.ppermute(
                    h_out, AXIS_PP, [(i, (i + 1) % S) for i in range(S)]
                )
                return (h_next, k, v, out), None

            (_, k, v, out), _ = jax.lax.scan(tick, (h0, k, v, out0), jnp.arange(S))
            out = jax.lax.psum(out, AXIS_PP)
            logits = self._vs_head(shared, vparts, out)  # (B, V) f32
            return (
                logits,
                jax.tree.map(lambda x: x[None], k),
                jax.tree.map(lambda x: x[None], v),
            )

        spec_stage, spec_rep = P(AXIS_PP), P()
        smapped = shard_map(
            body,
            mesh=self.mesh,
            in_specs=(
                self.layer_specs,
                jax.tree.map(lambda _: spec_stage, self.layer_masks),
                jax.tree.map(lambda _: spec_stage, self.vocab_parts),
                jax.tree.map(lambda _: spec_rep, self.shared_params),
                spec_rep,  # tokens (B, T)
                spec_rep,  # slot
                self._kv_spec,  # k
                self._kv_spec,  # v
                spec_rep,  # offsets
                spec_rep,  # n_valid
                spec_rep,  # page table (paged mode; dummy otherwise)
            ),
            out_specs=(spec_rep, self._kv_spec, self._kv_spec),
            check_vma=False,
        )
        dummy_table = jnp.zeros((1, 1), jnp.int32)

        def step(layer_params, masks, vparts, shared, tokens, slot, cache, n_valid,
                 table=None):
            logits, k, v = smapped(
                layer_params, masks, vparts, shared, tokens, slot, cache.k, cache.v,
                cache.offset, n_valid, dummy_table if table is None else table,
            )
            offsets = cache.offset.at[slot].add(n_valid)
            return logits, KVCache(k=k, v=v, offset=offsets)

        return jax.jit(step, donate_argnums=(6,))

    @staticmethod
    def _sample_fn(logits, recent, key, sp):
        m, b = logits.shape[0], logits.shape[1]
        key, sub = jax.random.split(key)
        tok, logprobs = sample_token(sub, logits.reshape(m * b, -1), sp, recent)
        recent = update_recent_tokens(recent, tok)
        return tok.reshape(m, b), logprobs, recent, key

    # ------------------------------------------------------------------
    def generate_step(
        self,
        prompt_tokens,
        *,
        temperature: float = 0.0,
        top_p: float = 1.0,
        repetition_penalty: Optional[float] = None,
        repetition_context_size: int = 20,
        logit_bias: Optional[dict[int, float]] = None,
        seed: Optional[int] = None,
        max_tokens: int = 256,
        want_logprobs: bool = False,
    ):
        """Same contract as generate.Generator.generate_step — tokens stream
        out one at a time; every microbatch runs the same prompt (serving
        uses M=1; M>1 is the throughput path driven via raw step calls).
        ``want_logprobs`` yields TokenLogprobs summaries (device-side
        lax.top_k, pulled per block) instead of None."""
        import time as _time

        sp = make_sampler_params(temperature, top_p, repetition_penalty, logit_bias)
        key = jax.random.PRNGKey(
            int(_time.time_ns()) & 0x7FFFFFFF if seed is None else seed
        )
        M, B = self.microbatches, self.batch
        prompt = np.asarray(prompt_tokens, np.int32).reshape(1, 1, -1)
        prompt = np.broadcast_to(prompt, (M, B, prompt.shape[-1]))
        n_prompt = prompt.shape[-1]
        if n_prompt == 0:
            # the prefill loop below would be skipped and the first sample
            # would crash on logits=None — reject at entry instead
            raise ValueError("empty prompt")
        if n_prompt + max_tokens > self.max_seq:
            raise ValueError(
                f"prompt ({n_prompt}) + max_tokens ({max_tokens}) exceeds KV "
                f"capacity {self.max_seq}"
            )

        cache = self.init_cache()
        recent = init_recent_tokens(
            M * B, repetition_context_size, prompt.reshape(M * B, -1)
        )

        c = self.prefill_chunk
        logits = None
        for start in range(0, n_prompt, c):
            chunk = prompt[..., start : start + c]
            n_valid = chunk.shape[-1]
            if n_valid < c:
                chunk = np.pad(chunk, ((0, 0), (0, 0), (0, c - n_valid)))
            logits, cache = self._prefill(
                self.layer_params, self.layer_masks, self.vocab_parts,
                self.shared_params, jnp.asarray(chunk), cache,
                jnp.asarray(n_valid, jnp.int32),
            )
        tok, logprobs, recent, key = self._sample(logits, recent, key, sp)

        from mlx_sharding_tpu.generate import (
            TokenLogprobs,
            block_lp_outputs,
        )

        first_lp = None
        if want_logprobs:
            chosen, tv, ti = block_lp_outputs(tok.reshape(M * B), logprobs)
            first_lp = TokenLogprobs(
                float(chosen[0]), np.asarray(ti[0]), np.asarray(tv[0])
            )
        yield int(tok[0, 0]), first_lp
        remaining = max_tokens - 1
        if remaining <= 0:
            return

        from mlx_sharding_tpu.generate import blocked_token_stream

        block = self.decode_block_prog(self.decode_block, want_logprobs)

        def dispatch(carry):
            outs, t, c, r, k = block(
                self.layer_params, self.layer_masks, self.vocab_parts,
                self.shared_params, carry[0], carry[1], carry[2], carry[3], sp,
            )
            return outs, (t, c, r, k)

        yield from blocked_token_stream(
            dispatch, (tok, cache, recent, key), remaining,
            self.decode_block, want_logprobs, tok_index=(0, 0),
        )
