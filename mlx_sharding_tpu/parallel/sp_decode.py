"""Decode over sp-sharded KV — the long-context decode path.

``sp_prefill`` shards a long prompt's sequence dim over the ``sp`` axis; up
to round 2 the resulting per-layer K/V was all-gathered into ONE device's
cache, so decode stayed bounded by a single chip's HBM (VERDICT r2 weak #5).
This module removes that bound: the cache keeps its sequence dim sharded
over ``sp`` for the whole generation, and each decode step runs distributed
attention over the shards.

For T=1 queries a rotating ring buys nothing — the right collective is a
*partial-softmax merge*: every device computes streaming-softmax statistics
``(m, l, acc)`` over its local KV rows only, then one ``pmax`` + two
``psum``s per layer merge them exactly:

    m_g   = pmax(m_i)
    l_g   = Σ_i l_i · exp(m_i − m_g)
    acc_g = Σ_i acc_i · exp(m_i − m_g)
    attn  = acc_g / l_g

Communication per layer per token is O(B·Hq·Dv) — independent of context
length — riding ICI. Activations/weights are replicated over ``sp`` (every
device runs the same projections/MLP redundantly; what's sharded is the KV
*memory*, which is the resource long contexts exhaust). The new token's K/V
is written only by the device whose shard owns position ``offset``.

The reference has no analogue (its long-context story is a dense T×T mask,
SURVEY §5); this is a capability beyond parity. Wired through the same
model hooks as sp_prefill (``sp_layer``/``sp_groups``): Llama family,
Gemma-2 (per-layer window/softcap) and DeepSeek-V2 MLA (compressed-latent
MQA, values_from_k, grouped dense/moe scan).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mlx_sharding_tpu.cache import KVCache
from mlx_sharding_tpu.parallel.mesh import AXIS_SP, shard_map
from mlx_sharding_tpu.sample import sample_token, update_recent_tokens


def sp_decode_attention(q, k_buf, v_buf, offset, scale, axis_name=AXIS_SP,
                        logit_softcap=None, sliding_window=None):
    """Distributed T=1..T attention: local partial softmax over this device's
    KV shard rows (global positions ``idx*cap + j``), merged exactly across
    ``axis_name``. q (B, T, Hq, Dk); k_buf/v_buf (B, cap_local, Hkv, D).
    Validity: global position <= offset + (query index); ``sliding_window``
    further restricts to the last W positions (Gemma-2), ``logit_softcap``
    caps the scores before masking."""
    b, t, hq, dk = q.shape
    cap, hkv = k_buf.shape[1], k_buf.shape[2]
    groups = hq // hkv
    idx = jax.lax.axis_index(axis_name)

    qg = q.reshape(b, t, hkv, groups, dk)
    scores = jnp.einsum(
        "bthgd,bshd->bhgts", qg, k_buf, preferred_element_type=jnp.float32
    ) * scale
    if logit_softcap is not None:  # same gate as ops.attention (bit parity)
        scores = logit_softcap * jnp.tanh(scores / logit_softcap)
    q_pos = offset + jnp.arange(t)[:, None]  # (T, 1) global
    k_pos = idx * cap + jnp.arange(cap)[None, :]  # (1, cap) global
    allowed = k_pos <= q_pos
    if sliding_window is not None:
        allowed &= k_pos > q_pos - sliding_window
    scores = jnp.where(allowed[None, None, None], scores, -jnp.inf)

    m_loc = scores.max(axis=-1)  # (B, Hkv, G, T)
    m_glob = jax.lax.pmax(m_loc, axis_name)
    m_safe = jnp.where(jnp.isneginf(m_glob), 0.0, m_glob)
    p = jnp.exp(scores - m_safe[..., None])  # -inf rows -> 0
    l_loc = p.sum(axis=-1)
    acc_loc = jnp.einsum(
        "bhgts,bshd->bhgtd", p, v_buf.astype(jnp.float32)
    )
    l_glob = jax.lax.psum(l_loc, axis_name)
    acc_glob = jax.lax.psum(acc_loc, axis_name)
    out = acc_glob / jnp.maximum(l_glob[..., None], 1e-30)
    return out.transpose(0, 3, 1, 2, 4).reshape(b, t, hq, -1).astype(q.dtype)


class SpDecode:
    """Blocked decode over an sp-sharded KV cache for one (model, mesh).

    Owns the jitted shard_map block program (same decode_block / one-block
    lookahead protocol as generate.Generator — see its docstring for the
    host-pull economics). The cache's per-device shard is max_seq/sp rows
    per layer: generation capacity scales with the mesh instead of one
    chip's HBM.
    """

    def __init__(self, model, params, mesh: Mesh, *, decode_block: int = 16):
        self.model = model
        self.mesh = mesh
        self.size = mesh.shape[AXIS_SP]
        self.decode_block = decode_block
        self._rep = NamedSharding(mesh, P())
        # (L, B, S, H, D): shard the sequence axis
        self._kv = NamedSharding(mesh, P(None, None, AXIS_SP))
        self.params = params  # already replicated by the caller (SpPrefill)
        self._blocks: dict = {}
        # jit once — these run on every request's hot path
        self._zeros = jax.jit(
            lambda shape, dtype: jnp.zeros(shape, dtype),
            static_argnums=(0, 1), out_shardings=self._kv,
        )

        def write(k_c, v_c, ks, vs):
            zero = jnp.zeros((), jnp.int32)
            k_c = jax.lax.dynamic_update_slice(
                k_c, ks.astype(k_c.dtype), (zero,) * k_c.ndim
            )
            v_c = jax.lax.dynamic_update_slice(
                v_c, vs.astype(v_c.dtype), (zero,) * v_c.ndim
            )
            return k_c, v_c

        self._write = jax.jit(
            write, donate_argnums=(0, 1), out_shardings=(self._kv, self._kv)
        )

    def make_cache(self, batch: int, max_seq: int, dtype) -> KVCache:
        if max_seq % self.size:
            raise ValueError(
                f"sp={self.size} must divide the cache capacity {max_seq}"
            )
        cfg = self.model.config
        # model-declared cache layout: per-tensor head dims (MLA's K dim ≠
        # V dim) and head count (the compressed latent's single head)
        hd = self.model.cache_head_dim()
        k_dim, v_dim = (hd, hd) if not isinstance(hd, (tuple, list)) else hd
        heads = self.model.cache_num_heads()
        base = (cfg.num_local_layers, batch, max_seq, heads)
        return KVCache(
            k=self._zeros((*base, k_dim), dtype),
            v=self._zeros((*base, v_dim), dtype),
            offset=jax.device_put(jnp.zeros((), jnp.int32), self._rep),
        )

    def write_prefill(self, cache: KVCache, ks, vs, n_valid) -> KVCache:
        """Install sp-prefill K/V (sharded by T_pad/sp chunks) into the
        cache (sharded by max_seq/sp chunks). Plain global-semantics update
        under jit — GSPMD inserts the one-time reshard between the two
        layouts; nothing is gathered to a single device."""
        k_c, v_c = self._write(cache.k, cache.v, ks, vs)
        return KVCache(
            k=k_c, v=v_c,
            offset=jax.device_put(jnp.asarray(n_valid, jnp.int32), self._rep),
        )

    # ------------------------------------------------------------------
    def block_prog(self, want_lp: bool):
        if want_lp not in self._blocks:
            model, K = self.model, self.decode_block

            def step_body(params, tok, k_c, v_c, offset, recent, key, sp):
                """One decode step inside shard_map: replicated activations,
                sharded KV. k_c/v_c are this device's (L, B, cap, H, D)."""
                idx = jax.lax.axis_index(AXIS_SP)
                cap = k_c.shape[2]
                h = model.embed(params, tok[:, None])

                from mlx_sharding_tpu.models.base import scan_layers

                def make_layer(g):
                    def layer(h, p, k_buf, v_buf):
                        # the injected attention owner-writes the new row at
                        # global ``offset`` into this shard, then attends;
                        # the updated buffers escape through ``done`` to
                        # become the scan's cache ys
                        done = {}

                        def attn_fn(q, k_new, v_new, logit_softcap=None,
                                    sliding_window=None, values_from_k=None):
                            local = offset - idx * cap
                            in_range = (local >= 0) & (local < cap)
                            lp = jnp.clip(local, 0, cap - 1)
                            old_k = jax.lax.dynamic_slice_in_dim(k_buf, lp, 1, 1)
                            old_v = jax.lax.dynamic_slice_in_dim(v_buf, lp, 1, 1)
                            k_row = jnp.where(
                                in_range, k_new.astype(k_buf.dtype), old_k
                            )
                            v_row = jnp.where(
                                in_range, v_new.astype(v_buf.dtype), old_v
                            )
                            kb = jax.lax.dynamic_update_slice_in_dim(
                                k_buf, k_row, lp, 1
                            )
                            vb = jax.lax.dynamic_update_slice_in_dim(
                                v_buf, v_row, lp, 1
                            )
                            done["k"], done["v"] = kb, vb
                            vv = (
                                kb[..., :values_from_k]
                                if values_from_k is not None else vb
                            )
                            return sp_decode_attention(
                                q, kb, vv, offset, model.scale,
                                logit_softcap=logit_softcap,
                                sliding_window=sliding_window,
                            )

                        h2, _, _ = model.sp_layer(p, h, offset, attn_fn, group=g)
                        return h2, done["k"], done["v"]

                    return layer

                # per-group scans over the stacked layer sub-trees, the
                # cache buffers sliced to each group's layer range
                lo = 0
                k_parts, v_parts = [], []
                for g in model.sp_groups():
                    stack = params["layers"] if g is None else params["layers"][g]
                    n_g = jax.tree.leaves(stack)[0].shape[0]
                    h, k_g, v_g = scan_layers(
                        make_layer(g), h, stack,
                        k_c[lo : lo + n_g], v_c[lo : lo + n_g],
                    )
                    k_parts.append(k_g)
                    v_parts.append(v_g)
                    lo += n_g
                k_c = (
                    jnp.concatenate(k_parts, axis=0)
                    if len(k_parts) > 1 else k_parts[0]
                )
                v_c = (
                    jnp.concatenate(v_parts, axis=0)
                    if len(v_parts) > 1 else v_parts[0]
                )
                logits = model.apply_head(params, h)
                key, sub = jax.random.split(key)
                tok, logprobs = sample_token(sub, logits[:, -1], sp, recent)
                recent = update_recent_tokens(recent, tok)
                return tok, logprobs, k_c, v_c, offset + 1, recent, key

            def block_body(params, tok, k_c, v_c, offset, recent, key, sp):
                def body(carry, _):
                    tok, k_c, v_c, offset, recent, key = carry
                    tok, logprobs, k_c, v_c, offset, recent, key = step_body(
                        params, tok, k_c, v_c, offset, recent, key, sp
                    )
                    if want_lp:
                        from mlx_sharding_tpu.generate import block_lp_outputs

                        out = (tok, *block_lp_outputs(tok, logprobs))
                    else:
                        out = (tok,)
                    return (tok, k_c, v_c, offset, recent, key), out

                (tok, k_c, v_c, offset, recent, key), outs = jax.lax.scan(
                    body, (tok, k_c, v_c, offset, recent, key), None,
                    length=K,
                )
                return outs, tok, k_c, v_c, offset, recent, key

            rep = P()
            kv = P(None, None, AXIS_SP)
            self._blocks[want_lp] = jax.jit(
                shard_map(
                    block_body,
                    mesh=self.mesh,
                    in_specs=(rep, rep, kv, kv, rep, rep, rep, rep),
                    out_specs=(rep, rep, kv, kv, rep, rep, rep),
                    check_vma=False,
                ),
                donate_argnums=(2, 3, 5),
            )
        return self._blocks[want_lp]
