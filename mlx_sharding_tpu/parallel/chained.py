"""Chained pipeline — per-stage compiled programs with device-resident
parameters and device-to-device activation hand-off.

This is the second pipeline mode, complementing the fused SPMD engine
(parallel/pipeline.py). It reproduces the reference's topology most directly
— a driver that owns the loop and pushes activations through stages in order
(ref: shard/utils.py:156-178, generate.py:52-88) — but where the reference
pays serialize → TCP → Python-deserialize per stage per token
(SURVEY §3.5), here each stage is a jitted program compiled against
parameters committed to its own device, and the hand-off is an async
device-to-device transfer (ICI on real TPU hardware; the host only enqueues).

Why it exists alongside the SPMD engine: it places no structural constraints
on stages. Uneven layer splits and heterogeneous layer stacks (DeepSeek-V2's
dense-prefix + MoE mix) work unchanged, because every stage is its own
program — exactly the flexibility the reference's ``[start, end)`` sharding
offers (BASELINE config #1: DeepSeek split 0-14 / 14-27).
"""

from __future__ import annotations

import time
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from mlx_sharding_tpu.sample import (
    init_recent_tokens,
    make_sampler_params,
    sample_token,
    update_recent_tokens,
)


class ChainedPipeline:
    """Drives a list of stage (model, params) pairs, one per device.

    Stage 0 must be a first-stage config (embeds tokens), the last stage a
    last-stage config (produces logits); bounds may be uneven.
    """

    def __init__(
        self,
        stage_models: Sequence,
        stage_params: Sequence[dict],
        *,
        devices: Optional[Sequence] = None,
        max_seq: int = 4096,
        batch: int = 1,
        cache_dtype=jnp.bfloat16,
        prefill_chunk: int = 256,
    ):
        if len(stage_models) != len(stage_params):
            raise ValueError("one params pytree per stage model")
        if not stage_models[0].config.is_first_stage:
            raise ValueError("stage 0 must start at layer 0")
        if not stage_models[-1].config.is_last_stage:
            raise ValueError("last stage must end at num_hidden_layers")
        self.models = list(stage_models)
        self.num_stages = len(self.models)
        if devices is None:
            devices = jax.devices()[: self.num_stages]
        if len(devices) < self.num_stages:
            raise ValueError(
                f"{self.num_stages} stages need {self.num_stages} devices, "
                f"have {len(devices)}"
            )
        self.devices = list(devices[: self.num_stages])
        self.params = [
            jax.device_put(p, d) for p, d in zip(stage_params, self.devices)
        ]
        self.max_seq = -(-max_seq // prefill_chunk) * prefill_chunk
        self.batch = batch
        self.cache_dtype = cache_dtype
        self.prefill_chunk = prefill_chunk

        # one compiled stage program per stage; compilation happens against
        # the stage's committed device, so execution is placed there
        self._stage_fns = []
        for model in self.models:
            def fn(params, x, cache, n_valid, model=model):
                return model(params, x, cache, n_valid=n_valid)

            self._stage_fns.append(jax.jit(fn, donate_argnums=(2,)))

        def sample_fn(logits, n_valid, recent, key, sp):
            last = jax.lax.dynamic_index_in_dim(logits, n_valid - 1, 1, keepdims=False)
            key, sub = jax.random.split(key)
            tok, logprobs = sample_token(sub, last, sp, recent)
            recent = update_recent_tokens(recent, tok)
            return tok, logprobs, recent, key

        self._sample = jax.jit(sample_fn, donate_argnums=(2,))

    # ------------------------------------------------------------------
    def _make_caches(self):
        return [
            jax.device_put(
                m.make_cache(self.batch, self.max_seq, self.cache_dtype), d
            )
            for m, d in zip(self.models, self.devices)
        ]

    def _forward(self, x, caches, n_valid):
        """Run one token-step through every stage. The loop only enqueues:
        transfers and stage programs are dispatched asynchronously."""
        h = x
        for i, (fn, params) in enumerate(zip(self._stage_fns, self.params)):
            # D2D hop (ICI on TPU); for i==0 this also moves the previously
            # sampled token from the last device back to stage 0. No-op when
            # already resident.
            h = jax.device_put(h, self.devices[i])
            h, caches[i] = fn(params, h, caches[i], n_valid)
        return h, caches

    def generate_step(
        self,
        prompt_tokens,
        *,
        temperature: float = 0.0,
        top_p: float = 1.0,
        repetition_penalty: Optional[float] = None,
        repetition_context_size: int = 20,
        logit_bias: Optional[dict[int, float]] = None,
        seed: Optional[int] = None,
        max_tokens: int = 256,
        want_logprobs: bool = False,  # full (B, V) rows are always yielded
    ):
        """Same contract as generate.Generator.generate_step."""
        sp = make_sampler_params(temperature, top_p, repetition_penalty, logit_bias)
        key = jax.random.PRNGKey(
            int(time.time_ns()) & 0x7FFFFFFF if seed is None else seed
        )
        prompt = np.asarray(prompt_tokens, np.int32).reshape(self.batch, -1)
        n_prompt = prompt.shape[1]
        if n_prompt == 0:
            # without this the prefill loop below never runs and the sample
            # call crashes on logits=None — reject at entry instead
            raise ValueError("empty prompt")
        if n_prompt + max_tokens > self.max_seq:
            raise ValueError(
                f"prompt ({n_prompt}) + max_tokens ({max_tokens}) exceeds KV "
                f"capacity {self.max_seq}"
            )

        caches = self._make_caches()
        recent = init_recent_tokens(self.batch, repetition_context_size, prompt)

        c = self.prefill_chunk
        logits = None
        n_valid = None
        for start in range(0, n_prompt, c):
            chunk = prompt[:, start : start + c]
            n_valid = jnp.asarray(chunk.shape[1], jnp.int32)
            if chunk.shape[1] < c:
                chunk = np.pad(chunk, ((0, 0), (0, c - chunk.shape[1])))
            logits, caches = self._forward(jnp.asarray(chunk), caches, n_valid)

        tok, logprobs, recent, key = self._sample(logits, n_valid, recent, key, sp)

        one = jnp.asarray(1, jnp.int32)
        n = 0
        while True:
            next_logits, caches = self._forward(tok[:, None], caches, one)
            next_tok, next_logprobs, recent, key = self._sample(
                next_logits, one, recent, key, sp
            )
            yield int(tok[0]), logprobs
            n += 1
            if n >= max_tokens:
                break
            tok, logprobs = next_tok, next_logprobs


def load_chained_pipeline(
    model_path: str,
    stage_bounds: Sequence[tuple[int, int]],
    *,
    dtype=jnp.bfloat16,
    keep_quantized: bool = False,
    **kwargs,
) -> ChainedPipeline:
    """Dynamic sharding into a chained pipeline: every stage loads from the
    same full checkpoint with injected bounds (ref: shard/utils.py:36-39),
    e.g. ``stage_bounds=[(0, 14), (14, 27)]`` for the BASELINE DeepSeek
    split."""
    from mlx_sharding_tpu.loading import load_model

    models, params = [], []
    for start, end in stage_bounds:
        m, p = load_model(
            model_path, start, end, dtype=dtype, keep_quantized=keep_quantized
        )
        models.append(m)
        params.append(p)
    return ChainedPipeline(models, params, **kwargs)
