"""Multi-host serving: rank-0 driver + worker protocol over jax.distributed.

This is the reference's per-machine deployment reborn (one shard process per
machine: /root/reference/shard/main.py:4-14, driven over gRPC from the
primary at /root/reference/generate.py:17, shard/utils.py:162-164) on the
TPU-native substrate. Differences, by design:

- The reference ships ACTIVATIONS over the wire every token (serialize →
  TCP → deserialize per stage, SURVEY §3.5). Here the model math runs as
  multi-controller SPMD over one global mesh: every process executes the
  SAME jitted step, and activations cross host boundaries inside XLA
  collectives (ICI/DCN), never through Python.
- The only thing rank 0 broadcasts is CONTROL: request admission (prompt
  tokens + sampler params) and per-token step ops. Sampling is
  replicated-deterministic — same PRNG key chain on every process — so
  sampled tokens never need to be sent anywhere; every process computes
  them identically.
- Rank 0 is the reference's "primary": it owns the tokenizer, the HTTP
  server and the decode loop. Ranks > 0 run :func:`serve_worker`, the
  equivalent of `mlx-sharding-server` (shard/main.py): load the same
  checkpoint, build the same engine, mirror the step sequence.

Wire format: fixed-shape int32/float32 buffers through
``multihost_utils.broadcast_one_to_all`` (a tiny psum over the global mesh),
so the control plane itself is just another XLA collective — no sockets, no
serde code, no message framing.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from mlx_sharding_tpu.sample import (
    init_recent_tokens,
    make_sampler_params,
)
from mlx_sharding_tpu.testing.faults import inject
from mlx_sharding_tpu.utils.clock import MONOTONIC, Clock


class WorkerTimeoutError(RuntimeError):
    """A control-plane collective did not complete in time — a peer rank is
    dead or wedged. The plane is marked down: every later exchange fails
    fast instead of stranding another thread in the collective, so rank 0
    keeps answering (5xx + degraded /health) and can be restarted."""

# control ops
OP_IDLE = 0
OP_REQUEST = 1
OP_DECODE = 2
OP_STOP_REQUEST = 3
OP_SHUTDOWN = 4
# continuous-batching ops (the batched protocol below)
OP_B_ASSIGN = 10
OP_B_PREFILL = 11
OP_B_DECODE = 12
OP_B_CANCEL = 13
OP_B_FAIL = 14

# matches the scheduler's per-slot width (scheduler.py make_sampler_params
# min_bias_slots=512) and the HTTP-layer validation cap, so a request that
# works single-host never fails multi-host (covers OpenAI's documented 300)
_BIAS_SLOTS = 512


class _Shutdown(Exception):
    pass


# Shared wire encoding — the single-stream protocol (_request_msg /
# _start_request) and the batched one (_assign_msg / _req_from_msg) must
# never drift apart on these.

def _pack_seed(seed: int) -> tuple[int, int]:
    """62-bit seed into two int32-safe halves — a full user seed round-trips
    so multi-host reproduces the single-host stream for the same request."""
    return seed & 0x7FFFFFFF, (seed >> 31) & 0x7FFFFFFF


def _unpack_seed(lo, hi) -> int:
    return int(lo) | (int(hi) << 31)


def _pack_bias(logit_bias) -> tuple[np.ndarray, np.ndarray, int]:
    bias_idx = np.zeros((_BIAS_SLOTS,), np.int32)
    bias_val = np.zeros((_BIAS_SLOTS,), np.float32)
    n_bias = 0
    if logit_bias:
        if len(logit_bias) > _BIAS_SLOTS:
            # silent truncation would make multi-host output diverge from
            # the same request served single-host
            raise ValueError(
                f"logit_bias with {len(logit_bias)} entries exceeds the "
                f"multi-host control-plane width {_BIAS_SLOTS}"
            )
        items = list(logit_bias.items())
        n_bias = len(items)
        bias_idx[:n_bias] = [int(k) for k, _ in items]
        bias_val[:n_bias] = [float(v) for _, v in items]
    return bias_idx, bias_val, n_bias


def _unpack_bias(bias_idx, bias_val, n_bias: int):
    return {
        int(i): float(v)
        for i, v in zip(bias_idx[:n_bias], bias_val[:n_bias])
    } or None


class ControlPlane:
    """Fixed-shape broadcast buffers; rank 0 publishes, all ranks receive the
    same pytree (broadcast_one_to_all ignores non-zero ranks' inputs).

    Liveness (rank 0 only): a collective completes only when EVERY rank
    arrives, so a SIGKILLed worker would block rank 0 in the broadcast
    forever, invisible to /health. Rank 0 therefore runs each exchange on a
    dedicated thread and bounds the wait (``MST_MULTIHOST_TIMEOUT_S``,
    default 600s — generous enough for a worker's slowest compile between
    two exchanges; 0 disables). On timeout the plane is marked ``dead``:
    the in-flight request errors to its client, later exchanges fail fast,
    and /health flips to degraded. Workers keep unbounded waits — an idle
    deployment broadcasts nothing, and their liveness is rank 0's concern."""

    header_size = 8

    def __init__(self, max_prompt: int, timeout_s: Optional[float] = None,
                 clock: Clock = MONOTONIC):
        self.max_prompt = max_prompt
        self.clock = clock  # liveness stamps read the injectable source
        if timeout_s is None:
            try:
                timeout_s = float(os.environ.get("MST_MULTIHOST_TIMEOUT_S", "600"))
            except ValueError:
                timeout_s = 600.0
        if jax.process_index() != 0 or timeout_s <= 0:
            timeout_s = None  # workers (and 0 = disabled) wait unbounded
        self.timeout_s = timeout_s
        self.dead = False
        self.last_ok: Optional[float] = None  # monotonic stamp of the last
        # completed collective — proof every rank was alive at that moment
        self._thread = None  # lazy daemon worker (timed exchanges only)
        from mlx_sharding_tpu.analysis.runtime import make_lock

        # serializes the timed path: two callers racing the lazy init would
        # spawn duplicate broadcast threads, and interleaved _work/_out
        # queue traffic could hand one caller the other's reply
        self._lock = make_lock("ControlPlane._lock")

    @staticmethod
    def _broadcast(buf):
        from jax.experimental import multihost_utils

        return multihost_utils.broadcast_one_to_all(buf)

    def _zeros(self):
        return {
            "header": np.zeros((self.header_size,), np.int32),
            "floats": np.zeros((4,), np.float32),
            "tokens": np.zeros((self.max_prompt,), np.int32),
            "bias_idx": np.zeros((_BIAS_SLOTS,), np.int32),
            "bias_val": np.zeros((_BIAS_SLOTS,), np.float32),
        }

    def exchange(self, msg: Optional[dict] = None) -> dict:
        """Collective: rank 0 passes ``msg`` (padded in), workers pass None.
        Everyone gets rank 0's message back as host numpy. Raises
        :class:`WorkerTimeoutError` (rank 0) when a peer doesn't show up
        within the liveness budget, and instantly once the plane is dead."""
        try:
            # fault harness: a raise here simulates a collective whose peer
            # never arrives (faults.DropExchange) — same conclusion as a
            # timeout, detected instantly
            inject("multihost.exchange")
        except Exception as e:  # noqa: BLE001 — any injected failure means
            # the plane can no longer be trusted; normalize like a timeout
            with self._lock:  # exchange's dead-check reads under this lock
                self.dead = True
            raise WorkerTimeoutError(
                "multi-host collective dropped (injected fault) — marking "
                "the control plane down (restart the deployment)"
            ) from e
        buf = self._zeros()
        if msg is not None:
            for k, v in msg.items():
                arr = np.asarray(v).reshape(-1)
                buf[k][: arr.size] = arr
        if self.timeout_s is None:
            out = self._broadcast(buf)
        else:
            # the whole timed path holds the lock: dead-check, lazy init,
            # submit and reply must be one atomic unit or a concurrent
            # caller could collect this caller's broadcast result
            with self._lock:
                if self.dead:
                    raise WorkerTimeoutError(
                        "multi-host control plane is down (a peer rank "
                        "previously failed to respond) — restart the deployment"
                    )
                import queue as _q

                if self._thread is None:
                    # one DAEMON thread issuing collectives in program order:
                    # a timed-out broadcast stays blocked in it forever, and
                    # a daemon can be abandoned at interpreter exit — a
                    # ThreadPoolExecutor worker would be joined by the
                    # concurrent.futures atexit hook and wedge process
                    # shutdown
                    self._work: _q.Queue = _q.Queue()
                    self._out: _q.Queue = _q.Queue()

                    def run():
                        while True:
                            b = self._work.get()
                            try:
                                self._out.put(("ok", self._broadcast(b)))
                            except BaseException as e:  # noqa: BLE001
                                self._out.put(("err", e))

                    import threading

                    self._thread = threading.Thread(
                        target=run, name="mst-ctrl", daemon=True
                    )
                    self._thread.start()
                self._work.put(buf)
                try:
                    kind, val = self._out.get(timeout=self.timeout_s)
                except _q.Empty:
                    self.dead = True  # the broadcast thread stays stuck in
                    # the collective; being a daemon, it is abandoned, never
                    # joined
                    raise WorkerTimeoutError(
                        f"multi-host collective did not complete within "
                        f"{self.timeout_s:.0f}s — a worker rank is dead or "
                        "wedged; failing the request and marking the control "
                        "plane down (restart the deployment)"
                    ) from None
                if kind == "err":
                    # the distributed runtime itself noticed the dead peer
                    # and errored the collective — same conclusion, better
                    # latency. Normalized to WorkerTimeoutError (cause
                    # chained) so every dead-plane swallow site (STOP /
                    # SHUTDOWN / batcher close) behaves identically on both
                    # detection paths.
                    self.dead = True
                    raise WorkerTimeoutError(
                        "multi-host collective failed — the distributed "
                        "runtime reported a dead or unreachable peer rank; "
                        "marking the control plane down (restart the "
                        "deployment)"
                    ) from val
                out = val
        self.last_ok = self.clock()
        return {k: np.asarray(v) for k, v in out.items()}


def _request_msg(prompt, temperature, top_p, repetition_penalty,
                 repetition_context_size, logit_bias, seed, max_tokens):
    prompt = np.asarray(prompt, np.int32).reshape(-1)
    bias_idx, bias_val, n_bias = _pack_bias(logit_bias)
    seed_lo, seed_hi = _pack_seed(seed)
    return {
        "header": np.asarray(
            [OP_REQUEST, prompt.size, max_tokens, seed_lo,
             repetition_context_size,
             0 if repetition_penalty is None else 1, n_bias, seed_hi],
            np.int32,
        ),
        "floats": np.asarray(
            # None-ness rides ONLY in the has_pen header flag: `or 1.0`
            # would mangle an explicit penalty of 0.0 on the wire
            [temperature, top_p,
             1.0 if repetition_penalty is None else repetition_penalty, 0.0],
            np.float32,
        ),
        "tokens": prompt,
        "bias_idx": bias_idx,
        "bias_val": bias_val,
    }


def _start_request(engine, msg):
    """Identical on every rank: prefill the broadcast prompt and sample the
    first token. Returns the rolling decode state."""
    hdr = msg["header"]
    n_prompt = int(hdr[1])
    seed = _unpack_seed(hdr[3], hdr[7])
    rep_ctx = int(hdr[4])
    n_bias = int(hdr[6])
    temperature, top_p, rep_pen = (float(x) for x in msg["floats"][:3])
    bias = _unpack_bias(msg["bias_idx"], msg["bias_val"], n_bias)
    sp = make_sampler_params(
        temperature, top_p, rep_pen if hdr[5] else None, bias
    )
    prompt = msg["tokens"][:n_prompt]

    M, B = engine.microbatches, engine.batch
    arr = np.broadcast_to(prompt.reshape(1, 1, -1), (M, B, n_prompt))
    cache = engine.init_cache()

    # every host-built input must be explicitly committed as a REPLICATED
    # global array: under multi-controller JAX, mixing plain host arrays
    # with global-mesh arrays in one jit is not well-defined
    from jax.sharding import NamedSharding, PartitionSpec as P

    from mlx_sharding_tpu.parallel.pipeline import put_global

    rep = NamedSharding(engine.mesh, P())
    # put_global, not device_put: every rank builds the same value from the
    # broadcast request, so device_put's assert-equal broadcast is overhead
    put = lambda x: put_global(x, rep)  # noqa: E731
    recent = put(init_recent_tokens(M * B, rep_ctx, arr.reshape(M * B, -1)))
    key = put(jax.random.PRNGKey(seed))
    sp = jax.tree.map(put, sp)

    c = engine.prefill_chunk
    logits = None
    for start in range(0, n_prompt, c):
        chunk = arr[..., start : start + c]
        n_valid = chunk.shape[-1]
        if n_valid < c:
            chunk = np.pad(chunk, ((0, 0), (0, 0), (0, c - n_valid)))
        logits, cache = engine._prefill(
            engine.layer_params, engine.layer_masks, engine.vocab_parts,
            engine.shared_params, put(jnp.asarray(chunk)), cache,
            put(jnp.asarray(n_valid, jnp.int32)),
        )
    tok, logprobs, recent, key = engine._sample(logits, recent, key, sp)
    return dict(cache=cache, recent=recent, key=key, sp=sp, tok=tok,
                logprobs=logprobs, _put=put)


def _decode_step(engine, state):
    one = state["_put"](jnp.asarray(1, jnp.int32))
    tok, logprobs, cache, recent, key = engine._decode(
        engine.layer_params, engine.layer_masks, engine.vocab_parts,
        engine.shared_params, state["tok"][..., None], state["cache"],
        state["recent"], state["key"], state["sp"], one,
    )
    state.update(cache=cache, recent=recent, key=key, tok=tok, logprobs=logprobs)
    return state


class MultiHostPipeline:
    """Rank-0 driver with the ``generate_step`` contract. Each yielded token
    was computed redundantly by every process; the broadcasts only carry
    \"take another step\" (one tiny collective per token — the reference pays
    a full activation serialize/RPC per STAGE per token here)."""

    concurrent = False  # requests serialize through the server's gen lock

    def __init__(self, engine):
        self.engine = engine
        self.ctrl = ControlPlane(max_prompt=engine.max_seq)

    def generate_step(
        self,
        prompt_tokens,
        *,
        temperature: float = 0.0,
        top_p: float = 1.0,
        repetition_penalty: Optional[float] = None,
        repetition_context_size: int = 20,
        logit_bias: Optional[dict[int, float]] = None,
        seed: Optional[int] = None,
        max_tokens: int = 256,
        want_logprobs: bool = False,  # full (B, V) rows are always yielded
    ):
        import time as _time

        prompt = np.asarray(prompt_tokens, np.int32).reshape(-1)
        if prompt.size + max_tokens > self.engine.max_seq:
            raise ValueError(
                f"prompt ({prompt.size}) + max_tokens ({max_tokens}) exceeds "
                f"KV capacity {self.engine.max_seq}"
            )
        if seed is not None and not 0 <= int(seed) < (1 << 62):
            raise ValueError("seed must fit in 62 bits for multi-host serving")
        msg = _request_msg(
            prompt, temperature, top_p, repetition_penalty,
            repetition_context_size, logit_bias,
            (int(_time.time_ns()) if seed is None else int(seed)),
            max_tokens,
        )
        self.ctrl.exchange(msg)
        # everything after the OP_REQUEST broadcast sits inside the try:
        # if prefill raises on rank 0, the finally still broadcasts STOP so
        # workers leave the request loop instead of hanging the collective
        try:
            state = _start_request(self.engine, msg)
            n = 0
            while True:
                yield int(np.asarray(state["tok"]).reshape(-1)[0]), state["logprobs"]
                n += 1
                if n >= max_tokens:
                    break
                self.ctrl.exchange({"header": np.asarray([OP_DECODE], np.int32)})
                state = _decode_step(self.engine, state)
        finally:
            # exactly one STOP per request, whether it ran to max_tokens or
            # the consumer closed early (stop sequence / disconnect). A dead
            # control plane (worker timeout mid-request) must not let this
            # raise over the original error — there is no one left to resync.
            try:
                self.ctrl.exchange(
                    {"header": np.asarray([OP_STOP_REQUEST], np.int32)}
                )
            except WorkerTimeoutError:
                pass

    def shutdown(self):
        try:
            self.ctrl.exchange({"header": np.asarray([OP_SHUTDOWN], np.int32)})
        except WorkerTimeoutError:
            pass  # nobody is listening; the plane is already down

    close = shutdown


def _drain_to_stop(ctrl) -> bool:
    """After a local step failure, consume broadcasts until rank 0's
    per-request STOP (its generator ``finally`` always sends exactly one) so
    the collective protocol stays aligned. Returns True on OP_SHUTDOWN."""
    while True:
        step = ctrl.exchange()
        op = int(step["header"][0])
        if op == OP_STOP_REQUEST:
            return False
        if op == OP_SHUTDOWN:
            return True
        if op != OP_DECODE:
            raise RuntimeError(f"worker protocol desync while draining: op {op}")


# --------------------------------------------------------------------------
# Continuous batching over the multi-host control plane.
#
# The scheduler's HOST decisions (which request gets which slot, when a
# prefill chunk runs, when a decode block runs, when a consumer cancels) are
# the only non-deterministic inputs — everything downstream of the op stream
# is deterministic: page allocation pops a mirrored free list, max_tokens
# finishes count mirrored emit loops, sampling is replicated PRNG. So rank 0
# runs the real ContinuousBatcher and broadcasts one tiny op message before
# each DEVICE op; every worker applies the same op to an identical mirror
# batcher and stays in lockstep. (The reference cannot express any of this —
# its serving is one request at a time over RPC-chained shards.)


class BatchControlPlane(ControlPlane):
    """ControlPlane with room for the batched ops' header fields."""

    header_size = 12


def _assign_msg(req, slot: int) -> dict:
    """OP_B_ASSIGN message: the request verbatim, so a worker rebuilds an
    identical _Request (sampler params, seed chain, page need)."""
    prompt = np.asarray(req.prompt, np.int32).reshape(-1)
    bias_idx, bias_val, n_bias = _pack_bias(req.logit_bias)
    seed_lo, seed_hi = _pack_seed(int(req.seed))
    return {
        "header": np.asarray(
            [OP_B_ASSIGN, slot, prompt.size, req.max_tokens,
             seed_lo, seed_hi, req.rep_context,
             0 if req.repetition_penalty is None else 1, n_bias,
             1 if req.want_logprobs else 0, 0, 0],
            np.int32,
        ),
        "floats": np.asarray(
            # see _request_msg: None-ness rides only in the has_pen flag
            [req.temperature, req.top_p,
             1.0 if req.repetition_penalty is None
             else req.repetition_penalty, 0.0],
            np.float32,
        ),
        "tokens": prompt,
        "bias_idx": bias_idx,
        "bias_val": bias_val,
    }


class _DiscardQueue:
    """Worker-side _Request.out: tokens are computed redundantly on every
    rank; only rank 0 has consumers. Dropping keeps device rows from
    accumulating."""

    def put(self, item):
        pass


def _req_from_msg(msg):
    from mlx_sharding_tpu.scheduler import _Request

    hdr = msg["header"]
    n_prompt, max_tokens = int(hdr[2]), int(hdr[3])
    seed = _unpack_seed(hdr[4], hdr[5])
    rep_ctx, has_pen, n_bias = int(hdr[6]), int(hdr[7]), int(hdr[8])
    temperature, top_p, rep_pen = (float(x) for x in msg["floats"][:3])
    bias = _unpack_bias(msg["bias_idx"], msg["bias_val"], n_bias)
    return _Request(
        prompt=np.asarray(msg["tokens"][:n_prompt], np.int32),
        sp=make_sampler_params(
            temperature, top_p, rep_pen if has_pen else None, bias
        ),
        seed=seed,
        max_tokens=max_tokens,
        rep_context=rep_ctx,
        want_logprobs=bool(hdr[9]),
        out=_DiscardQueue(),
        temperature=temperature,
        top_p=top_p,
        repetition_penalty=rep_pen if has_pen else None,
        logit_bias=bias,
    )


def _make_multihost_batcher():
    """Deferred subclassing keeps scheduler import out of this module's
    import time (the class is only needed on serving ranks)."""
    from mlx_sharding_tpu.scheduler import ContinuousBatcher

    class MultiHostBatcher(ContinuousBatcher):
        """Rank-0 continuous batcher that broadcasts each device op before
        applying it, so `serve_worker_batched` mirrors stay in lockstep.
        `--concurrent N` under `--coordinator` builds this."""

        def __init__(self, engine, **kw):
            super().__init__(engine, **kw)
            self.ctrl = BatchControlPlane(max_prompt=engine.max_seq)
            self._shut = False

        def generate_step(self, prompt_tokens, *, seed=None, **kw):
            if seed is not None and not 0 <= int(seed) < (1 << 62):
                raise ValueError(
                    "seed must fit in 62 bits for multi-host serving"
                )
            return super().generate_step(prompt_tokens, seed=seed, **kw)

        def _bcast(self, *header):
            self.ctrl.exchange({"header": np.asarray(header, np.int32)})

        def _assign_slot(self, req, slot):
            self.ctrl.exchange(_assign_msg(req, slot))
            super()._assign_slot(req, slot)

        def _prefill_one_chunk(self, req):
            self._bcast(OP_B_PREFILL, req.slot)
            super()._prefill_one_chunk(req)

        def _decode_once(self):
            self._bcast(OP_B_DECODE)
            super()._decode_once()

        def _reap_cancelled(self):
            # cancellation is the one finish the workers cannot derive
            # (max_tokens finishes they count themselves)
            for req in list(self._slots):
                if req is not None and req.cancelled:
                    self._bcast(OP_B_CANCEL, req.slot)
                    self._finish(req)

        def _fail_all(self, exc):
            import logging

            try:
                self._bcast(OP_B_FAIL)
            except Exception:
                logging.getLogger(__name__).exception(
                    "failed to broadcast scheduler failure"
                )
            super()._fail_all(exc)

        def close(self):
            super().close()  # joins the scheduler thread first: no
            # broadcast can race the shutdown one
            if self._thread is not None and self._thread.is_alive():
                # join timed out (e.g. mid-compile tick): the scheduler
                # thread may still broadcast ops — a SHUTDOWN from here
                # would interleave with them and strand a worker collective.
                # Skip it; process teardown is the backstop.
                return
            if not self._shut:
                self._shut = True  # workers exit on the first SHUTDOWN; a
                # second broadcast would hang awaiting departed peers
                try:
                    self._bcast(OP_SHUTDOWN)
                except WorkerTimeoutError:
                    pass  # plane already down; nothing to shut down

        shutdown = close

    return MultiHostBatcher


def make_multihost_batcher(engine, **kw):
    """Build the rank-0 batcher for multi-host continuous batching."""
    return _make_multihost_batcher()(engine, **kw)


def serve_worker_batched(engine, *, decode_block: int = 8,
                         repetition_window: int = 64,
                         prefix_cache: bool = False) -> None:
    """Rank>0 loop for multi-host continuous batching: apply rank 0's op
    stream to a mirror ContinuousBatcher. ``decode_block`` and
    ``prefix_cache`` must match rank 0's (the block sets the scanned
    program length; the cache changes the page-allocation sequence).

    Prefix caching mirrors deterministically: every index mutation lives
    inside a mirrored op — registration in OP_B_PREFILL, eviction +
    move-to-end during OP_B_ASSIGN, releases in the counted max_tokens
    finishes and OP_B_CANCEL — and rank 0's _fits polls are read-only, so
    identical op streams yield identical page tables on every rank.

    Failure discipline matches :func:`serve_worker`: device-op failures are
    deterministic, so rank 0 hits the same error, fails its consumers and
    broadcasts OP_B_FAIL — which resets this mirror too. An op code outside
    the protocol is a desync and raises."""
    import logging

    from mlx_sharding_tpu.scheduler import ContinuousBatcher

    logger = logging.getLogger(__name__)
    batcher = ContinuousBatcher(
        engine, decode_block=decode_block,
        repetition_window=repetition_window, prefix_cache=prefix_cache,
    )
    ctrl = BatchControlPlane(max_prompt=engine.max_seq)
    while True:
        msg = ctrl.exchange()
        hdr = msg["header"]
        op = int(hdr[0])
        if op == OP_SHUTDOWN:
            return
        if op == OP_B_FAIL:
            batcher._fail_all(RuntimeError("rank 0 scheduler failure"))
            continue
        if op not in (OP_B_ASSIGN, OP_B_PREFILL, OP_B_DECODE, OP_B_CANCEL):
            raise RuntimeError(f"worker protocol desync: unexpected op {op}")
        try:
            if op == OP_B_ASSIGN:
                batcher._assign_slot(_req_from_msg(msg), int(hdr[1]))
            elif op == OP_B_PREFILL:
                batcher._prefill_one_chunk(batcher._slots[int(hdr[1])])
            elif op == OP_B_DECODE:
                batcher._decode_once()
            else:  # OP_B_CANCEL
                req = batcher._slots[int(hdr[1])]
                if req is not None:
                    batcher._finish(req)
        except Exception:
            # deterministic failure: rank 0's identical op fails the same
            # way and OP_B_FAIL arrives next to reset this mirror
            logger.exception("worker batched op %d failed", op)


def serve_worker(engine) -> None:
    """Rank>0 main loop — the reference's shard-server process
    (shard/server/server.py:74-93) with the RPC surface replaced by the
    broadcast control plane. Blocks until rank 0 publishes OP_SHUTDOWN.

    Failure discipline: step failures are DETERMINISTIC (every rank runs the
    identical program on identical inputs), so when a local step raises this
    worker logs it and drains to the request's STOP instead of dying — rank 0
    raises the same error to the client and its ``finally`` broadcasts that
    STOP, leaving all ranks aligned for the next request. Rank-0-only host
    failures reach us as a bare STOP (handled at top level). Genuinely
    asymmetric failures cannot be resynced over a lockstep collective plane
    and surface as the loud desync RuntimeErrors."""
    import logging

    logger = logging.getLogger(__name__)
    ctrl = ControlPlane(max_prompt=engine.max_seq)
    while True:
        msg = ctrl.exchange()
        op = int(msg["header"][0])
        if op == OP_SHUTDOWN:
            return
        if op == OP_STOP_REQUEST:
            # rank 0's prefill failed after OP_REQUEST but before issuing
            # device work — its unconditional STOP resyncs us
            continue
        if op != OP_REQUEST:
            # a silent skip here would desync the collective protocol one
            # exchange at a time; fail loudly instead
            raise RuntimeError(f"worker protocol desync: unexpected op {op}")
        try:
            state = _start_request(engine, msg)
        except Exception:
            logger.exception("worker prefill failed; draining to STOP")
            if _drain_to_stop(ctrl):
                return
            continue
        while True:
            step = ctrl.exchange()
            op = int(step["header"][0])
            if op == OP_DECODE:
                try:
                    state = _decode_step(engine, state)
                except Exception:
                    logger.exception("worker decode failed; draining to STOP")
                    if _drain_to_stop(ctrl):
                        return
                    break
            elif op == OP_STOP_REQUEST:
                break
            elif op == OP_SHUTDOWN:
                return
            else:
                raise RuntimeError(
                    f"worker protocol desync: unexpected op {op} mid-request"
                )


# --------------------------------------------------------------------------
# Pod control plane: the symmetric (every-host-publishes) variant of the
# exchange, for the pod fleet subsystem (pod.py). Where ControlPlane is
# rank-0-publishes / workers-mirror (SPMD lockstep over ONE engine), the pod
# plane stitches N *independent* host fleets together: each host contributes
# its own fixed-shape buffer every pod tick and receives everyone's —
# heartbeats, weight-store registrations, autoscaler pressure, and chunked
# KV-block shipments all ride the same allgather.

# pod header slots (int32[POD_HEADER]): [seq, host_id, n_msgs, blob_used,
# epoch, flags, reserved, reserved]
POD_HEADER = 8


class PodControlPlane:
    """Fixed-shape symmetric exchange over ``process_allgather``.

    Every pod tick, every host calls :meth:`pod_exchange` with its header
    and message blob; the collective returns all hosts' buffers. Because a
    collective only completes when EVERY rank arrives, each host bounds the
    wait with the same timed daemon-thread discipline ControlPlane uses on
    rank 0 (``MST_POD_TIMEOUT_S``, default 60s) — a SIGKILLed peer turns
    into a :class:`WorkerTimeoutError` here, which the pod transport
    surfaces as "all peers dead" so the local fleet degrades to single-host
    serving instead of wedging its pod thread in the collective forever.

    The blob is an opaque uint8 payload (default 256 KiB,
    ``MST_POD_BLOB_BYTES``); framing/chunking is the transport's job
    (pod.CollectiveTransport), keeping this class a pure collective."""

    def __init__(self, blob_bytes: Optional[int] = None,
                 timeout_s: Optional[float] = None,
                 clock: Clock = MONOTONIC):
        if blob_bytes is None:
            try:
                blob_bytes = int(
                    os.environ.get("MST_POD_BLOB_BYTES", str(256 << 10))
                )
            except ValueError:
                blob_bytes = 256 << 10
        self.blob_bytes = max(4096, int(blob_bytes))
        self.clock = clock
        if timeout_s is None:
            try:
                timeout_s = float(os.environ.get("MST_POD_TIMEOUT_S", "60"))
            except ValueError:
                timeout_s = 60.0
        # unlike ControlPlane, EVERY host times its collectives: each host
        # drives its own pod tick loop, so each must detect dead peers
        self.timeout_s = timeout_s if timeout_s > 0 else None
        self.dead = False
        self.last_ok: Optional[float] = None
        self._thread = None
        from mlx_sharding_tpu.analysis.runtime import make_lock

        self._lock = make_lock("PodControlPlane._lock")

    @staticmethod
    def _allgather(buf):
        from jax.experimental import multihost_utils

        return multihost_utils.process_allgather(buf)

    def pod_exchange(self, header: np.ndarray, blob: np.ndarray) -> tuple:
        """One pod tick's collective: contribute ``(header, blob)``, get
        back ``(headers, blobs)`` stacked over hosts (shape ``[n_hosts,
        ...]``). Raises :class:`WorkerTimeoutError` when a peer doesn't
        arrive within the budget, and instantly once the plane is dead —
        the same fail-fast contract as ControlPlane.exchange."""
        try:
            # same fault site as the SPMD plane: a dropped pod collective
            # and a dropped broadcast have identical liveness semantics
            inject("multihost.exchange", plane="pod")
        except Exception as e:  # noqa: BLE001 — injected drop == dead plane
            with self._lock:
                self.dead = True
            raise WorkerTimeoutError(
                "pod collective dropped (injected fault) — marking the pod "
                "control plane down"
            ) from e
        hdr = np.zeros((POD_HEADER,), np.int32)
        hdr[: min(POD_HEADER, np.asarray(header).size)] = \
            np.asarray(header, np.int32).reshape(-1)[:POD_HEADER]
        buf = np.zeros((self.blob_bytes,), np.uint8)
        b = np.asarray(blob, np.uint8).reshape(-1)
        if b.size > self.blob_bytes:
            raise ValueError(
                f"pod blob of {b.size} bytes exceeds the plane width "
                f"{self.blob_bytes} — chunk it (transport bug)"
            )
        buf[: b.size] = b
        tree = {"header": hdr, "blob": buf}
        if self.timeout_s is None:
            out = self._allgather(tree)
        else:
            with self._lock:
                if self.dead:
                    raise WorkerTimeoutError(
                        "pod control plane is down (a peer host previously "
                        "failed to respond)"
                    )
                import queue as _q

                if self._thread is None:
                    # same rationale as ControlPlane: one daemon thread
                    # issuing collectives in program order; a timed-out
                    # allgather strands the thread, not the pod loop
                    self._work: _q.Queue = _q.Queue()
                    self._out: _q.Queue = _q.Queue()

                    def run():
                        while True:
                            t = self._work.get()
                            try:
                                self._out.put(("ok", self._allgather(t)))
                            except BaseException as e:  # noqa: BLE001
                                self._out.put(("err", e))

                    import threading

                    self._thread = threading.Thread(
                        target=run, name="mst-pod-ctrl", daemon=True
                    )
                    self._thread.start()
                self._work.put(tree)
                try:
                    kind, val = self._out.get(timeout=self.timeout_s)
                except _q.Empty:
                    self.dead = True
                    raise WorkerTimeoutError(
                        f"pod collective did not complete within "
                        f"{self.timeout_s:.0f}s — a peer host is dead or "
                        "wedged; marking the pod control plane down"
                    ) from None
                if kind == "err":
                    self.dead = True
                    raise WorkerTimeoutError(
                        "pod collective failed — the distributed runtime "
                        "reported a dead or unreachable peer host"
                    ) from val
                out = val
        self.last_ok = self.clock()
        return np.asarray(out["header"]), np.asarray(out["blob"])
