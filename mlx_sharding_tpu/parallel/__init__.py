from mlx_sharding_tpu.parallel.mesh import (
    AXIS_DP,
    AXIS_EP,
    AXIS_PP,
    AXIS_SP,
    AXIS_TP,
    make_mesh,
)

__all__ = ["make_mesh", "AXIS_PP", "AXIS_TP", "AXIS_DP", "AXIS_SP", "AXIS_EP"]
