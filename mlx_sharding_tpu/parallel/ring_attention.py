"""Ring attention — sequence/context parallelism over the ``sp`` mesh axis.

The reference has no long-context story at all: prefill materializes a dense
T×T mask and pushes the whole prompt through every stage in one call
(SURVEY §5 "Long-context"). Here long sequences shard over ``sp``: each
device keeps its Q block resident and the K/V blocks rotate around the ring
via ``lax.ppermute`` (one ICI hop per step) while a streaming flash-style
softmax (running max / normalizer / output, all fp32) accumulates the exact
attention result. Received blocks are processed in ``block_k`` sub-tiles, so
the live score tensor is O(T/S x block_k) — no (T/S)² (let alone T×T)
score matrix ever exists — and communication overlaps the block matmuls.

Causality is enforced with *global* positions: query block ``s`` holds
positions ``s*T_local + i``; at ring step ``j`` it sees K/V block
``(s - j) mod S``. Blocks strictly in the future contribute nothing and
their masked scores vanish in the streaming update.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mlx_sharding_tpu.parallel.mesh import AXIS_SP, shard_map


def _block_update(scores, v_blk, o, m, l):
    """One streaming-softmax step. scores (B,Hkv,G,T,Tk) fp32 (may contain
    -inf), v_blk (B,Tk,Hkv,Dv). Returns updated (o, m, l)."""
    m_new = jnp.maximum(m, scores.max(axis=-1))
    m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    p = jnp.exp(scores - m_safe[..., None])  # -inf rows -> 0
    corr = jnp.exp(m - m_safe)
    corr = jnp.where(jnp.isneginf(m), 0.0, corr)
    l = l * corr + p.sum(axis=-1)
    pv = jnp.einsum("bhgtk,bkhd->bhgtd", p, v_blk.astype(jnp.float32))
    o = o * corr[..., None] + pv
    return o, m_new, l


def ring_attention_local(
    q, k, v, scale: float, axis_name: str = AXIS_SP, block_k: int = 512,
    logit_softcap=None, sliding_window=None, values_from_k=None,
):
    """shard_map-level kernel: q/k/v are this device's (B, T_local, H, D)
    blocks of a sequence sharded over ``axis_name``. Causal, GQA-aware.
    Returns (B, T_local, Hq, Dv).

    Within each ring step the received K/V block is processed in ``block_k``
    sub-tiles through the same streaming-softmax update, so the live score
    tensor is (B, Hkv, G, T_local, block_k) — per-device activation memory
    stays O(T_local * block_k), never O(T_local^2).

    ``logit_softcap`` applies Gemma-2-style cap*tanh(s/cap) to the scores
    (before masking — tanh of a masked -inf would be NaN); ``sliding_window``
    (may be a traced per-layer scalar) restricts each query to the last W
    positions. Ring steps whose whole K/V block is irrelevant — strictly in
    the causal future, or entirely behind every query's window — skip their
    block matmuls via lax.cond (the rotation still runs): the causal skip
    alone halves the ring's compute, and a sliding window prunes most of the
    rest for long sequences.

    ``values_from_k`` (MLA's latent-as-values): attend values =
    keys[..., :n]; ``v`` is ignored and only the key blocks rotate around
    the ring — compressed MLA pays ~half the ICI bytes it would rotating a
    redundant value copy."""
    import math

    b, t, hq, dk = q.shape
    hkv = k.shape[2]
    groups = hq // hkv
    from mlx_sharding_tpu.parallel.mesh import axis_size

    size = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)

    qg = q.reshape(b, t, hkv, groups, dk)
    q_pos = idx * t + jnp.arange(t)  # global positions of local queries

    bk = math.gcd(t, block_k)  # largest aligned sub-tile <= block_k
    nb = t // bk

    dv = values_from_k if values_from_k is not None else v.shape[-1]
    o = jnp.zeros((b, hkv, groups, t, dv), jnp.float32)
    m = jnp.full((b, hkv, groups, t), -jnp.inf, jnp.float32)
    l = jnp.zeros((b, hkv, groups, t), jnp.float32)

    def step(carry, j):
        if values_from_k is None:
            o, m, l, k_blk, v_blk = carry
        else:
            o, m, l, k_blk = carry
            v_blk = k_blk[..., :values_from_k]
        blk = (idx - j) % size

        # (B, T, H, D) -> (nb, B, bk, H, D) sub-tiles for the inner scan
        k_sub = k_blk.reshape(b, nb, bk, hkv, -1).transpose(1, 0, 2, 3, 4)
        v_sub = v_blk.reshape(b, nb, bk, hkv, -1).transpose(1, 0, 2, 3, 4)

        def sub(carry2, xs):
            o, m, l = carry2
            ks, vs, si = xs
            k_pos = blk * t + si * bk + jnp.arange(bk)
            scores = jnp.einsum(
                "bthgd,bkhd->bhgtk", qg, ks, preferred_element_type=jnp.float32
            ) * scale
            if logit_softcap is not None:  # same gate as ops.attention
                scores = logit_softcap * jnp.tanh(scores / logit_softcap)
            allowed = k_pos[None, :] <= q_pos[:, None]  # (T, bk) global causal
            if sliding_window is not None:
                allowed &= k_pos[None, :] > q_pos[:, None] - sliding_window
            scores = jnp.where(allowed[None, None, None], scores, -jnp.inf)
            return _block_update(scores, vs, o, m, l), None

        def compute(oml):
            out, _ = jax.lax.scan(sub, oml, (k_sub, v_sub, jnp.arange(nb)))
            return out

        # whole-block relevance: its oldest position vs the newest query
        # (causal future) and its newest position vs the oldest query's
        # window edge — a fully-masked block would contribute exactly
        # nothing through the streaming update, so skipping is lossless
        in_future = blk * t > idx * t + (t - 1)
        relevant = ~in_future
        if sliding_window is not None:
            behind = (blk * t + t - 1) < (idx * t - sliding_window + 1)
            relevant &= ~behind
        o, m, l = jax.lax.cond(relevant, compute, lambda oml: oml, (o, m, l))
        k_next = jax.lax.ppermute(
            k_blk, axis_name, [(i, (i + 1) % size) for i in range(size)]
        )
        if values_from_k is not None:
            return (o, m, l, k_next), None
        v_next = jax.lax.ppermute(
            v_blk, axis_name, [(i, (i + 1) % size) for i in range(size)]
        )
        return (o, m, l, k_next, v_next), None

    init = (o, m, l, k) if values_from_k is not None else (o, m, l, k, v)
    outs, _ = jax.lax.scan(step, init, jnp.arange(size))
    o, m, l = outs[0], outs[1], outs[2]
    o = o / jnp.maximum(l[..., None], 1e-30)
    # (B, Hkv, G, T, Dv) -> (B, T, Hq, Dv)
    return o.transpose(0, 3, 1, 2, 4).reshape(b, t, hq, -1).astype(q.dtype)


def ring_attention(q, k, v, scale: float, mesh: Mesh, axis_name: str = AXIS_SP):
    """Driver-level entry: q/k/v (B, T, H, D) get sharded over ``axis_name``
    on their sequence dim and attended exactly. T must divide by the axis
    size."""
    spec = P(None, axis_name)
    f = shard_map(
        lambda q, k, v: ring_attention_local(q, k, v, scale, axis_name),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    sharding = NamedSharding(mesh, spec)
    return f(
        jax.device_put(q, sharding),
        jax.device_put(k, sharding),
        jax.device_put(v, sharding),
    )
