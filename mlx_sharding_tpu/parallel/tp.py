"""Tensor-parallel sharding specs.

The reference splits weights only by layer index (sharding_weight.py:17-20);
intra-stage tensor parallelism doesn't exist there (SURVEY §2.3 "TP: NO").
On TPU it's nearly free to offer: annotate the stacked parameter pytree with
PartitionSpecs over the ``tp`` axis and let GSPMD insert the all-reduces —
column-parallel Q/K/V/gate/up (output dim sharded), row-parallel O/down
(contracting dim sharded), so each decoder block needs exactly one psum per
attention and one per MLP, riding ICI.

These specs compose with the other axes: the leading stacked-layer axis can
carry ``pp`` (layer ranges per stage), batch carries ``dp``, sequence ``sp``.
"""

from __future__ import annotations

from jax.sharding import PartitionSpec as P


def llama_param_specs(tp: str | None = "tp", layers: str | None = None) -> dict:
    """PartitionSpec pytree matching LlamaModel.init_params/map_weights.
    ``layers`` optionally shards the stacked-layer axis (pipeline-style
    weight placement for the GSPMD training path)."""
    col = P(layers, None, tp)  # (L, in, out) — split output dim
    row = P(layers, tp, None)  # (L, in, out) — split contracting dim
    norm = P(layers, None)
    bias = P(layers, tp)  # (L, out) — follows its column-split projection
    return {
        "layers": {
            "input_norm": norm,
            "post_norm": norm,
            "q_proj": col,
            "k_proj": col,
            "v_proj": col,
            "o_proj": row,
            "gate_proj": col,
            "up_proj": col,
            "down_proj": row,
            # build-time fused packed groups (model.fused_projection_groups);
            # engines only fuse at tp == 1 today, entries kept for parity
            "qkv_proj": col,
            "gate_up_proj": col,
            # Qwen2-style QKV biases and Qwen3 per-head q/k norms — present
            # only for those variants; prune_specs drops unused entries
            "q_bias": bias,
            "k_bias": bias,
            "v_bias": bias,
            "q_norm": norm,
            "k_norm": norm,
        },
        "embed": {"weight": P(None, None)},
        "final_norm": {"weight": P(None)},
        "lm_head": {"weight": P(None, tp)},
    }


def prune_specs(specs: dict, params: dict) -> dict:
    """Drop spec entries for params the stage doesn't have (no embed on
    non-first stages, etc.)."""
    return {
        k: (prune_specs(specs[k], v) if isinstance(v, dict) else specs[k])
        for k, v in params.items()
    }
