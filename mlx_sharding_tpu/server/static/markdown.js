/* Minimal markdown renderer for assistant turns — the reference UI pulls
 * `marked` from a CDN (ref shard/static/index.html:81); this build runs in
 * air-gapped deployments, so a small self-contained renderer covers the
 * chat-relevant subset: fenced code blocks, headings, lists, blockquotes,
 * inline code/bold/italic/links. XSS-safe by construction: output is built
 * with createElement/textContent only — model output never reaches
 * innerHTML. */

function renderInline(text) {
  const frag = document.createDocumentFragment();
  // tokenize: `code`, **bold**, *italic*, [label](url)
  const re = /(`[^`]+`)|(\*\*[^*]+\*\*)|(\*[^*\s][^*]*\*)|(\[[^\]]+\]\((?:https?:\/\/|\/(?!\/))[^)\s]+\))/g;
  let last = 0;
  for (let m; (m = re.exec(text)); ) {
    if (m.index > last) frag.append(text.slice(last, m.index));
    const tok = m[0];
    if (m[1]) {
      const el = document.createElement("code");
      el.textContent = tok.slice(1, -1);
      frag.append(el);
    } else if (m[2]) {
      const el = document.createElement("strong");
      el.append(renderInline(tok.slice(2, -2)));
      frag.append(el);
    } else if (m[3]) {
      const el = document.createElement("em");
      el.append(renderInline(tok.slice(1, -1)));
      frag.append(el);
    } else {
      const close = tok.indexOf("](");
      const a = document.createElement("a");
      a.textContent = tok.slice(1, close);
      a.href = tok.slice(close + 2, -1); // http(s)/relative only, per the regex
      a.target = "_blank";
      a.rel = "noopener noreferrer";
      frag.append(a);
    }
    last = m.index + tok.length;
  }
  if (last < text.length) frag.append(text.slice(last));
  return frag;
}

function renderMarkdown(text) {
  const root = document.createDocumentFragment();
  const lines = text.split("\n");
  let i = 0;
  let list = null;
  const flushList = () => { list = null; };
  while (i < lines.length) {
    const line = lines[i];
    // tolerate info strings after the language ("```python title=x") — the
    // open-fence test must accept every line the paragraph scanner excludes
    // with /^```/ or an unmatched line would loop forever
    const fence = line.match(/^```(\w*)/);
    if (fence) {
      flushList();
      const code = [];
      for (i++; i < lines.length && !/^```\s*$/.test(lines[i]); i++) code.push(lines[i]);
      i++; // closing fence (or EOF)
      const pre = document.createElement("pre");
      const codeEl = document.createElement("code");
      if (fence[1]) codeEl.dataset.lang = fence[1];
      codeEl.textContent = code.join("\n");
      pre.append(codeEl);
      root.append(pre);
      continue;
    }
    const heading = line.match(/^(#{1,4})\s+(.*)$/);
    if (heading) {
      flushList();
      const h = document.createElement(`h${heading[1].length + 2}`); // h3..h6
      h.append(renderInline(heading[2]));
      root.append(h);
      i++;
      continue;
    }
    const item = line.match(/^\s*(?:[-*]|\d+\.)\s+(.*)$/);
    if (item) {
      const ordered = /^\s*\d+\./.test(line);
      const tag = ordered ? "ol" : "ul";
      if (!list || list.tagName.toLowerCase() !== tag) {
        list = document.createElement(tag);
        root.append(list);
      }
      const li = document.createElement("li");
      li.append(renderInline(item[1]));
      list.append(li);
      i++;
      continue;
    }
    if (/^\s*>\s?/.test(line)) {
      flushList();
      const quote = [];
      for (; i < lines.length && /^\s*>\s?/.test(lines[i]); i++)
        quote.push(lines[i].replace(/^\s*>\s?/, ""));
      const bq = document.createElement("blockquote");
      bq.append(renderMarkdown(quote.join("\n")));
      root.append(bq);
      continue;
    }
    flushList();
    if (line.trim() === "") {
      i++;
      continue;
    }
    // paragraph: greedy until a blank line or structural line
    const para = [];
    for (; i < lines.length && lines[i].trim() !== "" &&
           !/^(```|#{1,4}\s|\s*(?:[-*]|\d+\.)\s|\s*>)/.test(lines[i]); i++)
      para.push(lines[i]);
    const p = document.createElement("p");
    p.append(renderInline(para.join("\n")));
    root.append(p);
  }
  return root;
}
