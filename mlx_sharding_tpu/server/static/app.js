/* Browser chat client for the SSE API (feature parity with the reference's
 * web UI: settings + named sessions in localStorage, streamed delta
 * rendering, editable user turns with regenerate — ref shard/static/app.js;
 * written fresh for this framework). */

const $ = (id) => document.getElementById(id);
const messagesEl = $("messages");
const SETTINGS_KEYS = ["endpoint", "model", "api_key", "temperature", "top_p", "max_tokens", "stop"];

let history = []; // {role, content}
let aborter = null;

// ---------------------------------------------------------------- settings
function loadSettings() {
  const saved = JSON.parse(localStorage.getItem("mst_settings") || "{}");
  for (const k of SETTINGS_KEYS) if (saved[k] !== undefined) $(k).value = saved[k];
}
function saveSettings() {
  const out = {};
  for (const k of SETTINGS_KEYS) out[k] = $(k).value;
  localStorage.setItem("mst_settings", JSON.stringify(out));
}
SETTINGS_KEYS.forEach((k) => $(k).addEventListener("change", saveSettings));
loadSettings();

// ---------------------------------------------------------------- sessions
function refreshSessions() {
  const sessions = JSON.parse(localStorage.getItem("mst_sessions") || "{}");
  const ul = $("session-list");
  ul.innerHTML = "";
  for (const name of Object.keys(sessions)) {
    const li = document.createElement("li");
    const label = document.createElement("span");
    label.textContent = name;
    const del = document.createElement("span");
    del.textContent = "✕";
    del.className = "del";
    del.onclick = (e) => {
      e.stopPropagation();
      delete sessions[name];
      localStorage.setItem("mst_sessions", JSON.stringify(sessions));
      refreshSessions();
    };
    li.onclick = () => {
      history = sessions[name].slice();
      render();
    };
    li.append(label, del);
    ul.append(li);
  }
}
$("save-session").onclick = () => {
  const name = $("session-name").value.trim() || new Date().toISOString();
  const sessions = JSON.parse(localStorage.getItem("mst_sessions") || "{}");
  sessions[name] = history;
  localStorage.setItem("mst_sessions", JSON.stringify(sessions));
  refreshSessions();
};
$("clear-chat").onclick = () => {
  history = [];
  render();
};
refreshSessions();

// --------------------------------------------------------------- rendering
function render() {
  messagesEl.innerHTML = "";
  history.forEach((m, i) => {
    const div = document.createElement("div");
    div.className = `msg ${m.role}`;
    const meta = document.createElement("div");
    meta.className = "meta";
    const role = document.createElement("span");
    role.textContent = m.role;
    const actions = document.createElement("span");
    actions.className = "actions";
    if (m.role === "user") {
      actions.textContent = "✎ edit";
      actions.onclick = () => editMessage(i);
    } else {
      actions.textContent = "↻ regenerate";
      actions.onclick = () => regenerate(i);
    }
    meta.append(role, actions);
    const body = document.createElement("div");
    if (m.role === "assistant") {
      body.className = "markdown";
      body.append(renderMarkdown(m.content)); // DOM-built, XSS-safe
    } else {
      body.textContent = m.content;
    }
    div.append(meta, body);
    messagesEl.append(div);
  });
  messagesEl.scrollTop = messagesEl.scrollHeight;
}

function editMessage(i) {
  const next = prompt("Edit message:", history[i].content);
  if (next === null) return;
  history[i].content = next;
  history = history.slice(0, i + 1); // drop everything after the edit
  render();
  send(false);
}

function regenerate(i) {
  history = history.slice(0, i); // drop this assistant turn
  render();
  send(false);
}

// --------------------------------------------------------------- streaming
async function send(fromComposer = true) {
  if (aborter) return;
  if (fromComposer) {
    const text = $("input").value.trim();
    if (!text) return;
    $("input").value = "";
    history.push({ role: "user", content: text });
  }
  history.push({ role: "assistant", content: "" });
  render();
  const liveEl = messagesEl.lastChild.lastChild;
  liveEl.classList.add("cursor");

  const stopWords = $("stop").value.split(",").map((s) => s.trim()).filter(Boolean);
  const payload = {
    model: $("model").value,
    messages: history.slice(0, -1),
    temperature: parseFloat($("temperature").value),
    top_p: parseFloat($("top_p").value),
    max_tokens: parseInt($("max_tokens").value, 10),
    stream: true,
  };
  if (stopWords.length) payload.stop = stopWords;

  aborter = new AbortController();
  $("stop-gen").hidden = false;
  $("send").hidden = true;
  try {
    const headers = { "Content-Type": "application/json" };
    const apiKey = $("api_key").value.trim();
    if (apiKey) headers["Authorization"] = `Bearer ${apiKey}`;
    const resp = await fetch($("endpoint").value, {
      method: "POST",
      headers,
      body: JSON.stringify(payload),
      signal: aborter.signal,
    });
    if (!resp.ok) {
      const err = await resp.json().catch(() => ({}));
      throw new Error(err.error?.message || `HTTP ${resp.status}`);
    }
    const reader = resp.body.getReader();
    const decoder = new TextDecoder();
    let buf = "";
    for (;;) {
      const { done, value } = await reader.read();
      if (done) break;
      buf += decoder.decode(value, { stream: true });
      let idx;
      while ((idx = buf.indexOf("\n\n")) >= 0) {
        const line = buf.slice(0, idx).trim();
        buf = buf.slice(idx + 2);
        if (!line.startsWith("data: ")) continue;
        const data = line.slice(6);
        if (data === "[DONE]") continue;
        const chunk = JSON.parse(data);
        const delta = chunk.choices?.[0]?.delta?.content;
        if (delta) {
          history[history.length - 1].content += delta;
          liveEl.textContent = history[history.length - 1].content;
          messagesEl.scrollTop = messagesEl.scrollHeight;
        }
      }
    }
  } catch (e) {
    if (e.name !== "AbortError") {
      history[history.length - 1].content += `\n[error: ${e.message}]`;
    }
  } finally {
    liveEl.classList.remove("cursor");
    aborter = null;
    $("stop-gen").hidden = true;
    $("send").hidden = false;
    render();
  }
}

$("composer").onsubmit = (e) => {
  e.preventDefault();
  send();
};
$("stop-gen").onclick = () => aborter?.abort();
$("input").addEventListener("keydown", (e) => {
  if (e.key === "Enter" && !e.shiftKey) {
    e.preventDefault();
    send();
  }
});
