"""OpenAI-compatible HTTP server with SSE streaming.

Behavior-parity target is the reference's API front end
(ref: shard/openai_api.py): ``POST /v1/completions`` and
``POST /v1/chat/completions`` (routing ref :182-186), CORS headers
(ref :137-141), static web-UI serving on GET (ref :157-176), request
parameter validation (ref :252-294), chat-template prompt building with a
plain role-mapped fallback (ref convert_chat :46-67), non-streaming
responses with usage + token logprobs (ref :357-434), SSE streaming that
buffers partial stop-sequences so a half-emitted stop word never reaches the
client (ref :436-505), and a model provider that caches the loaded model and
can hot-swap on request (ref ModelProvider :70-127).

The execution engine underneath is the TPU stack: one resident
``Generator``/``PipelineEngine`` whose compiled step programs are reused
across requests — a request costs zero compiles. Generation is serialized by
a lock (the honest version of the reference's single-threaded-HTTP-server
concurrency story, SURVEY §5 "race detection"; here it is explicit instead
of accidental).
"""

from __future__ import annotations

import heapq
import json
import logging
import os
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from typing import Optional

import numpy as np

from mlx_sharding_tpu import tracing
from mlx_sharding_tpu.analysis.runtime import make_lock
from mlx_sharding_tpu.generate import TokenLogprobs
from mlx_sharding_tpu.kv_compress import load_compress_map
from mlx_sharding_tpu.kv_share import load_share_map
from mlx_sharding_tpu.resilience import (
    QueueFullError,
    ReplicasUnavailableError,
    RequestTimeoutError,
)
from mlx_sharding_tpu.testing.faults import inject
from mlx_sharding_tpu.tokenizer_utils import (
    StreamingDetokenizer,
    sequence_overlap,
    stopping_criteria,
)
from mlx_sharding_tpu.utils.observability import ServingMetrics, profile_trace
from mlx_sharding_tpu.weights import weight_store

logger = logging.getLogger(__name__)

STATIC_DIR = Path(__file__).parent / "static"
CONTENT_TYPES = {
    ".html": "text/html",
    ".js": "application/javascript",
    ".css": "text/css",
    ".json": "application/json",
}


def _encode_plain(tokenizer, text: str) -> list[int]:
    """Encode without special tokens (stop sequences must match raw ids)."""
    try:
        return list(tokenizer.encode(text, add_special_tokens=False))
    except TypeError:
        return list(tokenizer.encode(text))


def convert_chat(messages: list, role_mapping: Optional[dict] = None) -> str:
    """Plain-text fallback prompt when the tokenizer has no chat template
    (semantics of ref shard/openai_api.py:46-67)."""
    default = {
        "system_prompt": "A chat between a curious user and an artificial "
        "intelligence assistant. The assistant follows the given rules no "
        "matter what.",
        "system": "ASSISTANT's RULE: ",
        "user": "USER: ",
        "assistant": "ASSISTANT: ",
        "stop": "\n",
    }
    role_mapping = role_mapping or default
    prompt = role_mapping.get("system_prompt", "")
    for m in messages:
        role = m["role"]
        prefix = role_mapping.get(role, "")
        stop = role_mapping.get("stop", "")
        prompt += f"{prefix}{m['content']}{stop}"
    prompt += role_mapping.get("assistant", "")
    return prompt.rstrip()


class _SliceAllocator:
    """Free-list of per-replica device slices. The spawn factories used to
    burn a fresh slice index per spawn (``spawn_state["next"] += 1``), so a
    few spawn/drain cycles exhausted the grid while drained replicas'
    devices sat idle — a device-slice leak. Retired slices now come back
    through ``ReplicaSet.on_retire`` and are handed out lowest-index-first
    (heap), so the fleet reuses hardware instead of failing spawns."""

    def __init__(self, devices, per: int):
        self.devices = devices
        self.per = per
        self.total = len(devices) // per
        self._free = list(range(self.total))
        heapq.heapify(self._free)
        self._lock = make_lock("_SliceAllocator._lock")

    def slice_for(self, i: int):
        return self.devices[i * self.per : (i + 1) * self.per]

    def take(self) -> int:
        with self._lock:
            if not self._free:
                raise RuntimeError(
                    f"no free device slice: all {self.total} slices of "
                    f"{self.per} device(s) are held by live replicas"
                )
            return heapq.heappop(self._free)

    def give(self, i: int):
        with self._lock:
            # a double-give is an upstream bug, but corrupting the heap
            # with a duplicate entry would hand one slice to two replicas
            if 0 <= i < self.total and i not in self._free:
                heapq.heappush(self._free, i)

    def free_count(self) -> int:
        with self._lock:
            return len(self._free)


class ModelProvider:
    """Loads and caches one model+tokenizer, swapping when a request names a
    different one (ref shard/openai_api.py:70-127). Paths are validated to
    stay under the working directory, as the reference does."""

    def __init__(
        self,
        default_model: Optional[str] = None,
        *,
        start_layer: Optional[int] = None,
        end_layer: Optional[int] = None,
        num_stages: Optional[int] = None,
        stage_bounds: Optional[list[tuple[int, int]]] = None,
        engine: str = "fused",
        concurrent: int = 1,
        multihost: bool = False,
        tp: int = 1,
        ep: int = 1,
        max_seq: int = 4096,
        prefill_chunk: int = 256,
        cache_dtype=None,
        trust_remote_paths: bool = False,
        chat_template: Optional[str] = None,
        keep_quantized: bool = False,
        decode_block: int = 16,
        paged_pool: Optional[int] = None,
        page_size: Optional[int] = None,
        paged_attention: str = "auto",
        kv_dtype: Optional[str] = None,
        kv_share_map: Optional[str] = None,
        kv_compress_map: Optional[str] = None,
        kv_compress_rank: Optional[int] = None,
        admission_policy: str = "fifo",
        overcommit: bool = False,
        spill_bytes: Optional[int] = None,
        spill_cold_after: Optional[int] = None,
        kv_prefetch: str = "auto",
        draft_model: Optional[str] = None,
        spec_k: int = 4,
        draft: str = "auto",
        spec_window_max: Optional[int] = None,
        prompt_cache: bool = False,
        prefix_store: bool = False,
        prefix_store_bytes: Optional[int] = None,
        prefix_insert_min_hits: int = 1,
        replicas: int = 1,
        max_queue: Optional[int] = None,
        async_sched: str = "auto",
        autoscale: bool = False,
        autoscale_min: Optional[int] = None,
        autoscale_max: Optional[int] = None,
        autoscale_interval: float = 2.0,
        autoscale_cooldown: float = 15.0,
        brownout: bool = True,
        disagg: bool = False,
        prefill_replicas: int = 1,
        decode_replicas: int = 1,
        shared_weights: str = "auto",
        pod: bool = False,
    ):
        # admission control: per-batcher bound on queued requests; a full
        # queue rejects with QueueFullError (HTTP 429 + Retry-After)
        self.max_queue = max_queue
        # async tick pipelining in the continuous batcher: dispatch decode
        # block t+1 before harvesting block t ("auto" = on for plain
        # single-host decode, off when speculating/multi-host)
        self.async_sched = async_sched
        # data-parallel serving: R independent engine replicas, each on its
        # own slice of jax.devices(), score-based request routing
        self.replicas = max(1, replicas)
        # elastic fleet (fleet.py): autoscaler loop spawning/draining
        # replicas under queue pressure, brownout degradation ladder
        self.autoscale = bool(autoscale)
        self.autoscale_min = autoscale_min
        self.autoscale_max = autoscale_max
        self.autoscale_interval = autoscale_interval
        self.autoscale_cooldown = autoscale_cooldown
        self.brownout = bool(brownout)
        self.fleet = None  # FleetAutoscaler once a ReplicaSet is loaded
        # disaggregated prefill/decode serving (disagg.py): two role-split
        # replica pools bridged by KVPageBlock handoff; with --autoscale,
        # self.fleet becomes a (prefill, decode) controller tuple
        self.disagg = bool(disagg)
        self.prefill_replicas = max(1, prefill_replicas)
        self.decode_replicas = max(1, decode_replicas)
        # pod-scale serving (pod.py): N independent host-local fleets (one
        # per process, engines on local devices only) stitched by the pod
        # gossip plane — NOT the SPMD mirror plane (the two are mutually
        # exclusive, so only one collective plane ever exists)
        self.pod = bool(pod)
        self.pod_fleet = None  # PodFleet once a generator is loaded
        # cross-replica shared weights (weights.WeightStore): one resident
        # packed tree per host, every replica co-located on one model-
        # parallel slice and aliasing it — fleet weight bytes ~W, not N×W.
        # "auto" turns it on exactly when a fleet would otherwise hold N
        # copies: multiple replicas (or disagg pools), single-host, on the
        # fused-engine path.
        self.shared_weights = shared_weights
        self.shared_weights_active = False
        # speculative decoding: --draft selects the proposal source
        # ("auto" keeps the legacy contract — engine iff --draft-model,
        # else off; "ngram" drafts from the stream's own history, no
        # second checkpoint), --spec-window-max bounds the per-slot
        # adaptive window ladder
        self.draft_model = draft_model
        self.spec_k = spec_k
        self.draft_mode = draft
        self.spec_window_max = spec_window_max
        # prompt-prefix KV reuse across requests (single-chip generator)
        self.prompt_cache = prompt_cache
        # fleet-wide content-addressed prefix KV store (prefix_store.py):
        # ONE store shared by every batcher this provider builds — device
        # entries leased copy-on-write within a replica, host-tier blocks
        # imported across replicas. Subsumes --prompt-cache (main()
        # rejects the combination).
        self.prefix_store = bool(prefix_store)
        self.prefix_store_bytes = prefix_store_bytes
        self.prefix_insert_min_hits = prefix_insert_min_hits
        self.prefix_store_obj = None  # built once per load()
        self.chat_template = chat_template
        self.keep_quantized = keep_quantized
        # decode steps fused per program launch: 16 amortizes a network-
        # attached chip's per-pull round trip; 1 restores strict per-token
        # streaming granularity for a locally-attached device
        self.decode_block = max(1, decode_block)
        # paged KV pool (continuous batching): pages shared across slots,
        # reservation admission — see scheduler.ContinuousBatcher
        self.paged_pool = paged_pool
        self.page_size = page_size
        # decode-attention path over the pool: "ragged" attends in place
        # (ops/paged_attention.py), "gather" materializes the contiguous
        # per-slot view, "auto" picks ragged where the engine supports it
        self.paged_attention = paged_attention
        # KV-pool storage: "int8" stores {codes, per-row-per-head scale}
        # pools at ~half the bytes of bf16 (see cache.quantize_kv_rows)
        self.kv_dtype = kv_dtype
        # layer-wise KV sharing (kv_share.py, KVSharer): path to a
        # calibrated share-map artifact; pools allocate one physical
        # buffer per share GROUP. Loaded once here — a bad artifact fails
        # at startup, not per-engine-build
        self.kv_share_map_path = kv_share_map
        self.kv_share_map = load_share_map(kv_share_map)
        # compressed-latent KV transport (kv_compress.py): path to a
        # calibrated low-rank artifact (GQA models) — MLA-native models
        # compress without one. Loaded once here, same startup-failure
        # contract as the share map; --kv-compress-rank truncates the
        # nested SVD basis to a cheaper operating point
        self.kv_compress_map_path = kv_compress_map
        self.kv_compress_map = load_compress_map(
            kv_compress_map, kv_compress_rank)
        self.admission_policy = admission_policy
        self.overcommit = overcommit
        # host-DRAM spill tier for preempted requests' KV page blocks
        # (kv_transfer.KVSpillTier): resume re-imports instead of
        # re-prefilling; None = legacy discard preemption
        self.spill_bytes = spill_bytes
        # proactive residency: spill slots whose consumer stopped pulling
        # for N ticks, and stage re-imports ahead of the resume tick
        self.spill_cold_after = spill_cold_after
        self.kv_prefetch = kv_prefetch
        self.default_model = default_model
        self.start_layer = start_layer
        self.end_layer = end_layer
        self.num_stages = num_stages
        self.stage_bounds = stage_bounds
        self.engine = engine
        self.concurrent = max(1, concurrent)
        self.multihost = multihost
        self.tp = max(1, tp)
        self.ep = max(1, ep)
        self.max_seq = max_seq
        self.prefill_chunk = prefill_chunk
        self.cache_dtype = cache_dtype
        self.trust_remote_paths = trust_remote_paths
        self._key: Optional[str] = None
        # hot-swap loads must be serialized: two concurrent requests naming
        # different models would otherwise race _key/generator mutation and
        # double-load onto the device
        self._load_lock = make_lock("ModelProvider._load_lock")
        self.generator = None
        self.tokenizer = None
        if default_model:
            self.load("default_model")

    @property
    def prefix_cache_enabled(self) -> bool:
        """--prompt-cache with a paged pool. The ONE definition every
        consumer (rank-0 batcher, multi-host batcher, worker mirror) must
        share: the cache changes the page-allocation sequence, so a
        rank-divergent answer here is a multi-host desync."""
        return bool(self.prompt_cache and self.paged_pool is not None)

    def kv_share_stats(self) -> Optional[dict]:
        """Layer-wise KV sharing summary for /metrics and /health: the
        configured map's geometry plus the first live engine's measured
        pool-bytes saving (every engine binds the same artifact, so one
        engine's view is the fleet's per-engine view). None when no
        --kv-share-map is configured — the metric families stay absent."""
        m = self.kv_share_map
        if m is None:
            return None
        out = {
            "enabled": not m.is_identity,
            "groups": m.num_groups,
            "layers": m.num_layers,
            "share_hash": m.share_hash,
            "bytes_saved": 0,
        }
        try:
            eng = getattr(getattr(self, "generator", None), "engine", None)
            fn = getattr(eng, "kv_share_stats", None)
            if fn is not None:
                out["bytes_saved"] = int(fn().get("bytes_saved", 0))
        except Exception:  # noqa: BLE001 — geometry still renders
            pass
        return out

    def kv_compress_stats(self) -> Optional[dict]:
        """Compressed-latent KV transport summary for /metrics and
        /health: the live engine codec's counters (blocks, faults, bytes
        raw vs wire) when one is bound — which covers MLA-native models
        that compress WITHOUT a configured map — else the configured
        artifact's geometry, else None (metric families stay absent)."""
        try:
            eng = getattr(getattr(self, "generator", None), "engine", None)
            fn = getattr(eng, "kv_compress_stats", None)
            live = fn() if fn is not None else None
        except Exception:  # noqa: BLE001 — fall back to map geometry
            live = None
        if live is not None:
            return live
        m = self.kv_compress_map
        if m is None:
            return None
        return {
            "mode": "lowrank",
            "compress_hash": m.compress_hash,
            "rank": m.rank,
            "blocks_compressed": 0,
            "blocks_reconstructed": 0,
            "compress_faults": 0,
            "reconstruct_faults": 0,
            "bytes_raw_total": 0,
            "bytes_wire_total": 0,
            "bytes_saved_total": 0,
        }

    def _shared_weights_on(self, *, weight_bytes: int = 0, want: int = 0,
                           per: int = 0, n_devices: int = 0) -> bool:
        """Resolve --shared-weights. ``on`` forces (main() already rejected
        the incompatible multihost/chained configs); ``auto`` prices the
        trade capacity-aware when the caller passes the fleet shape.

        Sharing co-locates all ``want`` replicas on ONE slice: it saves
        ``(want-1)*W`` of weight uploads but squeezes every replica's KV
        headroom into the single slice's budget ``B`` instead of spreading
        the fleet over ``want`` private slices. Equating the two — bytes
        saved ``(N-1)W`` against per-slice KV headroom forfeited
        ``(B-W)(N-1)/N`` — sharing wins exactly when ``W*(N+1) >= B``.
        ``B`` comes from ``MST_DEVICE_MEMORY_BYTES`` (per device, scaled by
        the slice width); unset means the budget is unknown and ``auto``
        keeps the legacy rule (a fleet always shares). A grid too small
        for ``want`` private slices forces sharing regardless: co-location
        is then the only way the fleet fits at all."""
        mode = (self.shared_weights or "auto").lower()
        if mode == "off":
            return False
        if mode == "on":
            return True
        if not ((self.replicas > 1 or self.disagg) and not self.multihost):
            return False
        if not (weight_bytes and want > 1 and per):
            return True
        if n_devices and want * per > n_devices:
            logger.info(
                "shared-weights auto: forced ON — %d private slices of %d "
                "devices exceed the %d-device grid", want, per, n_devices,
            )
            return True
        per_device = int(os.environ.get("MST_DEVICE_MEMORY_BYTES", 0) or 0)
        if per_device <= 0:
            return True
        budget = per_device * per
        share = weight_bytes * (want + 1) >= budget
        logger.info(
            "shared-weights auto: %s — weights %.1f MiB x (%d replicas + 1) "
            "%s slice budget %.1f MiB (saved upload %.1f MiB vs KV headroom "
            "%.1f MiB/replica private)",
            "ON" if share else "OFF", weight_bytes / 2**20, want,
            ">=" if share else "<", budget / 2**20,
            (want - 1) * weight_bytes / 2**20,
            max(0, budget - weight_bytes) / 2**20,
        )
        return share

    def _load_draft(self, cache_dtype):
        """Load the draft model pair for speculative decoding. The draft
        rides the packed path only if IT is a quantized checkpoint — a
        dense draft next to a quantized target is a legitimate pairing."""
        from mlx_sharding_tpu.loading import (
            get_model_path,
            load_config,
            load_model,
        )

        draft_quant = (
            load_config(get_model_path(self.draft_model))
            .get("quantization") is not None
        )
        return load_model(
            self.draft_model, dtype=cache_dtype,
            keep_quantized=self.keep_quantized and draft_quant,
        )

    def _validate(self, name: str) -> str:
        if name == "default_model":
            if not self.default_model:
                raise ValueError(
                    "no default model configured; request must name a model"
                )
            return self.default_model
        # Only allow local paths inside CWD unless explicitly trusted
        # (ref shard/openai_api.py:96-104 cwd-relative validation).
        p = Path(name)
        if not self.trust_remote_paths:
            # Proper containment check — a plain str.startswith would let a
            # sibling like /root/repo-evil pass for cwd /root/repo.
            if not p.resolve().is_relative_to(Path.cwd().resolve()):
                raise ValueError(f"model path {name!r} escapes the working directory")
        return name

    def load(self, name: str):
        target = self._validate(name)
        with self._load_lock:
            if self._key == target:
                return self.generator, self.tokenizer
            if self.multihost and self._key is not None:
                # workers mirror only the step sequence, not model swaps
                raise ValueError(
                    "model hot-swap is not supported in multi-host serving"
                )
            logger.info("loading model %s", target)
            import jax.numpy as jnp

            from mlx_sharding_tpu.generate import Generator
            from mlx_sharding_tpu.loading import get_model_path, load_model

            cache_dtype = self.cache_dtype or jnp.bfloat16
            pstore = None  # built below iff --prefix-store applies
            if self.stage_bounds and self.engine == "chained":
                from mlx_sharding_tpu.parallel.chained import load_chained_pipeline

                generator = load_chained_pipeline(
                    target, self.stage_bounds, dtype=cache_dtype,
                    max_seq=self.max_seq, cache_dtype=cache_dtype,
                    prefill_chunk=self.prefill_chunk,
                    keep_quantized=self.keep_quantized,
                )
            else:
                model, params = load_model(
                    target, self.start_layer, self.end_layer, dtype=cache_dtype,
                    keep_quantized=self.keep_quantized,
                )
                stages = (
                    len(self.stage_bounds) if self.stage_bounds
                    else (self.num_stages or 1)
                )
                if (
                    stages > 1 or self.concurrent > 1 or self.tp > 1
                    or self.ep > 1 or self.replicas > 1 or self.disagg
                ):
                    import jax as _jax

                    from mlx_sharding_tpu.parallel.mesh import make_mesh
                    from mlx_sharding_tpu.parallel.pipeline import PipelineEngine

                    draft_pair = (
                        self._load_draft(cache_dtype)
                        if self.draft_model and self.concurrent > 1 else None
                    )

                    if (self.prefix_store and self.concurrent > 1
                            and self.paged_pool and not self.multihost):
                        from mlx_sharding_tpu.prefix_store import PrefixStore

                        # ONE store for the whole fleet: every batcher
                        # (all replicas, both disagg pools, autoscaler
                        # spawns) binds to it — device entries are
                        # per-engine (page ids are pool-local) but the
                        # host tier and the digest index span the fleet
                        pstore = PrefixStore(
                            host_bytes=self.prefix_store_bytes or (256 << 20),
                            insert_min_hits=self.prefix_insert_min_hits,
                        )

                    per = stages * self.tp * self.ep
                    # a pod host's fleet lives on ITS devices only — local
                    # meshes are process-addressable, so each host builds
                    # engines without any cross-host program
                    devices = (
                        _jax.local_devices() if self.pod else _jax.devices()
                    )
                    want = (
                        self.prefill_replicas + self.decode_replicas
                        if self.disagg else self.replicas
                    )
                    shared = self._shared_weights_on(
                        weight_bytes=sum(
                            getattr(leaf, "nbytes", 0)
                            for leaf in _jax.tree.leaves(params)
                        ),
                        want=want, per=per, n_devices=len(devices),
                    ) and not self.multihost
                    self.shared_weights_active = shared
                    if shared:
                        # shared-weights replicas all co-locate on ONE
                        # model-parallel slice and alias one resident tree
                        # (jit rejects arrays committed to a different
                        # device set, so sharing REQUIRES co-location) —
                        # fleet size is bounded by KV memory, not by how
                        # many weight copies the grid can hold
                        if per > len(devices):
                            raise ValueError(
                                f"shared-weights serving needs one slice "
                                f"of {per} devices, have {len(devices)}"
                            )
                    elif want * per > len(devices):
                        raise ValueError(
                            f"{want} replicas x {per} devices each "
                            f"needs {want * per} devices, have "
                            f"{len(devices)}"
                        )

                    alloc = _SliceAllocator(devices, per)
                    store = key = build_weights = None
                    if shared:
                        from mlx_sharding_tpu.loading import (
                            checkpoint_signature,
                        )
                        from mlx_sharding_tpu.parallel.mesh import (
                            mesh_fingerprint,
                        )
                        from mlx_sharding_tpu.parallel.pipeline import (
                            place_weights,
                        )
                        from mlx_sharding_tpu.weights import (
                            WeightKey,
                            aliased_spawn,
                            weight_store,
                        )

                        base_mesh = make_mesh(
                            pp=stages, tp=self.tp, ep=self.ep,
                            devices=devices[:per],
                        )
                        store = weight_store()
                        key = WeightKey(
                            checkpoint=checkpoint_signature(
                                target, keep_quantized=self.keep_quantized
                            ),
                            stage_bounds=(
                                tuple(tuple(b) for b in self.stage_bounds)
                                if self.stage_bounds else ("auto", stages)
                            ),
                            dtype=jnp.dtype(cache_dtype).name,
                            # build-time transforms are part of the tree's
                            # identity: projection fusion rewrites the
                            # layout, the autotune sweep fixes kernel picks
                            quant=(
                                f"tp{self.tp}:ep{self.ep}"
                                f":fuse="
                                f"{os.environ.get('MST_FUSE_PROJ', '')}"
                                f":tune="
                                f"{os.environ.get('MST_QMM_AUTOTUNE', '')}"
                            ),
                            placement=mesh_fingerprint(base_mesh),
                        )

                        def build_weights():
                            return place_weights(
                                model, params, base_mesh,
                                stage_bounds=self.stage_bounds,
                            )

                        if draft_pair is not None:
                            # the draft checkpoint is a WeightStore tree
                            # exactly like the base: keyed by its own
                            # checkpoint signature + placement, aliased by
                            # every replica on this host, digest gossiped
                            # over the pod heartbeat by the same registry
                            draft_mesh = make_mesh(
                                pp=1, tp=1, ep=1, devices=devices[:per]
                            )
                            draft_key = WeightKey(
                                checkpoint=checkpoint_signature(
                                    self.draft_model,
                                    keep_quantized=self.keep_quantized,
                                ),
                                stage_bounds=("auto", 1),
                                dtype=jnp.dtype(cache_dtype).name,
                                quant="draft",
                                placement=mesh_fingerprint(draft_mesh),
                            )

                            def build_draft_weights():
                                dm, dp = draft_pair
                                return place_weights(dm, dp, draft_mesh)

                    def build_engine(dev_slice, *, weights_lease=None,
                                     speculate=True):
                        if weights_lease is not None:
                            engine = PipelineEngine(
                                model, None, weights_lease.weights.mesh,
                                weights=weights_lease.weights,
                                stage_bounds=self.stage_bounds,
                                microbatches=self.concurrent,
                                max_seq=self.max_seq,
                                cache_dtype=cache_dtype,
                                prefill_chunk=self.prefill_chunk,
                                decode_block=self.decode_block,
                                pool_pages=self.paged_pool
                                if self.concurrent > 1 else None,
                                page_size=self.page_size,
                                paged_attention=self.paged_attention,
                                kv_dtype=self.kv_dtype,
                                kv_share_map=self.kv_share_map
                                if self.paged_pool and self.concurrent > 1
                                else None,
                                kv_compress_map=self.kv_compress_map
                                if self.paged_pool and self.concurrent > 1
                                else None,
                            )
                            # retirement releases the ref; the LAST engine
                            # to close frees the store's tree
                            engine.on_close(weights_lease.release)
                        else:
                            engine = PipelineEngine(
                                model, params,
                                make_mesh(pp=stages, tp=self.tp, ep=self.ep,
                                          devices=dev_slice),
                                stage_bounds=self.stage_bounds,
                                microbatches=self.concurrent,
                                max_seq=self.max_seq,
                                cache_dtype=cache_dtype,
                                prefill_chunk=self.prefill_chunk,
                                decode_block=self.decode_block,
                                pool_pages=self.paged_pool
                                if self.concurrent > 1 else None,
                                page_size=self.page_size,
                                paged_attention=self.paged_attention,
                                kv_dtype=self.kv_dtype,
                                kv_share_map=self.kv_share_map
                                if self.paged_pool and self.concurrent > 1
                                else None,
                                kv_compress_map=self.kv_compress_map
                                if self.paged_pool and self.concurrent > 1
                                else None,
                            )
                        if self.concurrent > 1 and not self.multihost:
                            from mlx_sharding_tpu.scheduler import (
                                ContinuousBatcher,
                            )

                            draft_eng = None
                            if draft_pair is not None and speculate:
                                dmodel, dparams = draft_pair
                                if shared:
                                    # alias the store's resident draft
                                    # tree; the ref drops when the batcher
                                    # closes this engine. Same spawn
                                    # contract as the base tree: a faulted
                                    # build releases before re-raising.
                                    def make_draft(dlease):
                                        deng = PipelineEngine(
                                            dmodel, None,
                                            dlease.weights.mesh,
                                            weights=dlease.weights,
                                            microbatches=self.concurrent,
                                            max_seq=self.max_seq,
                                            cache_dtype=cache_dtype,
                                            prefill_chunk=self.prefill_chunk,
                                        )
                                        deng.on_close(dlease.release)
                                        return deng

                                    draft_eng = aliased_spawn(
                                        store, draft_key,
                                        build_draft_weights, make_draft,
                                    )
                                else:
                                    draft_eng = PipelineEngine(
                                        dmodel, dparams,
                                        make_mesh(pp=1, tp=1, ep=1,
                                                  devices=dev_slice),
                                        microbatches=self.concurrent,
                                        max_seq=self.max_seq,
                                        cache_dtype=cache_dtype,
                                        prefill_chunk=self.prefill_chunk,
                                    )
                            engine = ContinuousBatcher(
                                engine,
                                decode_block=min(8, self.decode_block),
                                policy=self.admission_policy,
                                prefix_cache=self.prefix_cache_enabled,
                                overcommit=self.overcommit,
                                spill_bytes=self.spill_bytes,
                                spill_cold_after=self.spill_cold_after,
                                kv_prefetch=self.kv_prefetch,
                                draft_engine=draft_eng,
                                spec_k=self.spec_k,
                                draft=self.draft_mode if speculate else "off",
                                spec_window_max=(
                                    self.spec_window_max if speculate
                                    else None
                                ),
                                max_queue=self.max_queue,
                                async_sched=self.async_sched,
                                prefix_store=pstore,
                            )
                        return engine

                    def spawn_replica(speculate=True):
                        """One replica by either strategy: alias the
                        store's resident tree (shared) or take a private
                        device slice and upload a full copy. Both paths
                        leave state consistent when the build faults — the
                        lease is released / the slice returned before the
                        error propagates, so the autoscaler degrades to
                        the static fleet with nothing leaked and nothing
                        freed in use. ``speculate=False`` builds a
                        non-drafting replica (disagg prefill pools: a
                        prefill replica emits one token per request, so
                        draft windows there are pure ballast)."""
                        if shared:
                            return aliased_spawn(
                                store, key, build_weights,
                                lambda lease: build_engine(
                                    devices[:per], weights_lease=lease,
                                    speculate=speculate,
                                ),
                            )
                        i = alloc.take()
                        try:
                            eng = build_engine(
                                alloc.slice_for(i), speculate=speculate
                            )
                        except BaseException:
                            alloc.give(i)
                            raise
                        eng._mst_slice = i
                        return eng

                    def recycle_slice(rep):
                        # ReplicaSet.on_retire: a drained-and-closed
                        # replica's device slice goes back on the free list
                        # (shared replicas carry no slice tag — their
                        # release rides the engine close hook)
                        i = getattr(rep, "_mst_slice", None)
                        if i is not None:
                            alloc.give(i)

                    if self.disagg:
                        from mlx_sharding_tpu.disagg import DisaggCoordinator
                        from mlx_sharding_tpu.replicas import ReplicaSet

                        if self.concurrent <= 1:
                            raise ValueError(
                                "disagg serving requires concurrent > 1: "
                                "only the continuous batcher can park a "
                                "prefill-only request and resume it from a "
                                "KV page block"
                            )
                        n_pf = self.prefill_replicas
                        n_dc = self.decode_replicas
                        # role-aware spawns: decode replicas speculate
                        # (adaptive windows per stream), prefill replicas
                        # never do — and their autoscaler factories below
                        # inherit the same role
                        import functools

                        spawn_prefill = functools.partial(
                            spawn_replica, speculate=False
                        )
                        prefill = ReplicaSet([
                            spawn_prefill() for _ in range(n_pf)
                        ], role="prefill", prefix_store=pstore)
                        decode = ReplicaSet([
                            spawn_replica() for _ in range(n_dc)
                        ], role="decode", prefix_store=pstore)
                        prefill.on_retire = recycle_slice
                        decode.on_retire = recycle_slice
                        generator = DisaggCoordinator(
                            prefill, decode, prefix_store=pstore
                        )
                        if self.autoscale:
                            from mlx_sharding_tpu.fleet import FleetAutoscaler

                            # Two controllers, one per role pool — each
                            # reads only its own pool's pressure
                            # (fleet.pool_pressure), so a prefill storm
                            # can't spawn decode replicas and vice versa.
                            # Private spawns draw device slices from the
                            # shared free list: the pools compete for
                            # leftover (and recycled) hardware first-come,
                            # and an empty list fails the next spawn —
                            # which degrades to the static pool, by design.
                            # Shared spawns consume no slice, so each pool
                            # keeps at least one elastic spawn even on a
                            # fully-consumed grid.
                            spare = alloc.total - (n_pf + n_dc)
                            self.fleet = tuple(
                                FleetAutoscaler(
                                    pool,
                                    spawn_prefill if pool is prefill
                                    else spawn_replica,
                                    min_replicas=base,
                                    max_replicas=base + (
                                        max(1, spare) if shared
                                        else max(0, spare)
                                    ),
                                    interval_s=self.autoscale_interval,
                                    cooldown_s=self.autoscale_cooldown,
                                    enable_brownout=self.brownout,
                                )
                                for pool, base in (
                                    (prefill, n_pf), (decode, n_dc)
                                )
                            )
                            for ctrl in self.fleet:
                                ctrl.start()
                    elif self.replicas > 1:
                        from mlx_sharding_tpu.replicas import ReplicaSet

                        generator = ReplicaSet([
                            spawn_replica() for _ in range(self.replicas)
                        ], prefix_store=pstore)
                        generator.on_retire = recycle_slice
                        if self.autoscale:
                            from mlx_sharding_tpu.fleet import FleetAutoscaler

                            hw_max = alloc.total
                            self.fleet = FleetAutoscaler(
                                generator, spawn_replica,
                                min_replicas=self.autoscale_min or 1,
                                # shared replicas don't consume device
                                # slices, so the grid doesn't cap the fleet
                                # — KV memory does; private spawns stay
                                # clamped to the slice count (now a true
                                # bound on LIVE replicas, since drains
                                # recycle slices through the free list)
                                max_replicas=(
                                    (self.autoscale_max or hw_max) if shared
                                    else min(
                                        self.autoscale_max or hw_max, hw_max
                                    )
                                ),
                                interval_s=self.autoscale_interval,
                                cooldown_s=self.autoscale_cooldown,
                                enable_brownout=self.brownout,
                            )
                            self.fleet.start()
                    else:
                        generator = spawn_replica()
                    if self.multihost:
                        # (--replicas is rejected with --coordinator, so
                        # `generator` here is the raw single engine)
                        if _jax.process_index() > 0:
                            # raw engine: serve_worker / serve_worker_batched
                            # wraps it in its own mirror state
                            pass
                        elif self.concurrent > 1:
                            from mlx_sharding_tpu.parallel.multihost import (
                                make_multihost_batcher,
                            )

                            generator = make_multihost_batcher(
                                generator,
                                decode_block=min(8, self.decode_block),
                                policy=self.admission_policy,
                                prefix_cache=self.prefix_cache_enabled,
                                max_queue=self.max_queue,
                            )
                        else:
                            from mlx_sharding_tpu.parallel.multihost import (
                                MultiHostPipeline,
                            )

                            generator = MultiHostPipeline(generator)
                elif self.draft_mode == "ngram":
                    # single-stream prompt-lookup speculation: drafts from
                    # the stream's own history, no second checkpoint
                    from mlx_sharding_tpu.speculative import (
                        NgramSpeculativeGenerator,
                    )

                    generator = NgramSpeculativeGenerator(
                        model, params,
                        spec_window_max=self.spec_window_max or 8,
                        max_seq=self.max_seq, cache_dtype=cache_dtype,
                        prefill_chunk=self.prefill_chunk,
                        decode_block=self.decode_block,
                    )
                elif self.draft_model:
                    from mlx_sharding_tpu.speculative import (
                        SpeculativeGenerator,
                    )

                    dmodel, dparams = self._load_draft(cache_dtype)
                    generator = SpeculativeGenerator(
                        model, params, dmodel, dparams, spec_k=self.spec_k,
                        max_seq=self.max_seq, cache_dtype=cache_dtype,
                        prefill_chunk=self.prefill_chunk,
                        decode_block=self.decode_block,
                    )
                else:
                    generator = Generator(
                        model, params, max_seq=self.max_seq,
                        cache_dtype=cache_dtype,
                        prefill_chunk=self.prefill_chunk,
                        decode_block=self.decode_block,
                        prompt_cache=self.prompt_cache,
                    )
            if self.pod:
                # stitch this host's fleet into the pod: gossip transport
                # over the PodControlPlane, weight-registry + handoff +
                # pod-autoscaler front door wrapping the local generator
                # (DisaggCoordinator gets the cross-host decode leg via
                # attach_pod inside PodFleet)
                from mlx_sharding_tpu.pod import CollectiveTransport, PodFleet

                ctrls = (
                    self.fleet if isinstance(self.fleet, tuple)
                    else (self.fleet,) if self.fleet is not None else ()
                )
                transport = CollectiveTransport()
                pf = PodFleet(
                    transport.host_id, transport, generator,
                    controllers=list(ctrls),
                    # federate the prefix store's host tier over the pod:
                    # its digest inventory rides the heartbeat and a local
                    # miss can pull the owner's exported block instead of
                    # re-prefilling (pod.PodPrefixFederation)
                    prefix_store=pstore,
                )
                pf.start()
                self.pod_fleet = pf
                generator = pf
            from transformers import AutoTokenizer

            tokenizer = AutoTokenizer.from_pretrained(str(get_model_path(target)))
            # swap the fleet store with the generator: _set closes the old
            # generator first (its close() drops its owner entries), so
            # the old store drains cleanly before its host tier is freed
            old_store, self.prefix_store_obj = self.prefix_store_obj, pstore
            self._set(target, generator, tokenizer)
            if old_store is not None:
                old_store.close()
            return self.generator, self.tokenizer

    def _set(self, key, generator, tokenizer):
        # operator-supplied chat template wins over the checkpoint's
        # (ref shard/openai_api.py --chat-template flag behavior)
        if getattr(self, "chat_template", None):
            tokenizer.chat_template = self.chat_template
        old = getattr(self, "generator", None)
        self._key = key
        self.generator = generator
        self.tokenizer = tokenizer
        if old is not None and hasattr(old, "close"):
            old.close()  # stop a replaced batcher's scheduler thread
            # a fleet controller bound to the replaced generator died with
            # it (rs.close() stopped the loop) — drop the stale handle;
            # disagg stores a (prefill, decode) controller tuple whose
            # pools hang off the replaced coordinator
            fleet = getattr(self, "fleet", None)
            ctrls = fleet if isinstance(fleet, tuple) else (fleet,)
            owned = {id(o) for o in (old, getattr(old, "prefill", None),
                                     getattr(old, "decode", None))
                     if o is not None}
            if any(c is not None and getattr(c, "rs", None) is not None
                   and id(c.rs) in owned for c in ctrls):
                self.fleet = None


class APIHandler(BaseHTTPRequestHandler):
    """One handler class per server instance, bound to its provider via a
    factory (class attributes), as stdlib requires."""

    provider: ModelProvider = None
    gen_lock: threading.Lock = None
    metrics: ServingMetrics = None
    profile_dir: Optional[str] = None
    api_key: Optional[str] = None
    # server-wide deadline defaults (--request-timeout / --ttft-timeout);
    # per-request body fields override them
    request_timeout: Optional[float] = None
    ttft_timeout: Optional[float] = None
    protocol_version = "HTTP/1.1"

    # ------------------------------------------------------------- helpers
    def log_message(self, fmt, *args):
        logger.debug("%s - %s", self.address_string(), fmt % args)

    def _cors(self):
        # ref shard/openai_api.py:137-141
        self.send_header("Access-Control-Allow-Origin", "*")
        self.send_header("Access-Control-Allow-Methods", "GET, POST, OPTIONS")
        self.send_header("Access-Control-Allow-Headers", "Content-Type, Authorization")

    def _json(self, code: int, payload: dict,
              extra_headers: Optional[dict] = None):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        # per-request headers accumulated during handling (brownout level,
        # caps) ride along on whatever response finally goes out
        headers = dict(getattr(self, "_resp_headers", None) or {})
        headers.update(extra_headers or {})
        for k, v in headers.items():
            self.send_header(k, str(v))
        self._cors()
        self.end_headers()
        self.wfile.write(body)

    def _error(self, code: int, message: str,
               extra_headers: Optional[dict] = None):
        # OpenAI error envelope with a type that reflects the status class,
        # so clients can distinguish bad requests from engine failures.
        kind = (
            "invalid_request_error" if code == 400
            else "not_found_error" if code == 404
            else "overloaded_error" if code == 429
            else "service_unavailable_error" if code == 503
            else "timeout_error" if code == 504
            else "server_error"
        )
        self._json(
            code, {"error": {"message": message, "type": kind, "code": code}},
            extra_headers=extra_headers,
        )

    # ------------------------------------------------------------- routing
    def do_OPTIONS(self):
        self.send_response(204)
        self._cors()
        self.end_headers()

    def do_GET(self):
        # static web UI (ref shard/openai_api.py:157-176)
        path = self.path.split("?")[0]
        if path in ("/", "/index.html"):
            path = "/index.html"
        elif path == "/health":
            # Layered health: the generator's own view (scheduler thread
            # liveness / per-replica circuit state — ok, degraded, draining)
            # plus multi-host control-plane liveness. ``serving`` decides the
            # status code: partial capacity (some replicas circuit-broken,
            # ≥1 alive) is degraded WITH a 200 — degraded, not dead; a
            # wedged scheduler, drained server, or dead control plane is a
            # 503.
            gen = self.provider.generator
            payload, serving = {"status": "ok"}, True
            if hasattr(gen, "health"):
                payload = dict(gen.health())
                serving = bool(payload.pop("serving", True))
            # resident weight-tree occupancy (weights.WeightStore): how many
            # trees this host holds, how many engine refs alias them, and
            # the resident bytes — the N×W → ~W number, live
            try:
                st = weight_store().stats()
                payload["weight_store"] = {
                    "shared_weights": bool(
                        getattr(self.provider, "shared_weights_active",
                                False)
                    ),
                    "trees": st["trees"],
                    "refs": st["refs"],
                    "bytes": st["bytes"],
                }
            except Exception:  # noqa: BLE001 — health must render anyway
                pass
            # fleet prefix store: residency split, hit rate, insertion-
            # policy counters — the block operators watch to size
            # --prefix-store-bytes and tune --prefix-insert-min-hits
            store = getattr(self.provider, "prefix_store_obj", None)
            if store is not None:
                try:
                    payload["prefix_store"] = store.stats()
                except Exception:  # noqa: BLE001 — health must render anyway
                    pass
            # pod fleet: per-host liveness/weights from the gossip view,
            # handoff + autoscaler counters — absent on every single-host
            # deployment (shape contract: no pod key, no host labels)
            pod = getattr(self.provider, "pod_fleet", None)
            if pod is not None:
                try:
                    payload["pod"] = pod.pod_stats()
                except Exception:  # noqa: BLE001 — health must render anyway
                    pass
            if getattr(self.provider, "kv_share_map", None) is not None:
                try:
                    payload["kv_share"] = self.provider.kv_share_stats()
                except Exception:  # noqa: BLE001 — health must render anyway
                    pass
            try:
                kc = self.provider.kv_compress_stats()
                if kc is not None:
                    payload["kv_compress"] = kc
            except Exception:  # noqa: BLE001 — health must render anyway
                pass
            ctrl = getattr(gen, "ctrl", None)
            if ctrl is not None:
                # a timed-out collective marks the plane dead (multihost.py
                # ControlPlane); every completed one proves all ranks alive
                import time as _time

                last = getattr(ctrl, "last_ok", None)
                payload["multihost"] = {
                    "workers_responsive": not getattr(ctrl, "dead", False),
                    "last_exchange_s_ago": (
                        None if last is None
                        else round(_time.monotonic() - last, 1)
                    ),
                }
                if getattr(ctrl, "dead", False):
                    payload["status"] = "degraded"
                    serving = False
            return self._json(200 if serving else 503, payload)
        elif path == "/admin/trace" or path.startswith("/admin/trace/"):
            # flight-recorder readout: /admin/trace/dump is the whole ring
            # (+ incident snapshots) as ONE chrome://tracing JSON document;
            # /admin/trace/<request_id> is one request's timeline (live,
            # retired, or preserved in a snapshot)
            tracer = tracing.get_tracer()
            if tracer is None or not tracer.enabled:
                return self._error(
                    404, "tracing is off — start the server with "
                         "--trace sample|on"
                )
            rest = path[len("/admin/trace"):].strip("/")
            if rest in ("", "dump"):
                return self._json(200, tracer.export_dump())
            payload = tracer.export_request(rest)
            if payload is None:
                return self._error(404, f"no trace recorded for {rest!r}")
            return self._json(200, payload)
        elif path == "/metrics":
            body = self.metrics.render().encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self._cors()
            self.end_headers()
            self.wfile.write(body)
            return
        target = (STATIC_DIR / path.lstrip("/")).resolve()
        if not str(target).startswith(str(STATIC_DIR.resolve())) or not target.is_file():
            return self._error(404, f"not found: {self.path}")
        body = target.read_bytes()
        self.send_response(200)
        self.send_header("Content-Type", CONTENT_TYPES.get(target.suffix, "application/octet-stream"))
        self.send_header("Content-Length", str(len(body)))
        self._cors()
        self.end_headers()
        self.wfile.write(body)

    # request bodies above this are rejected before being read — an
    # unauthenticated client must not be able to buffer arbitrary bytes or
    # pin a handler thread with a huge/negative Content-Length
    MAX_BODY = 8 << 20

    ADMIN_ROUTES = ("/admin/drain", "/admin/autoscaler")

    def do_POST(self):
        route = self.path.split("?")[0]
        self._resp_headers: dict = {}  # reset per request (handler reuse)
        handlers = {
            "/v1/completions": self._handle_text_completion,
            "/v1/chat/completions": self._handle_chat_completion,
        }
        try:
            length = int(self.headers.get("Content-Length", 0))
        except ValueError:
            length = -1
        if not 0 <= length <= self.MAX_BODY:
            self.close_connection = True  # can't safely drain; don't reuse
            return self._error(413, "invalid or oversized request body")
        try:
            raw = self.rfile.read(length)  # always drain — before ANY reply,
            # including 404/401: replying with the body unread desyncs
            # HTTP/1.1 keep-alive (the leftover bytes would parse as the
            # next request line)
        except OSError:
            return self._error(400, "unreadable request body")
        if route not in handlers and route not in self.ADMIN_ROUTES:
            return self._error(404, f"unknown route {route}")
        if self.api_key:
            # the reference UI sends Authorization: Bearer <key>
            # (ref shard/static/app.js:151) but its server never checks it;
            # here --api-key makes the check real. Static/health/metrics
            # stay open — only the generation endpoints are gated.
            # bytes compare: compare_digest rejects non-ASCII str, and
            # header bytes are remotely controlled
            import hmac

            auth = self.headers.get("Authorization", "").encode(
                "utf-8", "surrogateescape"
            )
            want = f"Bearer {self.api_key}".encode()
            if not hmac.compare_digest(auth, want):
                return self._json(401, {"error": {
                    "message": "invalid or missing API key",
                    "type": "authentication_error", "code": 401,
                }})
        try:
            body = json.loads(raw or b"{}")
        except json.JSONDecodeError:
            return self._error(400, "invalid JSON body")
        if route == "/admin/drain":
            # operator surface, not a generation request: no sampler params
            # to validate and no model hot-swap — but it IS key-gated above
            return self._handle_drain(body)
        if route == "/admin/autoscaler":
            return self._handle_autoscaler(body)
        try:
            params = self._validate_params(body)
        except ValueError as e:
            return self._error(400, str(e))
        try:
            generator, tokenizer = self.provider.load(body.get("model", "default_model"))
        except ValueError as e:
            return self._error(400, str(e))
        try:
            handlers[route](body, params, generator, tokenizer)
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away; _generate's close already cancelled
            # the in-flight request (the scheduler reclaims its slot/pages)
        except QueueFullError as e:
            # load shed at admission: the queue bound was hit before any
            # work was spent; tell the client when to come back
            try:
                self._error(429, str(e), extra_headers={
                    "Retry-After": str(max(1, round(e.retry_after_s))),
                })
            except Exception:
                pass
        except RequestTimeoutError as e:
            try:
                self._error(504, str(e))
            except Exception:
                pass
        except ReplicasUnavailableError as e:
            # every replica circuit-broken: the error carries the earliest
            # half-open probe ETA, so tell the client when a retry could
            # actually be admitted instead of inviting an instant hammer
            ra = getattr(e, "retry_after_s", None)
            hdrs = (
                {"Retry-After": str(max(1, round(ra)))}
                if isinstance(ra, (int, float)) and not isinstance(ra, bool)
                else None
            )
            try:
                self._error(503, str(e), extra_headers=hdrs)
            except Exception:
                pass
        except ValueError as e:  # bad request discovered late (e.g. KV capacity)
            try:
                self._error(400, str(e))
            except Exception:
                pass
        except Exception as e:  # return a structured error, don't kill the conn
            logger.exception("request failed")
            try:
                self._error(500, f"{type(e).__name__}: {e}")
            except Exception:
                pass

    def _handle_drain(self, body: dict):
        """POST /admin/drain ``{"replica": i, "deadline": s}`` — gracefully
        retire one replica. Its admitted requests migrate to the remaining
        replicas (their clients' streams continue seamlessly) and /health
        reports ``draining`` for the duration. 400 without --replicas
        serving; a mid-migration failure leaves the replica quarantined
        (500, retryable) with nothing dropped."""
        gen = self.provider.generator
        drain = getattr(gen, "drain", None)
        if drain is None:
            return self._error(400, "drain requires --replicas serving "
                                    "(a ReplicaSet generator)")
        if "replica" not in body:
            return self._error(400, "missing 'replica' index")
        try:
            replica = int(body["replica"])
            deadline = float(body.get("deadline", 30.0))
            if deadline <= 0:
                raise ValueError
        except (TypeError, ValueError):
            return self._error(400, "'replica' must be an integer and "
                                    "'deadline' a positive number of seconds")
        try:
            result = drain(replica, deadline=deadline)
        except ValueError as e:
            return self._error(400, str(e))
        except Exception as e:
            logger.exception("replica drain failed")
            return self._error(500, f"{type(e).__name__}: {e}")
        return self._json(200, result)

    def _handle_autoscaler(self, body: dict):
        """POST /admin/autoscaler ``{"enabled": bool}`` — start/stop the
        fleet autoscaler loop (omit ``enabled`` to just inspect it).
        Returns the controller's counters plus the brownout ladder state.
        400 when the server wasn't launched with --autoscale."""
        fleet = getattr(self.provider, "fleet", None)
        if fleet is None:
            return self._error(400, "autoscaler requires --autoscale "
                                    "(and --replicas > 1 or --disagg) "
                                    "serving")
        # --disagg runs one controller per role pool; start/stop applies
        # to both, and the response carries a per-pool state list
        ctrls = fleet if isinstance(fleet, tuple) else (fleet,)
        enabled = body.get("enabled")
        if enabled is not None and not isinstance(enabled, bool):
            return self._error(400, "'enabled' must be a boolean")
        try:
            for ctrl in ctrls:
                if enabled is True:
                    ctrl.start()
                elif enabled is False:
                    ctrl.stop()
        except Exception as e:
            logger.exception("autoscaler control failed")
            return self._error(500, f"{type(e).__name__}: {e}")

        def _state(ctrl):
            out = dict(ctrl.state())
            bro = getattr(ctrl, "brownout", None)
            if bro is not None:
                out["brownout"] = bro.state()
            return out

        if len(ctrls) == 1:
            return self._json(200, _state(ctrls[0]))
        return self._json(200, {"pools": [_state(c) for c in ctrls]})

    # ---------------------------------------------------------- validation
    def _validate_params(self, body: dict) -> dict:
        """Parameter extraction + validation (ref shard/openai_api.py:206-294,
        same bounds)."""
        p = {}
        p["stream"] = bool(body.get("stream", False))
        p["max_tokens"] = body.get("max_tokens", 100)
        if not isinstance(p["max_tokens"], int) or p["max_tokens"] < 0:
            raise ValueError("max_tokens must be a non-negative integer")
        p["temperature"] = body.get("temperature", 0.0)
        if not isinstance(p["temperature"], (int, float)) or p["temperature"] < 0:
            raise ValueError("temperature must be a non-negative float")
        p["top_p"] = body.get("top_p", 1.0)
        if not isinstance(p["top_p"], (int, float)) or not 0 < p["top_p"] <= 1:
            raise ValueError("top_p must be in (0, 1]")
        rp = body.get("repetition_penalty")
        if rp is not None and (not isinstance(rp, (int, float)) or rp <= 0):
            raise ValueError("repetition_penalty must be a positive float")
        p["repetition_penalty"] = rp
        rcs = body.get("repetition_context_size", 20)
        if not isinstance(rcs, int) or rcs < 1:
            raise ValueError("repetition_context_size must be a positive integer")
        p["repetition_context_size"] = rcs
        logprobs = body.get("logprobs", -1)
        if logprobs != -1 and not (0 < logprobs <= 10):
            raise ValueError("logprobs must be between 1 and 10")
        p["logprobs"] = logprobs
        bias = body.get("logit_bias")
        if bias is not None:
            if not isinstance(bias, dict):
                raise ValueError("logit_bias must be a token_id -> bias map")
            try:
                bias = {int(k): float(v) for k, v in bias.items()}
            except (ValueError, TypeError):
                raise ValueError("logit_bias keys must be token ids")
            # one cap for every serving path (solo / scheduler slots /
            # multi-host control plane all size their buffers to 512) so a
            # request never succeeds on one deployment and 500s on another;
            # OpenAI's documented cap is 300
            if len(bias) > 512:
                raise ValueError("logit_bias supports at most 512 entries")
        p["logit_bias"] = bias
        stop = body.get("stop", [])
        if isinstance(stop, str):
            stop = [stop]
        if not isinstance(stop, list) or not all(isinstance(s, str) for s in stop):
            raise ValueError("stop must be a string or list of strings")
        p["stop_words"] = stop
        p["seed"] = body.get("seed")
        # per-request deadline overrides; None falls back to the server-wide
        # --request-timeout / --ttft-timeout defaults
        for key in ("request_timeout", "ttft_timeout"):
            v = body.get(key)
            if v is not None and (
                isinstance(v, bool) or not isinstance(v, (int, float)) or v <= 0
            ):
                raise ValueError(f"{key} must be a positive number of seconds")
            p[key] = v
        return p

    # ------------------------------------------------------------- prompts
    def _chat_prompt(self, body: dict, tokenizer) -> list[int]:
        messages = body.get("messages")
        if not isinstance(messages, list) or not messages:
            raise ValueError("messages must be a non-empty list")
        if getattr(tokenizer, "chat_template", None):
            return tokenizer.apply_chat_template(
                messages, tokenize=True, add_generation_prompt=True
            )
        return tokenizer.encode(convert_chat(messages, body.get("role_mapping")))

    # ----------------------------------------------------------- responses
    @staticmethod
    def _response_id() -> str:
        return f"cmpl-{uuid.uuid4().hex[:24]}"

    def _make_response(
        self, *, rid, object_type, model, text=None, delta=None,
        finish_reason=None, usage=None, logprobs=None,
    ) -> dict:
        # OpenAI schema builder (ref generate_response shard/openai_api.py:296-355)
        choice = {"index": 0, "finish_reason": finish_reason, "logprobs": logprobs}
        if object_type.startswith("chat"):
            if delta is not None:
                choice["delta"] = delta
            else:
                choice["message"] = {"role": "assistant", "content": text}
        else:
            choice["text"] = text if text is not None else ""
        resp = {
            "id": rid,
            "object": object_type,
            "created": int(time.time()),
            "model": model,
            "system_fingerprint": f"fp_{uuid.uuid4().hex[:10]}",
            "choices": [choice],
        }
        if usage:
            resp["usage"] = usage
        return resp

    # ----------------------------------------------------------- execution
    def _run(self, body, params, generator, tokenizer, prompt_ids, chat: bool):
        rid = self._response_id()
        # the trace key the operator curls /admin/trace/<id> with — echoed
        # on EVERY response (traced or not) so clients can always correlate
        self._resp_headers["X-MST-Request-Id"] = rid
        model_name = body.get("model", "default_model")
        stop_id_sequences = [_encode_plain(tokenizer, s) for s in params["stop_words"]]
        eos = getattr(tokenizer, "eos_token_id", None)
        obj = "chat.completion" if chat else "text_completion"

        gen_kwargs = dict(
            temperature=params["temperature"],
            top_p=params["top_p"],
            repetition_penalty=params["repetition_penalty"],
            repetition_context_size=params["repetition_context_size"],
            logit_bias=params["logit_bias"],
            seed=params["seed"],
            max_tokens=params["max_tokens"],
        )
        if not params["stream"] and params["logprobs"] > 0:
            # streaming discards logprobs (ref shard/openai_api.py:454-455),
            # so only the non-streaming path asks the engine to compute them
            gen_kwargs["want_logprobs"] = True

        # Brownout: under sustained overload the ladder trades per-request
        # cost for admission — cap max_tokens before shedding anything. The
        # applied level is surfaced in a response header so load generators
        # and clients can observe degradation without parsing /health.
        fleet = getattr(self.provider, "fleet", None)
        if isinstance(fleet, tuple):
            # disagg: the decode pool's ladder governs generation caps
            # (max_tokens is decode-side cost; prefill overload sheds at
            # that pool's own admission instead)
            fleet = fleet[-1]
        bro = getattr(fleet, "brownout", None) if fleet is not None else None
        if bro is not None:
            bstate = bro.state()
            level = bstate.get("level", 0)
            if level > 0:
                self._resp_headers["X-MST-Brownout-Level"] = level
                cap = bstate.get("max_tokens_cap")
                if cap is not None and gen_kwargs["max_tokens"] > cap:
                    gen_kwargs["max_tokens"] = cap
                    self._resp_headers["X-MST-Max-Tokens-Capped"] = cap

        # Session stickiness: an explicit session_id (or OpenAI's `user`
        # field) lets the fleet router keep a conversation on the replica
        # that holds its prefix cache.
        sess = body.get("session_id") or body.get("user")
        if (
            isinstance(sess, str) and sess
            and getattr(generator, "supports_sessions", False)
        ):
            gen_kwargs["_session"] = sess

        # Deadlines: per-request override beats the server-wide flag. A
        # scheduler-backed generator enforces them itself (bounded out-queue
        # waits that survive a wedged engine); anything else gets a coarse
        # between-tokens check in _generate — it can't interrupt a stuck
        # step, but it bounds total generation.
        req_to = params.get("request_timeout")
        if req_to is None:
            req_to = self.request_timeout
        ttft_to = params.get("ttft_timeout")
        if ttft_to is None:
            ttft_to = self.ttft_timeout
        soft_timeout = None
        if getattr(generator, "supports_deadlines", False):
            if req_to is not None:
                gen_kwargs["request_timeout"] = req_to
            if ttft_to is not None:
                gen_kwargs["ttft_timeout"] = ttft_to
        else:
            soft_timeout = req_to

        # a concurrency-safe generator (ContinuousBatcher) interleaves
        # requests itself; everything else is serialized by the lock, which
        # is the reference's single-request behavior (shard/openai_api.py:543-563)
        import contextlib

        lock = (
            contextlib.nullcontext()
            if getattr(generator, "concurrent", False)
            else self.gen_lock
        )
        # request-lifecycle tracing: begin a timeline under the client-
        # visible request id and hand it down the stack — the scheduler,
        # disagg coordinator, replica router and KV paths all stamp spans
        # onto it. The server owns the handle, so it (not the scheduler)
        # retires it into the flight-recorder ring when the response ends.
        trace = (
            tracing.begin(rid)
            if getattr(generator, "supports_trace", False) else None
        )
        if trace is not None:
            gen_kwargs["_trace"] = trace
        try:
            with lock:
                if params["stream"]:
                    self._stream(
                        rid, obj + ".chunk", model_name, generator, tokenizer,
                        prompt_ids, stop_id_sequences, eos, chat, gen_kwargs,
                        soft_timeout, trace=trace,
                    )
                else:
                    self._complete(
                        rid, obj, model_name, generator, tokenizer, prompt_ids,
                        stop_id_sequences, eos, chat, params["logprobs"],
                        gen_kwargs, soft_timeout, trace=trace,
                    )
        finally:
            tracing.finish(trace)

    def _complete(
        self, rid, obj, model_name, generator, tokenizer, prompt_ids,
        stop_id_sequences, eos, chat, want_logprobs, gen_kwargs,
        soft_timeout=None, trace=None,
    ):
        # non-streaming path (ref handle_completion shard/openai_api.py:357-434)
        tokens: list[int] = []
        token_logprobs: list[float] = []
        top_logprobs: list[dict] = []
        finish_reason = "length"
        t_start = time.perf_counter()
        t_first = None
        it = self._generate(generator, prompt_ids, gen_kwargs, soft_timeout)
        try:
            for token, logprobs in it:
                if t_first is None:
                    t_first = time.perf_counter()
                if eos is not None and token == eos:
                    finish_reason = "stop"
                    break
                tokens.append(token)
                if want_logprobs > 0:
                    if isinstance(logprobs, TokenLogprobs):
                        # computed on device in the decode block (lax.top_k);
                        # nothing vocab-sized ever reaches the host
                        token_logprobs.append(logprobs.chosen)
                        top_logprobs.append(
                            {
                                int(i): float(v)
                                for i, v in zip(
                                    logprobs.top_indices[:want_logprobs],
                                    logprobs.top_values[:want_logprobs],
                                )
                            }
                        )
                    else:  # engines still yielding the full (B, V) row
                        row = np.asarray(logprobs[0])
                        token_logprobs.append(float(row[token]))
                        top_idx = np.argsort(row)[::-1][:want_logprobs]
                        top_logprobs.append({int(i): float(row[i]) for i in top_idx})
                stop = stopping_criteria(tokens, stop_id_sequences, None)
                if stop.stop_met:
                    if stop.trim_length:
                        tokens = tokens[: -stop.trim_length]
                        if want_logprobs > 0:
                            token_logprobs = token_logprobs[: -stop.trim_length]
                            top_logprobs = top_logprobs[: -stop.trim_length]
                    finish_reason = "stop"
                    break
        finally:
            # deterministic cancellation (stop-word / eos early exit, or an
            # exception): closing the generator flips the scheduler
            # request's cancelled flag NOW, not at some later GC, so the
            # slot and its KV pages are reclaimed within a tick
            it.close()
        self._record(len(prompt_ids), len(tokens), t_start, t_first)
        text = tokenizer.decode(tokens)
        logprobs_payload = None
        if want_logprobs > 0:
            logprobs_payload = {
                "token_logprobs": token_logprobs,
                "top_logprobs": top_logprobs,
                "tokens": tokens,
            }
        usage = {
            "prompt_tokens": len(prompt_ids),
            "completion_tokens": len(tokens),
            "total_tokens": len(prompt_ids) + len(tokens),
        }
        self._json(
            200,
            self._make_response(
                rid=rid, object_type=obj, model=model_name, text=text,
                finish_reason=finish_reason, usage=usage, logprobs=logprobs_payload,
            ),
        )

    def _stream(
        self, rid, obj, model_name, generator, tokenizer, prompt_ids,
        stop_id_sequences, eos, chat, gen_kwargs, soft_timeout=None,
        trace=None,
    ):
        # SSE with partial-stop-word buffering (ref handle_stream
        # shard/openai_api.py:436-505): if the current token tail could still
        # grow into a stop sequence, hold the text back.
        t_start = time.perf_counter()
        it = self._generate(generator, prompt_ids, gen_kwargs, soft_timeout)
        # Prime the FIRST token before committing to a 200/SSE response:
        # instant failures — queue full (429), TTFT timeout (504), bad
        # request discovered at admission (400), every replica down (503) —
        # surface as proper status codes instead of a broken event stream.
        try:
            head = next(it)
        except StopIteration:
            head = None
        except BaseException:
            it.close()
            raise
        t_first = time.perf_counter() if head is not None else None

        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        # SSE has no Content-Length; end-of-stream is signalled by closing
        # the connection after [DONE].
        self.send_header("Connection", "close")
        for k, v in (getattr(self, "_resp_headers", None) or {}).items():
            self.send_header(k, str(v))
        self._cors()
        self.end_headers()

        def emit(payload: dict):
            with tracing.bind(trace):
                inject("server.sse_write")  # fault harness: kill a live
                # stream (record_fault stamps the bound timeline first)
            buf = f"data: {json.dumps(payload)}\n\n".encode()
            if trace is not None:
                t0 = time.perf_counter()
                self.wfile.write(buf)
                self.wfile.flush()
                trace.add("sse_write", t0, time.perf_counter(),
                          bytes=len(buf))
            else:
                self.wfile.write(buf)
                self.wfile.flush()

        if chat:
            emit(
                self._make_response(
                    rid=rid, object_type=obj, model=model_name,
                    delta={"role": "assistant", "content": ""},
                )
            )

        def token_stream():
            if head is not None:
                yield head
            yield from it

        detok = StreamingDetokenizer(tokenizer)
        tokens: list[int] = []
        in_flight: list[int] = []  # tokens withheld due to stop-prefix overlap
        finish_reason = "length"
        timed_out: Optional[RequestTimeoutError] = None
        try:
            for token, _ in token_stream():
                if eos is not None and token == eos:
                    finish_reason = "stop"
                    break
                tokens.append(token)
                stop = stopping_criteria(tokens, stop_id_sequences, None)
                if stop.stop_met:
                    finish_reason = "stop"
                    in_flight.clear()
                    break
                if any(sequence_overlap(tokens, s) for s in stop_id_sequences):
                    in_flight.append(token)
                    continue
                for t in in_flight:
                    detok.add_token(t)
                in_flight.clear()
                detok.add_token(token)
                if detok.last_segment:
                    delta = {"content": detok.last_segment}
                    emit(
                        self._make_response(
                            rid=rid, object_type=obj, model=model_name,
                            **({"delta": delta} if chat else {"text": detok.last_segment}),
                        )
                    )
        except RequestTimeoutError as e:
            # headers are gone — close the stream with a final error event
            # instead of a raw connection drop
            timed_out = e
            in_flight.clear()
        finally:
            # deterministic cancellation: whatever path leaves this loop
            # (stop word, eos, timeout, BrokenPipeError from a vanished
            # client), the scheduler request's cancelled flag flips NOW and
            # its slot/KV pages are reclaimed within a tick
            it.close()
        self._record(len(prompt_ids), len(tokens), t_start, t_first)
        if timed_out is not None:
            emit({"error": {"message": str(timed_out), "type": "timeout_error",
                            "code": 504}})
            self.wfile.write(b"data: [DONE]\n\n")
            self.wfile.flush()
            self.close_connection = True
            return
        # a length-finished run that was still buffering emits the buffered
        # tokens — they never completed a stop sequence
        for t in in_flight:
            detok.add_token(t)
        detok.finalize()
        if detok.last_segment:
            emit(
                self._make_response(
                    rid=rid, object_type=obj, model=model_name,
                    **(
                        {"delta": {"content": detok.last_segment}}
                        if chat
                        else {"text": detok.last_segment}
                    ),
                )
            )
        emit(
            self._make_response(
                rid=rid, object_type=obj, model=model_name,
                **({"delta": {}} if chat else {"text": ""}),
                finish_reason=finish_reason,
            )
        )
        self.wfile.write(b"data: [DONE]\n\n")
        self.wfile.flush()
        self.close_connection = True

    # -------------------------------------------------------- observability
    def _generate(self, generator, prompt_ids, gen_kwargs, soft_timeout=None):
        """Generation wrapped in a JAX profiler trace when --profile-dir is
        set (SURVEY §5: the profiling layer the reference lacks).

        ``soft_timeout`` is the fallback total-generation bound for engines
        without scheduler-side deadline support: checked between tokens, so
        it bounds a long generation but cannot interrupt a wedged step."""
        with profile_trace(self.profile_dir):
            it = generator.generate_step(prompt_ids, **gen_kwargs)
            if soft_timeout is None:
                yield from it
                return
            t0 = time.monotonic()
            try:
                for item in it:
                    yield item
                    if time.monotonic() - t0 > soft_timeout:
                        raise RequestTimeoutError(
                            "total", time.monotonic() - t0, soft_timeout
                        )
            finally:
                it.close()

    def _record(self, n_prompt, n_gen, t_start, t_first):
        end = time.perf_counter()
        ttft = (t_first - t_start) if t_first else 0.0
        decode_time = (end - t_first) if t_first else 0.0
        self.metrics.record_request(
            prompt_tokens=n_prompt,
            generation_tokens=n_gen,
            ttft_s=ttft,
            decode_tps=(max(n_gen - 1, 0) / decode_time) if decode_time > 0 else 0.0,
        )

    # ------------------------------------------------------------ handlers
    def _handle_chat_completion(self, body, params, generator, tokenizer):
        prompt_ids = self._chat_prompt(body, tokenizer)
        self._run(body, params, generator, tokenizer, list(prompt_ids), chat=True)

    def _handle_text_completion(self, body, params, generator, tokenizer):
        prompt = body.get("prompt")
        if not isinstance(prompt, str) or not prompt:
            return self._error(400, "prompt must be a non-empty string")
        prompt_ids = tokenizer.encode(prompt)
        self._run(body, params, generator, tokenizer, list(prompt_ids), chat=False)


def make_server(
    provider: ModelProvider,
    host: str = "127.0.0.1",
    port: int = 8080,
    profile_dir: Optional[str] = None,
    api_key: Optional[str] = None,
    request_timeout: Optional[float] = None,
    ttft_timeout: Optional[float] = None,
):
    handler = type(
        "BoundAPIHandler",
        (APIHandler,),
        {
            "provider": provider,
            "gen_lock": make_lock("APIHandler.gen_lock"),
            "metrics": ServingMetrics(
                batcher_fn=lambda: provider.generator
                if getattr(provider.generator, "concurrent", False)
                else None,
                spec_fn=lambda: provider.generator
                if hasattr(provider.generator, "accepted_tokens")
                else None,
                weight_store_fn=weight_store,
                prefix_store_fn=lambda: getattr(
                    provider, "prefix_store_obj", None
                ),
                pod_stats_fn=lambda: (
                    provider.pod_fleet.pod_stats()
                    if getattr(provider, "pod_fleet", None) is not None
                    else None
                ),
                kv_share_fn=lambda: (
                    provider.kv_share_stats()
                    if getattr(provider, "kv_share_map", None) is not None
                    else None
                ),
                kv_compress_fn=lambda: provider.kv_compress_stats(),
            ),
            "profile_dir": profile_dir,
            "api_key": api_key,
            "request_timeout": request_timeout,
            "ttft_timeout": ttft_timeout,
        },
    )
    return ThreadingHTTPServer((host, port), handler)


def main(argv=None):
    import argparse

    parser = argparse.ArgumentParser(description="OpenAI-compatible API server")
    parser.add_argument("--model", default=None, help="default model path/repo")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8080)
    parser.add_argument("--start-layer", type=int, default=None)
    parser.add_argument("--end-layer", type=int, default=None)
    parser.add_argument("--num-stages", type=int, default=None,
                        help="pipeline stages on the local mesh (fused SPMD engine)")
    parser.add_argument("--stage-bounds", default=None,
                        help="pipeline stage bounds, e.g. '0-14,14-27' "
                        "(uneven splits and MoE/dense mixes allowed)")
    parser.add_argument("--engine", choices=("fused", "chained"), default="fused",
                        help="pipeline engine for --stage-bounds: fused SPMD "
                        "(one program per token, default) or chained per-stage "
                        "programs")
    parser.add_argument("--tp", type=int, default=1,
                        help="tensor-parallel width within each pipeline "
                        "stage")
    parser.add_argument("--keep-quantized", action="store_true",
                        help="keep 4-bit checkpoint weights packed in HBM "
                        "(fused dequant-matmul) instead of dequantizing on "
                        "load — 4x decode weight bandwidth")
    parser.add_argument("--ep", type=int, default=1,
                        help="expert-parallel width within each pipeline "
                        "stage (MoE models)")
    parser.add_argument("--concurrent", type=int, default=1,
                        help="continuous-batching slots: serve up to N "
                        "requests interleaved in one fused engine (N>1 "
                        "replaces the per-request generation lock)")
    parser.add_argument("--paged-pool", type=int, default=None,
                        help="with --concurrent: share a KV pool of N pages "
                             "across slots (reservation admission) instead "
                             "of dense per-slot max-seq allocations")
    parser.add_argument("--page-size", type=int, default=None,
                        help="KV page size in tokens (default: the prefill "
                             "chunk); must be a chunk multiple")
    parser.add_argument("--paged-attention",
                        choices=("auto", "ragged", "gather"), default="auto",
                        help="with --paged-pool: decode-attention path over "
                             "the page pool. 'ragged' attends in place via "
                             "the slot page tables (no per-tick gather/"
                             "scatter of the cache), 'gather' keeps the "
                             "contiguous per-slot view, 'auto' (default) "
                             "picks ragged where the engine supports it "
                             "(pp=1, tp=ep=1)")
    parser.add_argument("--kv-dtype", choices=("bf16", "int8"), default=None,
                        help="with --paged-pool: KV-pool storage. 'int8' "
                             "stores quantized codes plus a per-row-per-head "
                             "float32 scale (~2x the tokens per page of "
                             "bf16); default keeps the cache dtype")
    parser.add_argument("--kv-share-map", default=None, metavar="PATH",
                        help="with --paged-pool: layer-wise KV sharing "
                             "(KVSharer) — path to a calibrated share-map "
                             "artifact from cli/kv_share_calibrate.py. "
                             "Pools allocate one physical (k,v) buffer per "
                             "share GROUP (~25-50%% fewer KV bytes at the "
                             "calibrated sharing ratio); exported blocks "
                             "carry the map's hash so mismatched layouts "
                             "fail closed at import. Composes with "
                             "--kv-dtype int8, --spill-bytes and "
                             "--prefix-store")
    parser.add_argument("--kv-compress-map", default=None, metavar="PATH",
                        help="with --paged-pool: compressed-latent KV "
                             "transport (kv_compress.py) — path to a "
                             "calibrated low-rank artifact from "
                             "cli/kv_compress_calibrate.py. Exported KV "
                             "page blocks (spill, prefix demotion, disagg "
                             "handoff, pod federation) ship rank-r latent "
                             "coefficients instead of full per-head pages; "
                             "bounded-error, opt-in. MLA-native models "
                             "(DeepSeek-v2 compressed cache mode) compress "
                             "exactly WITHOUT this flag. Requires "
                             "float/bf16 pools (not --kv-dtype int8)")
    parser.add_argument("--kv-compress-rank", type=int, default=None,
                        metavar="R",
                        help="with --kv-compress-map: truncate the "
                             "artifact's nested SVD basis to rank R (a "
                             "cheaper operating point than the calibrated "
                             "rank; more reconstruction error)")
    parser.add_argument("--admission-policy", choices=("fifo", "first_fit"),
                        default="fifo",
                        help="waiting-line policy when a request doesn't fit "
                             "the page pool: strict order vs let smaller "
                             "requests jump a blocked head")
    parser.add_argument("--overcommit", action="store_true",
                        help="with --paged-pool: admit on current page need "
                             "(prompt + one decode block) and grow per "
                             "block, preempting the newest-admitted request "
                             "on pool exhaustion (token-exact resume) — "
                             "higher slot occupancy than reserving every "
                             "request's full prompt+max_tokens need")
    parser.add_argument("--spill-bytes", type=int, default=None,
                        help="with --overcommit or --spill-cold-after: "
                             "host-DRAM budget (bytes) for spilled KV page "
                             "blocks. Preemption/cold-spill exports the "
                             "victim's pages to host memory and resume "
                             "re-imports them — one page scatter instead of "
                             "a full re-prefill; LRU-evicted past the "
                             "budget, falling back to re-prefill")
    parser.add_argument("--spill-cold-after", type=int, default=None,
                        help="with --spill-bytes: proactively spill a "
                             "decode slot whose consumer stopped pulling "
                             "tokens for N scheduler ticks (idle streaming "
                             "session) — its pool pages free up for "
                             "admission and the session resumes "
                             "token-exactly when the consumer catches up")
    parser.add_argument("--kv-prefetch", choices=["on", "off", "auto"],
                        default="auto",
                        help="stage spilled KV blocks host→device BEFORE "
                             "the resume tick (overlapped with decode "
                             "compute), demoting demand import to a counted "
                             "fallback; auto = on whenever --spill-bytes is "
                             "set (default)")
    parser.add_argument("--draft-model", default=None,
                        help="speculative decoding: a small draft model "
                             "proposes --spec-k tokens per round (greedy "
                             "token-exact, sampled distribution-exact). "
                             "Single-chip generator path only.")
    parser.add_argument("--spec-k", type=int, default=4,
                        help="speculation window (with --draft-model)")
    parser.add_argument("--draft", choices=("auto", "off", "ngram", "engine"),
                        default="auto",
                        help="speculative proposal source. 'ngram' drafts "
                             "by prompt-lookup against the stream's own "
                             "prompt+history — no second checkpoint, no "
                             "draft KV, free to enable on every decode "
                             "host; 'engine' uses --draft-model; 'auto' "
                             "(default) keeps the legacy contract: engine "
                             "iff --draft-model, else off")
    parser.add_argument("--spec-window-max", type=int, default=None,
                        help="per-slot ADAPTIVE speculation windows, "
                             "resized each round on an acceptance EWMA "
                             "over the ladder {0,2,4,8} capped here "
                             "(losing slots disable and re-probe). Always "
                             "on for --draft ngram (default cap 8); opt-in "
                             "for --draft engine (without it the engine "
                             "path keeps fixed --spec-k rounds)")
    parser.add_argument("--replicas", type=int, default=1,
                        help="data-parallel serving: N independent engine "
                             "replicas, each on its own devices (stages x tp "
                             "x ep each), least-loaded request routing — "
                             "aggregate throughput scales with N")
    parser.add_argument("--disagg", action="store_true",
                        help="disaggregated prefill/decode serving: split "
                             "the fleet into a prefill pool and a decode "
                             "pool. Each request prefills (and emits its "
                             "first token) on a prefill replica, then its "
                             "KV page block is handed to the least-loaded "
                             "decode replica, which owns the rest of the "
                             "stream — long prefills stop stalling decode "
                             "steady-state. Requires --concurrent; "
                             "--paged-pool makes the handoff a block "
                             "import instead of a re-prefill; handoff "
                             "failures degrade to serve-in-place (never a "
                             "dropped stream)")
    parser.add_argument("--shared-weights", choices=("on", "off", "auto"),
                        default="auto",
                        help="cross-replica shared weights: place ONE "
                             "resident packed param tree per host and have "
                             "every replica (and both disagg pools) alias "
                             "it — fleet weight bytes ~W instead of N*W, "
                             "and an autoscaler spawn costs slot/cache "
                             "setup instead of a checkpoint re-upload. "
                             "Replicas co-locate on one model-parallel "
                             "slice (capacity is then bounded by KV "
                             "memory, not weight copies). auto: on when "
                             "--replicas > 1 or --disagg on a single-host "
                             "fused-engine config; off: always private "
                             "per-replica copies")
    parser.add_argument("--prefill-replicas", type=int, default=1,
                        help="with --disagg: replicas in the prefill pool")
    parser.add_argument("--decode-replicas", type=int, default=1,
                        help="with --disagg: replicas in the decode pool")
    parser.add_argument("--autoscale", action="store_true",
                        help="with --replicas: run the elastic fleet "
                             "controller — spawn extra replicas onto unused "
                             "device slices under sustained queue pressure, "
                             "drain idle ones back down; spawn/drain "
                             "failures degrade to the static fleet (never a "
                             "dropped stream). Control at runtime via POST "
                             "/admin/autoscaler")
    parser.add_argument("--autoscale-min", type=int, default=None,
                        help="autoscaler floor: never drain below this many "
                             "replicas (default: 1)")
    parser.add_argument("--autoscale-max", type=int, default=None,
                        help="autoscaler ceiling (default: every replica the "
                             "device count can hold)")
    parser.add_argument("--autoscale-interval", type=float, default=2.0,
                        help="seconds between autoscaler control ticks")
    parser.add_argument("--autoscale-cooldown", type=float, default=15.0,
                        help="seconds after any scale event (or failed "
                             "attempt) before the next one")
    parser.add_argument("--brownout", choices=("on", "off"), default="on",
                        help="overload brownout ladder: under sustained "
                             "pressure cap max_tokens, shed speculation "
                             "(per-slot lowest-acceptance-first under "
                             "adaptive windows, globally in fixed-K engine "
                             "mode) and tighten admission BEFORE shedding "
                             "with 429; "
                             "level surfaced in /health and the "
                             "X-MST-Brownout-Level response header")
    parser.add_argument("--prompt-cache", action="store_true",
                        help="reuse KV for shared prompt prefixes (chat turns "
                             "re-send their whole history: TTFT becomes "
                             "O(new tokens)). Single-chip generator path, or "
                             "with --concurrent --paged-pool: content-"
                             "addressed page sharing across interleaved "
                             "requests (composes with --coordinator — the "
                             "worker mirrors rebuild the same index from the "
                             "op stream — and with --replicas, one cache per "
                             "replica)")
    parser.add_argument("--prefix-store", action="store_true",
                        help="fleet-wide content-addressed prefix KV store "
                             "(with --concurrent --paged-pool): completed "
                             "prefills register their page-aligned prompt "
                             "prefix under chained chunk digests; later "
                             "requests sharing the prefix lease the pages "
                             "copy-on-write (zero-copy within a replica) or "
                             "import them from the host tier (across "
                             "replicas / after demotion) and prefill only "
                             "the uncovered tail. Subsumes --prompt-cache "
                             "(the two are mutually exclusive); with "
                             "--disagg a full-prefix hit skips the prefill "
                             "pool entirely")
    parser.add_argument("--prefix-store-bytes", type=int, default=None,
                        help="with --prefix-store: host-DRAM budget (bytes) "
                             "for the demoted-prefix tier (default 256 MiB); "
                             "LRU-evicted past the budget, falling back to "
                             "plain prefill")
    parser.add_argument("--prefix-insert-min-hits", type=int, default=1,
                        help="with --prefix-store: a prefix must MISS this "
                             "many times before a completed prefill inserts "
                             "it (damps one-shot prompts; default 1)")
    parser.add_argument("--decode-block", type=int, default=16,
                        help="decode steps fused per program launch (token "
                             "pulls amortize over this many tokens; set 1 "
                             "for strict per-token streaming on a local chip)")
    parser.add_argument("--async-sched", choices=("on", "off", "auto"),
                        default="auto",
                        help="with --concurrent: async tick pipelining — "
                             "dispatch decode block t+1 before harvesting "
                             "block t, overlapping host-side emit/stop/"
                             "admission work with device compute (token "
                             "streams stay bit-identical to sync). 'auto' "
                             "(default) enables it for plain decode AND "
                             "--draft ngram (host-built drafts chain pure "
                             "device-side) and falls back to sync with "
                             "--draft-model or multi-host — the resolution "
                             "reason is logged at startup; 'off' forces "
                             "the sequential tick")
    parser.add_argument("--max-seq", type=int, default=4096)
    parser.add_argument("--prefill-chunk", type=int, default=256)
    parser.add_argument("--request-timeout", type=float, default=None,
                        help="total-generation deadline in seconds (submit "
                             "to last token); expiry cancels the request, "
                             "frees its slot/KV pages and returns HTTP 504 "
                             "(or a final SSE error event). Per-request "
                             "'request_timeout' in the body overrides it")
    parser.add_argument("--ttft-timeout", type=float, default=None,
                        help="time-to-first-token deadline in seconds "
                             "(queue wait + prefill + compile); also the "
                             "default inter-token stall watchdog. Requests "
                             "still queued past it are shed before prefill. "
                             "Per-request 'ttft_timeout' overrides it")
    parser.add_argument("--max-queue", type=int, default=None,
                        help="with --concurrent: admission bound on queued "
                             "requests (per replica); a full queue rejects "
                             "with 429 + Retry-After instead of growing "
                             "without limit under overload")
    parser.add_argument("--api-key", default=None,
                        help="require 'Authorization: Bearer <key>' on the "
                             "/v1/* endpoints (the web UI's API key setting)")
    parser.add_argument("--log-level", default="INFO")
    parser.add_argument("--profile-dir", default=None,
                        help="write JAX profiler traces per request here")
    parser.add_argument("--trace", choices=("off", "sample", "on"),
                        default="off",
                        help="request-lifecycle tracing: record per-request "
                             "span timelines (queue wait, prefill, handoff, "
                             "decode ticks, spill/wake, SSE writes) into a "
                             "bounded flight-recorder ring, exported as "
                             "chrome://tracing JSON via GET /admin/trace/"
                             "{request_id} and /admin/trace/dump. 'sample' "
                             "traces every --trace-sample-th request; 'on' "
                             "traces all; 'off' (default) compiles to "
                             "None-check no-ops on the hot paths")
    parser.add_argument("--trace-buffer", type=int, default=256,
                        help="flight-recorder capacity: completed request "
                             "timelines kept in the ring (oldest evicted); "
                             "incident snapshots (breaker trip, wedge, "
                             "injected fault) preserve theirs separately")
    parser.add_argument("--trace-sample", type=int, default=8,
                        help="with --trace sample: trace every Nth request")
    parser.add_argument("--trace-profile", action="store_true",
                        help="with --trace: wrap traced decode blocks in "
                             "jax.profiler.TraceAnnotation so host spans "
                             "line up with the XLA timeline under "
                             "--profile-dir")
    parser.add_argument("--chat-template", default=None,
                        help="jinja chat template (inline, or @/path/to/file) "
                        "overriding the tokenizer's")
    # multi-host (DCN) bring-up — the jax.distributed control plane
    parser.add_argument("--coordinator", default=None,
                        help="host:port of jax.distributed coordinator")
    parser.add_argument("--process-id", type=int, default=None)
    parser.add_argument("--num-processes", type=int, default=None)
    parser.add_argument("--pod", action="store_true",
                        help="pod-scale serving: each process runs its own "
                             "host-local fleet on its local devices, "
                             "stitched by the pod gossip plane (weight "
                             "registry, cross-host disagg handoff, pod "
                             "autoscaler) instead of the SPMD mirror — "
                             "requires --coordinator and --num-processes")
    args = parser.parse_args(argv)

    if args.engine == "chained" and not args.stage_bounds:
        parser.error("--engine chained requires --stage-bounds")
    if args.concurrent > 1 and args.engine == "chained":
        parser.error("--concurrent requires the fused engine")
    if (args.tp > 1 or args.ep > 1) and args.engine == "chained":
        parser.error("--tp/--ep require the fused engine")
    if args.pod:
        if not (args.coordinator and (args.num_processes or 1) > 1):
            parser.error("--pod requires --coordinator and --num-processes "
                         "> 1 (the pod gossip plane rides "
                         "jax.distributed)")
        if not args.model:
            parser.error("--pod serving requires --model (every host loads "
                         "its fleet at startup)")
    if args.coordinator and (args.num_processes or 1) > 1 and not args.pod:
        if not args.model:
            parser.error("multi-host serving requires --model (workers load "
                         "the model at startup)")
        if not args.stage_bounds and (args.num_stages or 1) <= 1:
            parser.error("multi-host serving requires a pipeline "
                         "(--num-stages > 1 or --stage-bounds)")
    if args.trace_buffer < 1:
        parser.error("--trace-buffer must be >= 1")
    if args.trace_sample < 1:
        parser.error("--trace-sample must be >= 1")
    if args.trace_profile and args.trace == "off":
        parser.error("--trace-profile requires --trace sample|on")
    logging.basicConfig(level=args.log_level.upper())
    # before the provider builds any engine: batchers resolve the profile
    # bridge once at construction, so the tracer must exist first
    tracing.configure(args.trace, buffer=args.trace_buffer,
                      sample_n=args.trace_sample,
                      profile=args.trace_profile)
    if args.coordinator:
        import jax

        if os.environ.get("JAX_PLATFORMS", "") == "cpu":
            # CPU ranks (the multi-host tests, or a smoke deployment) need
            # an explicit cross-process collectives implementation on jax
            # versions where the CPU backend doesn't default to one
            try:
                jax.config.update(
                    "jax_cpu_collectives_implementation", "gloo"
                )
            except Exception:  # noqa: BLE001 — older/newer jax: best effort
                pass
        jax.distributed.initialize(
            args.coordinator, num_processes=args.num_processes,
            process_id=args.process_id,
        )
    stage_bounds = None
    if args.stage_bounds:
        stage_bounds = [
            tuple(int(x) for x in part.split("-"))
            for part in args.stage_bounds.split(",")
        ]
    chat_template = args.chat_template
    if chat_template and chat_template.startswith("@"):
        chat_template = Path(chat_template[1:]).read_text()
    if args.draft_model and (
        args.coordinator or args.tp > 1
        or args.ep > 1 or args.stage_bounds or (args.num_stages or 1) > 1
        or args.engine == "chained"
        or args.start_layer is not None or args.end_layer is not None
    ):
        parser.error("--draft-model applies to the single-chip full-model "
                     "generator or to --concurrent serving "
                     "(no --coordinator/--tp/--ep/stage or "
                     "layer-range flags)")
    if args.draft == "engine" and not args.draft_model:
        parser.error("--draft engine needs --draft-model")
    if args.draft_model and args.draft in ("off", "ngram"):
        parser.error(f"--draft {args.draft} conflicts with --draft-model: "
                     "drop one (--draft-model implies the engine proposer)")
    if args.draft == "ngram" and (
        (args.coordinator and (args.num_processes or 1) > 1
         and not args.pod)
        or args.tp > 1 or args.ep > 1 or args.stage_bounds
        or (args.num_stages or 1) > 1 or args.engine == "chained"
        or args.start_layer is not None or args.end_layer is not None
    ):
        parser.error("--draft ngram applies to the single-chip full-model "
                     "generator or to --concurrent serving (the verify "
                     "needs the pp=1 vectorized body; multi-host worker "
                     "mirrors replay plain decode ticks only — run it on "
                     "single-host replicas or --pod hosts instead)")
    if args.spec_window_max is not None:
        if args.spec_window_max < 2:
            parser.error("--spec-window-max must be >= 2")
        if args.draft == "off" or (
            args.draft == "auto" and not args.draft_model
        ):
            parser.error("--spec-window-max needs a speculating server: "
                         "--draft ngram or --draft-model")
    # ---- prompt-prefix reuse flags. --prefix-store (the fleet-wide
    # content-addressed store) SUBSUMES --prompt-cache (engine-local page
    # index): running both would put two owners over the same pool pages,
    # so the pair is rejected outright with a migration hint.
    if args.prefix_store:
        if args.prompt_cache:
            parser.error(
                "--prompt-cache is subsumed by --prefix-store: the fleet-"
                "wide store covers the slot-local prefix cache's reuse and "
                "adds cross-replica sharing and a host tier — drop "
                "--prompt-cache (see README: migrating from --prompt-cache)"
            )
        if args.concurrent <= 1 or not args.paged_pool:
            parser.error("--prefix-store requires --concurrent N (N > 1) "
                         "with --paged-pool (prefix reuse is page-granular)")
        if args.draft_model:
            parser.error("--prefix-store is incompatible with --draft-model "
                         "(the draft cache cannot alias shared prefix pages)")
        if args.coordinator and (args.num_processes or 1) > 1:
            parser.error("--prefix-store is single-host only: store "
                         "admissions rewrite page tables host-side, outside "
                         "the op stream worker ranks mirror")
    elif (args.prefix_store_bytes is not None
          or args.prefix_insert_min_hits != 1):
        parser.error("--prefix-store-bytes/--prefix-insert-min-hits require "
                     "--prefix-store")
    if args.prefix_store_bytes is not None and args.prefix_store_bytes < 1:
        parser.error("--prefix-store-bytes must be a positive byte count")
    if args.prefix_insert_min_hits < 1:
        parser.error("--prefix-insert-min-hits must be >= 1")
    if args.prompt_cache:
        # ONE home for every --prompt-cache rule (this used to be three
        # overlapping conditionals, each re-encoding part of the story —
        # the replicas check below no longer mentions --prompt-cache):
        # concurrent serving needs the paged pool; otherwise the flag
        # means the single-chip full-model generator path, nothing else.
        if args.concurrent > 1:
            if not args.paged_pool:
                parser.error("--prompt-cache with --concurrent requires "
                             "--paged-pool (prefix sharing is "
                             "page-granular)")
        elif (args.coordinator or args.tp > 1 or args.ep > 1
              or args.stage_bounds or (args.num_stages or 1) > 1
              or args.engine == "chained" or args.draft_model
              or args.replicas > 1 or args.disagg
              or args.start_layer is not None
              or args.end_layer is not None):
            parser.error("--prompt-cache applies to the single-chip "
                         "full-model generator path or to --concurrent "
                         "--paged-pool serving (no --coordinator/--tp/--ep/"
                         "stage, layer-range, --draft-model, or fleet "
                         "flags)")
    if args.replicas > 1 and (
        (args.coordinator and not args.pod) or args.engine == "chained"
        or (args.draft_model and args.concurrent <= 1)
        or (args.draft == "ngram" and args.concurrent <= 1)
        or args.start_layer is not None or args.end_layer is not None
    ):
        parser.error("--replicas requires the fused full-model engine path "
                     "(no --coordinator/--engine chained/layer-range flags "
                     "unless --pod; --draft-model/--draft ngram only with "
                     "--concurrent)")
    if args.paged_pool and args.concurrent <= 1:
        parser.error("--paged-pool requires --concurrent N (N > 1)")
    if args.paged_pool and args.engine == "chained":
        parser.error("--paged-pool requires the fused engine")
    if args.page_size and not args.paged_pool:
        parser.error("--page-size requires --paged-pool")
    if args.paged_attention != "auto" and not args.paged_pool:
        parser.error("--paged-attention requires --paged-pool")
    if args.kv_dtype and not args.paged_pool:
        parser.error("--kv-dtype requires --paged-pool")
    if args.kv_share_map:
        if not args.paged_pool:
            parser.error("--kv-share-map requires --paged-pool (sharing "
                         "deduplicates the paged KV pool's layer axis)")
        if args.stage_bounds or (args.num_stages or 1) > 1:
            parser.error("--kv-share-map requires a single-stage engine: "
                         "share groups span the full layer stack, which a "
                         "pipeline stage split cuts")
    if args.kv_compress_map:
        if not args.paged_pool:
            parser.error("--kv-compress-map requires --paged-pool "
                         "(compression rides the paged KV transport path)")
        if args.kv_dtype == "int8":
            parser.error("--kv-compress-map is incompatible with "
                         "--kv-dtype int8: dequantize->project->requantize "
                         "compounds quantization error past the artifact's "
                         "calibrated bound")
        if args.stage_bounds or (args.num_stages or 1) > 1:
            parser.error("--kv-compress-map requires a single-stage "
                         "engine: the calibration spans the full layer "
                         "stack, which a pipeline stage split cuts")
    if args.kv_compress_rank is not None and not args.kv_compress_map:
        parser.error("--kv-compress-rank requires --kv-compress-map")
    if args.admission_policy != "fifo" and not args.paged_pool:
        parser.error("--admission-policy requires --paged-pool")
    if args.overcommit and not args.paged_pool:
        parser.error("--overcommit requires --paged-pool")
    if (args.overcommit and args.coordinator
            and (args.num_processes or 1) > 1 and not args.pod):
        # the sampler-state stash is no longer the blocker (it travels in
        # KVPageBlock / ResumeState now); what remains is that preemption
        # and resume rewrite page tables and free lists host-side, outside
        # the op stream the worker ranks mirror — their page accounting
        # would silently diverge from rank 0's
        parser.error(
            "--overcommit is not supported in multi-host serving: "
            "preemption/resume rewrites page tables and free lists "
            "host-side, outside the op stream worker ranks mirror; run "
            "overcommit on single-host replicas (e.g. behind --replicas) "
            "instead"
        )
    if args.spill_bytes is not None:
        if args.spill_bytes < 1:
            parser.error("--spill-bytes must be a positive byte count")
        if not args.overcommit and args.spill_cold_after is None:
            parser.error("--spill-bytes requires --overcommit or "
                         "--spill-cold-after: the spill tier holds "
                         "preempted or cold-spilled requests' KV page "
                         "blocks")
        if args.draft_model:
            parser.error("--spill-bytes is incompatible with --draft-model "
                         "(speculative slots re-prefill on preemption)")
    if args.spill_cold_after is not None:
        if args.spill_cold_after < 1:
            parser.error("--spill-cold-after must be >= 1 (scheduler ticks)")
        if args.spill_bytes is None:
            parser.error("--spill-cold-after needs a spill tier to spill "
                         "into: set --spill-bytes")
        if args.concurrent <= 1:
            parser.error("--spill-cold-after requires --concurrent N "
                         "(N > 1): cold-slot residency is a continuous-"
                         "batching policy")
    if args.kv_prefetch == "on" and args.spill_bytes is None:
        parser.error("--kv-prefetch on needs a spill tier to prefetch "
                     "from: set --spill-bytes")
    if args.disagg:
        if args.concurrent <= 1:
            parser.error("--disagg requires --concurrent N (N > 1): only "
                         "the continuous batcher can park a prefill-only "
                         "request and resume it from a KV page block")
        if args.replicas > 1:
            parser.error("--disagg replaces --replicas: size the pools "
                         "with --prefill-replicas/--decode-replicas")
        if (args.coordinator and not args.pod) or args.engine == "chained":
            parser.error("--disagg requires the single-host fused engine "
                         "path (no --coordinator/--engine chained) — or "
                         "--pod, where each host runs its own disagg pools")
        if args.draft_model:
            parser.error("--disagg is incompatible with --draft-model: a "
                         "resumed stream's draft KV cannot be rebuilt from "
                         "the handed-off block (only the target's pages "
                         "travel). Use --draft ngram — prompt-lookup "
                         "drafts need no draft KV, so decode replicas "
                         "speculate on resumed streams too")
        if args.prefill_replicas < 1 or args.decode_replicas < 1:
            parser.error("--prefill-replicas/--decode-replicas must be "
                         "positive integers")
        if args.autoscale and (args.autoscale_min is not None
                               or args.autoscale_max is not None):
            parser.error("--autoscale-min/--autoscale-max do not apply to "
                         "--disagg: each pool's floor is its initial size "
                         "and its ceiling is the free device slices")
    elif args.prefill_replicas != 1 or args.decode_replicas != 1:
        parser.error("--prefill-replicas/--decode-replicas require "
                     "--disagg")
    if args.autoscale and args.replicas <= 1 and not args.disagg:
        parser.error("--autoscale requires --replicas N (N > 1) or "
                     "--disagg: only a ReplicaSet fleet can grow or shrink")
    if not args.autoscale and (
        args.autoscale_min is not None or args.autoscale_max is not None
    ):
        parser.error("--autoscale-min/--autoscale-max require --autoscale")
    if args.autoscale_min is not None and args.autoscale_min < 1:
        parser.error("--autoscale-min must be a positive integer")
    if (
        args.autoscale_min is not None and args.autoscale_max is not None
        and args.autoscale_max < args.autoscale_min
    ):
        parser.error("--autoscale-max must be >= --autoscale-min")
    if args.autoscale_interval <= 0 or args.autoscale_cooldown < 0:
        parser.error("--autoscale-interval must be > 0 and "
                     "--autoscale-cooldown >= 0")
    if args.shared_weights == "on":
        if (args.coordinator or (args.num_processes or 1) > 1) \
                and not args.pod:
            parser.error("--shared-weights on is single-host only: worker "
                         "ranks hold their own device grids, there is no "
                         "one resident tree for them to alias (--pod hosts "
                         "each alias their own local tree)")
        if args.engine == "chained":
            parser.error("--shared-weights on requires the fused engine "
                         "path (chained stage processes each own their "
                         "stage's weights)")
        if args.replicas <= 1 and not args.disagg:
            parser.error("--shared-weights on requires --replicas N "
                         "(N > 1) or --disagg: with one engine there is "
                         "nothing to alias")
    if args.max_queue is not None:
        if args.max_queue < 1:
            parser.error("--max-queue must be a positive integer")
        if args.concurrent <= 1:
            parser.error("--max-queue requires --concurrent N (N > 1): only "
                         "the continuous batcher has a submit queue to bound")
    if args.async_sched != "auto" and args.concurrent <= 1:
        parser.error("--async-sched requires --concurrent N (N > 1): only "
                     "the continuous batcher has a tick loop to pipeline")
    if args.async_sched == "on" and args.draft_model:
        parser.error("--async-sched on is incompatible with --draft-model "
                     "(speculative rounds harvest per-round accept counts); "
                     "use 'auto'")
    if args.async_sched == "on" and args.coordinator and (
        args.num_processes or 1
    ) > 1 and not args.pod:
        parser.error("--async-sched on is not supported in multi-host "
                     "serving (worker mirrors replay the op stream per "
                     "broadcast tick); use 'auto'")
    for flag, val in (("--request-timeout", args.request_timeout),
                      ("--ttft-timeout", args.ttft_timeout)):
        if val is not None and val <= 0:
            parser.error(f"{flag} must be a positive number of seconds")
    multihost = (bool(args.coordinator) and (args.num_processes or 1) > 1
                 and not args.pod)
    provider = ModelProvider(
        args.model, start_layer=args.start_layer, end_layer=args.end_layer,
        num_stages=args.num_stages, stage_bounds=stage_bounds,
        engine=args.engine, concurrent=args.concurrent, multihost=multihost,
        tp=args.tp, ep=args.ep,
        max_seq=args.max_seq, prefill_chunk=args.prefill_chunk,
        chat_template=chat_template, keep_quantized=args.keep_quantized,
        decode_block=args.decode_block, paged_pool=args.paged_pool,
        page_size=args.page_size, paged_attention=args.paged_attention,
        kv_dtype=args.kv_dtype,
        kv_share_map=args.kv_share_map,
        kv_compress_map=args.kv_compress_map,
        kv_compress_rank=args.kv_compress_rank,
        admission_policy=args.admission_policy,
        overcommit=args.overcommit,
        spill_bytes=args.spill_bytes,
        spill_cold_after=args.spill_cold_after,
        kv_prefetch=args.kv_prefetch,
        draft_model=args.draft_model, spec_k=args.spec_k,
        draft=args.draft, spec_window_max=args.spec_window_max,
        prompt_cache=args.prompt_cache, replicas=args.replicas,
        prefix_store=args.prefix_store,
        prefix_store_bytes=args.prefix_store_bytes,
        prefix_insert_min_hits=args.prefix_insert_min_hits,
        max_queue=args.max_queue,
        async_sched=args.async_sched,
        autoscale=args.autoscale,
        autoscale_min=args.autoscale_min,
        autoscale_max=args.autoscale_max,
        autoscale_interval=args.autoscale_interval,
        autoscale_cooldown=args.autoscale_cooldown,
        brownout=args.brownout == "on",
        disagg=args.disagg,
        prefill_replicas=args.prefill_replicas,
        decode_replicas=args.decode_replicas,
        shared_weights=args.shared_weights,
        pod=args.pod,
    )
    if multihost:
        import jax

        if jax.process_index() > 0:
            # worker rank: no HTTP — mirror rank 0's step sequence until
            # shutdown (the reference's per-machine shard server,
            # /root/reference/shard/main.py:4-14, without the RPC surface)
            logger.info("worker rank %d serving", jax.process_index())
            if args.concurrent > 1:
                from mlx_sharding_tpu.parallel.multihost import (
                    serve_worker_batched,
                )

                serve_worker_batched(
                    provider.generator,
                    decode_block=min(8, args.decode_block),
                    prefix_cache=provider.prefix_cache_enabled,
                )
            else:
                from mlx_sharding_tpu.parallel.multihost import serve_worker

                serve_worker(provider.generator)
            return
    server = make_server(provider, args.host, args.port,
                         profile_dir=args.profile_dir, api_key=args.api_key,
                         request_timeout=args.request_timeout,
                         ttft_timeout=args.ttft_timeout)
    logger.info("serving on http://%s:%d", args.host, args.port)
    server.serve_forever()


if __name__ == "__main__":
    main()
