"""Cross-replica shared weights: one resident packed copy per host.

Every data-parallel replica used to upload its OWN device copy of the
packed params — N replicas cost N×W HBM and every autoscaler spawn paid a
full checkpoint re-placement before it could serve. The ``WeightStore``
breaks that: device-resident param trees (the output of
``parallel.pipeline.place_weights``) are keyed by (checkpoint, stage
bounds, dtype, quant/fusion config, mesh placement) and placed ONCE; every
replica whose engine runs on the same model-parallel footprint aliases the
same arrays through a refcounted lease. Fleet weight bytes drop from N×W
to ~W, and a spawn that hits the store costs slot/cache setup only — the
PRESERVE-style property (arXiv:2501.08192) that scaling out overlaps with
serving instead of stalling on checkpoint I/O.

Lifecycle contract:

- ``acquire(key, build)`` returns a ``WeightLease``; the first acquire of
  a key runs ``build()`` (the one real upload), later acquires alias it.
- Each engine holds exactly one lease and releases it from ``close()``
  (``PipelineEngine.on_close``); ``ReplicaSet.drain``/``close`` and disagg
  pool teardown ride that hook, so retirement releases refs and the LAST
  release drops the store's reference (the arrays die with the last
  engine).
- A faulted spawn must release the lease it acquired before re-raising
  (``aliased_spawn`` wraps that), so ``replica.spawn`` faults leave
  refcounts consistent: never a leaked tree, never one freed in use.
- Releasing a key the store doesn't hold — or the same lease twice — is a
  bug, and raises.

The store is deliberately jax-free: it holds whatever resident-tree object
the builder returns (``ResidentWeights`` in practice) and only reads its
``weight_bytes`` for the ``mst_weight_store_bytes`` gauge.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from mlx_sharding_tpu.analysis.runtime import make_lock, note_acquire, note_release


@dataclass
class ResidentWeights:
    """A device-resident weight tree plus everything an engine needs to
    execute against it without re-deriving placement: the mesh it lives
    on, the resolved stage split, the PartitionSpecs, and the vocab-shard
    machinery. Built by ``parallel.pipeline.place_weights``; consumed by
    ``PipelineEngine(..., weights=...)`` for alias-fast construction."""

    mesh: Any
    stage_bounds: list
    layer_specs: Any
    layer_params: Any
    layer_masks: Any
    layers_per_stage: int
    fused_projections: list
    vocab_size: int
    head_tied: bool
    vocab_parts: Any
    shared_params: Any
    weight_bytes: int


@dataclass(frozen=True)
class WeightKey:
    """Identity of a resident tree. Two engines share arrays iff every
    field matches: the checkpoint's weight content (resolved path + quant
    config + packed/dense residency, see ``loading.checkpoint_signature``),
    the stage split, the compute dtype, the build-time fusion config, and
    the mesh placement (``mesh_fingerprint``) — arrays are device-resident,
    so WHERE they live is part of WHAT they are."""

    checkpoint: str
    stage_bounds: tuple
    dtype: str
    quant: str
    placement: str


def key_digest(key: WeightKey) -> str:
    """Short stable identity of a WeightKey for the pod control plane:
    hosts gossip digests (16 hex chars), not full keys — the checkpoint
    path alone can exceed a pod message slot, and equality is all the
    cross-host arbitration needs."""
    import hashlib

    h = hashlib.blake2b(digest_size=8)
    h.update(repr((
        key.checkpoint, key.stage_bounds, key.dtype, key.quant,
        key.placement,
    )).encode())
    return h.hexdigest()


class WeightLease:
    """One engine's refcounted handle on a resident tree. ``release()`` is
    single-shot by contract — the double-release of a shared tree is how a
    freed-in-use bug starts, so the second call raises instead of silently
    decrementing someone else's ref."""

    __slots__ = ("store", "key", "weights", "_released")

    def __init__(self, store: "WeightStore", key: WeightKey, weights):
        self.store = store
        self.key = key
        self.weights = weights
        self._released = False

    @property
    def released(self) -> bool:
        return self._released

    def release(self) -> bool:
        """Drop this lease's ref. Returns True when this was the last ref
        and the store freed the tree."""
        if self._released:
            raise RuntimeError(
                f"weight lease for {self.key.checkpoint!r} released twice"
            )
        self._released = True
        note_release("weights.lease", id(self))
        return self.store.release(self.key)


class _Entry:
    __slots__ = ("weights", "refs")

    def __init__(self, weights):
        self.weights = weights
        self.refs = 0


@dataclass
class WeightStore:
    """Refcounted registry of device-resident weight trees, one per
    ``WeightKey``. Per-host singleton in serving (``weight_store()``);
    tests build private instances."""

    _lock: Any = field(default_factory=lambda: make_lock("WeightStore._lock"))
    _entries: dict = field(default_factory=dict)

    def acquire(self, key: WeightKey, build: Callable[[], Any]) -> WeightLease:
        """Lease the tree for ``key``, building (uploading) it iff absent.
        The build runs under the store lock: two concurrent spawns of the
        same key must produce ONE placement, and an upload racing a
        last-release free must not resurrect a half-dropped entry. A build
        that raises leaves no entry behind."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                entry = _Entry(build())
                self._entries[key] = entry
            entry.refs += 1
            lease = WeightLease(self, key, entry.weights)
            note_acquire("weights.lease", id(lease), checkpoint=key.checkpoint)
            return lease

    def release(self, key: WeightKey) -> bool:
        """Drop one ref on ``key``; the last release frees the store's
        reference (engines still alive keep the arrays alive through their
        own attributes — the device memory dies with the last of them).
        Releasing a key the store doesn't hold raises: it means a lease
        was double-released or never acquired."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                raise RuntimeError(
                    f"release of weight tree the store does not hold: {key}"
                )
            entry.refs -= 1
            if entry.refs == 0:
                del self._entries[key]
                return True
            return False

    def refs(self, key: WeightKey) -> int:
        with self._lock:
            entry = self._entries.get(key)
            return 0 if entry is None else entry.refs

    def stats(self) -> dict:
        """Gauge source for ``mst_weight_store_{bytes,trees,refs}`` and the
        /health store block. Each entry carries its :func:`key_digest` so
        the pod weight registry can gossip which trees THIS host holds
        without shipping the full WeightKey over the control plane."""
        with self._lock:
            entries = [
                {
                    "checkpoint": key.checkpoint,
                    "placement": key.placement,
                    "digest": key_digest(key),
                    "refs": e.refs,
                    "bytes": int(getattr(e.weights, "weight_bytes", 0) or 0),
                }
                for key, e in self._entries.items()
            ]
        return {
            "trees": len(entries),
            "refs": sum(e["refs"] for e in entries),
            "bytes": sum(e["bytes"] for e in entries),
            "entries": entries,
        }

    def find(self, digest: str) -> Optional[WeightKey]:
        """Resolve a gossiped digest back to this host's WeightKey, or None
        when this host holds no such tree — the pod teardown handler uses
        this to map a ``weights.teardown`` message onto a local key."""
        with self._lock:
            for key in self._entries:
                if key_digest(key) == digest:
                    return key
        return None


def aliased_spawn(
    store: WeightStore,
    key: WeightKey,
    build: Callable[[], Any],
    make_engine: Callable[[WeightLease], Any],
):
    """The spawn-path contract in one place: acquire a lease, construct the
    engine against it, and on ANY construction failure release the lease
    before re-raising — a faulted ``replica.spawn`` degrades to the static
    fleet with refcounts exactly as they were, never holding a ref for an
    engine that doesn't exist (leak) and never having freed a tree another
    replica is executing against."""
    lease = store.acquire(key, build)
    try:
        return make_engine(lease)
    except BaseException:
        lease.release()
        raise


_STORE: Optional[WeightStore] = None
_STORE_LOCK = make_lock("weights._STORE_LOCK")


def weight_store() -> WeightStore:
    """The per-host (per-process) store serving and /metrics share."""
    global _STORE
    with _STORE_LOCK:
        if _STORE is None:
            _STORE = WeightStore()
        return _STORE
