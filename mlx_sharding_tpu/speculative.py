"""Speculative decoding with a draft model — exact greedy acceleration.

ROADMAP item: the reference has no speculation of any kind. A small draft
model proposes ``spec_k`` tokens per round; the target model scores all of
them in ONE T=K forward (prefill-shaped — MXU-efficient, unlike K
sequential matvecs) and the longest prefix the target agrees with is
emitted, plus the target's own correction token at the first divergence.
Every emitted token is exactly what plain greedy decode would produce —
whatever the draft's quality, only throughput changes, never content
(tested token-exact in tests/test_speculative.py).

The TPU-shaped part is the rollback: this framework's caches derive
validity from the offset (rows past it are never attended and are
overwritten in place), so rejecting draft tokens costs ONE scalar — set
``offset = verified_prefix_end`` — no copying, no paging, no mask
rebuild. The draft model keeps its own cache and rewinds the same way.

Greedy requests (temperature == 0 — the serving default) use exact prefix
acceptance: every emitted token is what plain greedy decode would produce.
Sampled requests (temperature > 0) use REJECTION SAMPLING (Leviathan et
al.): the draft SAMPLES its proposals and records its distribution q_i;
the target's one T=K forward yields p_i; proposal d is accepted with
probability min(1, p_i(d)/q_i(d)), and the first rejection resamples from
the residual norm(max(p_i - q_i, 0)). The emitted stream is distributed
EXACTLY as plain sampling from the target (tested distributionally in
tests/test_speculative.py) — the draft only changes throughput, never the
distribution. Both p and q are the fully-transformed distributions
(logit_bias, repetition penalty over an exactly-evolved window,
temperature, top-p nucleus), so speculation composes with every sampler
knob; the token streams differ from non-speculative sampling for the same
seed (the PRNG is consumed differently), which is inherent to the method.
"""

from __future__ import annotations

import time
from typing import Callable, Iterator, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from mlx_sharding_tpu.generate import (
    REPETITION_WINDOW,
    Generator,
    TokenLogprobs,
)
from mlx_sharding_tpu.sample import (
    init_recent_tokens,
    make_sampler_params,
    nucleus_logits,
    sample_token,
    transform_logits,
    update_recent_tokens,
)

# the adaptive window ladder: 0 == drafting disabled for the slot, the
# nonzero rungs are the candidate speculation windows. Powers of two keep
# the number of distinct verify-program compilations at 3.
SPEC_WINDOW_LADDER = (0, 2, 4, 8)


def _dist_logits(logits, recent, sp):
    """The request's full sampling distribution in log domain (unnormalized),
    via the SAME pipeline sample_token samples from (sample.py
    transform_logits → nucleus_logits) — p and q below are both defined by
    it, which is what makes the acceptance ratio meaningful."""
    return nucleus_logits(transform_logits(logits, recent, sp), sp)


def rejection_round(key, drafts, q_logprobs, p_logprobs):
    """One round of speculative rejection sampling (pure math, jit-safe).

    drafts: (K, B) proposals; q_logprobs / p_logprobs: (K, B, V) draft and
    target log-distributions at each slot. Returns (gs, m, count):
    gs (K, B) — per-slot emitted token (draft token where accepted, the
    residual resample where rejected; only slots ≤ m are meaningful),
    m (B,) — last emitted slot, count (B,) = m + 1.

    Guarantee (the Leviathan et al. identity, unit-tested directly): the
    token emitted at a slot is distributed exactly as p at that slot."""
    K, B = drafts.shape
    k_u, k_res = jax.random.split(key)
    u = jax.random.uniform(k_u, (K, B))
    d_lp_q = jnp.take_along_axis(
        q_logprobs, drafts[..., None], axis=-1
    )[..., 0]  # (K, B)
    d_lp_p = jnp.take_along_axis(
        p_logprobs, drafts[..., None], axis=-1
    )[..., 0]
    # accept with prob min(1, p/q); exp of a clamped-to-0 log ratio avoids
    # overflow and u < 1 makes ratio >= 1 an unconditional accept
    accept = u < jnp.exp(jnp.minimum(d_lp_p - d_lp_q, 0.0))
    reject = ~accept

    # residual distribution per slot: norm(max(p - q, 0)); if its mass is
    # ~0 (p ≈ q everywhere) resampling from p is the correct limit
    p = jnp.exp(p_logprobs)
    q = jnp.exp(q_logprobs)
    res = jnp.maximum(p - q, 0.0)
    mass = res.sum(axis=-1, keepdims=True)
    res_logits = jnp.where(mass > 1e-9, jnp.log(res), p_logprobs)
    r = jax.vmap(jax.random.categorical)(
        jax.random.split(k_res, K), res_logits
    ).astype(jnp.int32)  # (K, B)

    gs = jnp.where(reject, r, drafts)
    any_rej = reject.any(axis=0)
    first = jnp.argmax(reject, axis=0)
    m = jnp.where(any_rej, first, K - 1)
    return gs, m, (m + 1).astype(jnp.int32)


def _round_epilogue(K, gs, m, count, off0, cache, recent):
    """Shared verify epilogue (greedy and rejection-sampled rounds): replay
    ONLY the emitted tokens into the recent window, keep exactly the
    verified prefix in the cache (gs[m] is the next feed token and is NOT
    cached), return the round tuple."""

    def replay(carry, i):
        recent = carry
        upd = update_recent_tokens(recent, gs[i])
        return jnp.where((i <= m)[:, None], upd, recent), None

    recent, _ = jax.lax.scan(replay, recent, jnp.arange(K))
    cache = cache._replace(offset=off0 + count[0])
    return gs, count, gs[m[0]], cache, recent


def one_hot_draft_logprobs(drafts, vocab_size):
    """The q-distribution of a DETERMINISTIC proposer (n-gram lookup) in
    log domain: probability 1 on the proposed token, ~0 elsewhere. With
    this q the rejection-sampling identity degenerates to: accept d with
    probability p(d), else resample from p with d removed (renormalized) —
    exact for any proposal chain. Built INSIDE jit from the (K, B) draft
    ids, so no (K, B, V) array ever crosses the host boundary."""
    hot = jax.nn.one_hot(drafts, vocab_size, dtype=bool)  # (K, B, V)
    return jnp.where(hot, 0.0, -1e9)


class NgramDraftProposer:
    """Prompt-lookup drafting: propose the K tokens that followed the most
    recent occurrence of the stream's trailing n-gram (n = max_ngram down
    to min_ngram) in the slot's prompt + produced history. Free speculation
    — no second checkpoint, no draft KV cache, no device work; repetitive
    streams (code, extraction, chat with quoting) accept long runs while
    novel text simply proposes nothing and the round degenerates to plain
    decode for that slot.

    Host-pure by contract: ``propose`` touches numpy only — it runs inside
    the scheduler's tick-hot path (mstcheck MST114 enforces that neither it
    nor the acceptance tracker ever performs a device sync). The trailing
    ``window`` tokens of the history act as the ring buffer: matching cost
    is O(window) vectorized per round, independent of stream length.

    Proposals shorter than ``k`` are padded with token 0 — a VALID id, not
    a sentinel: padded rows still flow through the verify forward, and the
    caller cuts them off via the per-slot window cap (``n_valid``). A -1
    pad would be clamped to row 0 by ``take_along_axis`` and one_hot(-1)
    is all-zero, which silently corrupts the sampled acceptance math."""

    def __init__(self, *, max_ngram: int = 3, min_ngram: int = 1,
                 window: int = 2048):
        if not (1 <= min_ngram <= max_ngram):
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram, got "
                f"({min_ngram}, {max_ngram})"
            )
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram
        self.window = window

    def propose(self, tokens, k: int):
        """tokens: 1-D int sequence, most recent last (prompt + history).
        Returns ``(drafts, n_valid)``: drafts is (k,) int32 padded with
        token 0 past ``n_valid``; n_valid == 0 means no match anywhere."""
        toks = np.asarray(tokens, np.int32).ravel()
        if self.window and toks.size > self.window:
            toks = toks[-self.window:]
        out = np.zeros(k, np.int32)
        n_tok = int(toks.size)
        if k < 1 or n_tok < self.min_ngram + 1:
            return out, 0
        # longest context first; the trailing window itself is excluded
        # (a window over toks[:-1] can't start at the trailing position)
        hay = toks[:-1]
        for n in range(min(self.max_ngram, n_tok - 1), self.min_ngram - 1, -1):
            pat = toks[-n:]
            wins = np.lib.stride_tricks.sliding_window_view(hay, n)
            hits = np.nonzero((wins == pat).all(axis=1))[0]
            if hits.size == 0:
                continue
            start = int(hits[-1]) + n  # most recent occurrence wins
            cont = toks[start:start + k]
            out[:cont.size] = cont
            return out, int(cont.size)
        return out, 0


class AcceptanceTracker:
    """Per-slot adaptive speculation-window controller.

    Tracks an EWMA of tokens-emitted-per-round (``count`` ∈ [1, w]: 1 means
    the draft never agreed — the round cost a K-wide forward to emit what
    plain decode emits with a 1-wide one) and walks the slot's window along
    ``SPEC_WINDOW_LADDER``:

    - grow to the next rung when the EWMA fills ≥ ``grow_frac`` of the
      current window (the draft is saturating it);
    - shrink one rung when the EWMA pays for ≤ max(1.25, shrink_frac·w)
      tokens — below the bottom rung the slot DISABLES (window 0) and
      re-probes at the bottom rung after ``probe_after_s`` (injectable
      ``clock`` keeps the schedule deterministic under test).

    The same per-slot EWMAs order brownout shedding: at pressure level 2
    ``effective_windows`` sheds the lowest-acceptance half of live slots
    (speculation that barely pays is the first capacity lever to drop);
    level ≥ 3 sheds all. Shedding is per-round pressure, not slot state —
    the EWMA keeps evolving and the window returns the moment pressure
    clears. Host-pure: observe/effective_windows touch python ints only
    (MST114)."""

    def __init__(self, n_slots: int, *, w_max: int = 8, alpha: float = 0.25,
                 grow_frac: float = 0.85, shrink_frac: float = 0.35,
                 probe_after_s: float = 1.0,
                 clock: Callable[[], float] = time.monotonic):
        rungs = tuple(w for w in SPEC_WINDOW_LADDER if 0 < w <= max(w_max, 2))
        self.rungs = rungs
        self.alpha = alpha
        self.grow_frac = grow_frac
        self.shrink_frac = shrink_frac
        self.probe_after_s = probe_after_s
        self.clock = clock
        self.shed_events = 0
        self._win = [rungs[0]] * n_slots
        self._ewma: list[Optional[float]] = [None] * n_slots
        self._disabled_at: list[Optional[float]] = [None] * n_slots
        self._shed_prev: set[int] = set()

    def reset(self, slot: int):
        """New request in the slot: fresh window at the bottom rung (probe
        first, grow on evidence) and no carried-over acceptance history."""
        self._win[slot] = self.rungs[0]
        self._ewma[slot] = None
        self._disabled_at[slot] = None

    def observe(self, slot: int, window: int, count: int):
        """Fold one round's outcome (``count`` tokens emitted from a
        ``window``-wide round) into the slot's EWMA and resize."""
        if window < 1:
            return
        e = self._ewma[slot]
        e = float(count) if e is None else (
            self.alpha * count + (1.0 - self.alpha) * e
        )
        self._ewma[slot] = e
        w = self._win[slot]
        if w == 0:
            return
        if e >= self.grow_frac * w and w < self.rungs[-1]:
            self._win[slot] = self.rungs[
                min(self.rungs.index(w) + 1, len(self.rungs) - 1)
            ]
        elif e <= max(1.25, self.shrink_frac * w):
            i = self.rungs.index(w)
            if i == 0:
                self._win[slot] = 0
                self._disabled_at[slot] = self.clock()
                self._ewma[slot] = None  # the probe gets fresh evidence
            else:
                self._win[slot] = self.rungs[i - 1]

    def window(self, slot: int) -> int:
        """Current window for the slot, applying the re-probe schedule:
        a disabled slot returns to the bottom rung after probe_after_s."""
        if self._win[slot] == 0 and self._disabled_at[slot] is not None:
            if self.clock() - self._disabled_at[slot] >= self.probe_after_s:
                self._win[slot] = self.rungs[0]
                self._disabled_at[slot] = None
        return self._win[slot]

    def effective_windows(self, slots: Sequence[int], level: int = 0):
        """Per-round window plan for the live ``slots`` under brownout
        pressure ``level``: level >= 3 sheds every slot, level 2 sheds the
        lowest-EWMA half (no-evidence slots shed first — under pressure,
        unproven speculation goes before proven), below 2 sheds nothing.
        Returns {slot: window}; counts shed-set ENTRY transitions in
        ``shed_events``."""
        wins = {s: self.window(s) for s in slots}
        enabled = [s for s in slots if wins[s] > 0]
        if level >= 3:
            shed = set(enabled)
        elif level == 2 and enabled:
            order = sorted(
                enabled,
                key=lambda s: (
                    self._ewma[s] if self._ewma[s] is not None else 0.0, s
                ),
            )
            shed = set(order[: (len(enabled) + 1) // 2])
        else:
            shed = set()
        self.shed_events += len(shed - self._shed_prev)
        self._shed_prev = shed
        for s in shed:
            wins[s] = 0
        return wins

    def ewma(self, slot: int) -> Optional[float]:
        return self._ewma[slot]

    def stats(self) -> dict:
        """Gauge source for the mst_spec_* metrics and /health."""
        tracked = [e for e in self._ewma if e is not None]
        return {
            "windows": list(self._win),
            "disabled_slots": sum(
                1 for w, d in zip(self._win, self._disabled_at)
                if w == 0 and d is not None
            ),
            "shed_events": self.shed_events,
            "ewma_mean": (sum(tracked) / len(tracked)) if tracked else 0.0,
        }


class SpeculativeGenerator:
    """``generate_step`` contract over a (target, draft) model pair.

    Holds two plain Generators (their prefill/sample programs are reused
    verbatim) plus two speculation programs: the draft's K-step greedy
    scan and the target's fused verify (T=K forward + transform-aware
    acceptance)."""

    def __init__(
        self,
        model,
        params,
        draft_model,
        draft_params,
        *,
        spec_k: int = 4,
        max_seq: int = 4096,
        cache_dtype=jnp.bfloat16,
        prefill_chunk: int = 256,
        decode_block: int = 16,
    ):
        if spec_k < 1:
            raise ValueError(f"spec_k must be >= 1, got {spec_k}")
        tv = getattr(model.config, "vocab_size", None)
        dv = getattr(draft_model.config, "vocab_size", None)
        if tv != dv:
            # a mismatched pair would silently emit clamped-index garbage:
            # draft token ids index the target's embedding/logprob rows
            raise ValueError(
                f"draft vocab ({dv}) must match target vocab ({tv}) — "
                "speculation exchanges raw token ids between the models"
            )
        if not (model.config.is_first_stage and model.config.is_last_stage):
            raise ValueError(
                "speculative decoding needs the FULL model on one program "
                "(no start/end-layer stage slice)"
            )
        self.spec_k = spec_k
        # acceptance telemetry: tokens emitted per verify round averages
        # between 1 (draft never agrees) and K (always agrees)
        self.rounds = 0
        self.accepted_tokens = 0
        self.target = Generator(
            model, params, max_seq=max_seq, cache_dtype=cache_dtype,
            prefill_chunk=prefill_chunk, decode_block=decode_block,
        )
        self.draft = Generator(
            draft_model, draft_params, max_seq=max_seq,
            cache_dtype=cache_dtype, prefill_chunk=prefill_chunk,
        )
        self.max_seq = self.target.max_seq

        K = spec_k

        def draft_block_fn(dparams, token, dcache):
            """K greedy draft proposals (plain argmax — transforms live on
            the verify side where exactness is decided)."""

            def step(carry, _):
                tok, dcache = carry
                logits, dcache = draft_model(dparams, tok[:, None], dcache)
                tok = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
                return (tok, dcache), tok

            (_, dcache), drafts = jax.lax.scan(
                step, (token, dcache), None, length=K
            )
            return drafts, dcache  # drafts (K, B)

        def finish_round(gs, m, count, off0, cache, recent):
            return _round_epilogue(K, gs, m, count, off0, cache, recent)

        def verify_fn(params, token, drafts, cache, recent, sp):
            """One target forward over [t0, d1..d_{K-1}] scores every draft
            position; acceptance walks the agreement prefix. Returns the
            emitted tokens (K, B; rows past ``count`` are garbage), the
            count, the next feed token, and state rewound to the verified
            prefix."""
            b = token.shape[0]
            x = jnp.concatenate([token[:, None], drafts[:-1].T], axis=1)  # (B, K)
            off0 = cache.offset
            logits, cache = model(params, x, cache)  # (B, K, V)
            zero_key = jax.random.PRNGKey(0)  # unused at temperature 0

            def score(carry, i):
                recent = carry
                g, _ = sample_token(zero_key, logits[:, i], sp, recent)
                recent = update_recent_tokens(recent, g)
                return recent, g

            _, gs = jax.lax.scan(score, recent, jnp.arange(K))  # (K, B)

            mism = gs != drafts  # position i: target's g_i vs proposal d_{i+1}
            any_mism = mism.any(axis=0)  # (B,)
            first = jnp.argmax(mism, axis=0)  # first True (0 if none)
            m = jnp.where(any_mism, first, K - 1)
            count = (m + 1).astype(jnp.int32)  # tokens emitted this round
            return finish_round(gs, m, count, off0, cache, recent)

        def draft_sampled_fn(dparams, token, dcache, recent, keys, sp):
            """K sampled draft proposals + the exact distribution each was
            drawn from (q_i log rows — the acceptance denominator). The
            draft sees the target's true recent window and evolves a local
            copy with its own proposals."""

            def step(carry, key_i):
                tok, dcache, recent = carry
                logits, dcache = draft_model(dparams, tok[:, None], dcache)
                f = _dist_logits(logits[:, -1], recent, sp)
                qlp = jax.nn.log_softmax(f, axis=-1)
                tok = jax.random.categorical(key_i, f, axis=-1).astype(
                    jnp.int32
                )
                recent = update_recent_tokens(recent, tok)
                return (tok, dcache, recent), (tok, qlp)

            (_, dcache, _), (drafts, qlps) = jax.lax.scan(
                step, (token, dcache, recent), keys
            )
            return drafts, qlps, dcache  # (K, B), (K, B, V)

        def verify_sampled_fn(params, token, drafts, qlps, cache, recent,
                              key, sp):
            """Target T=K forward + rejection sampling. Same bookkeeping as
            the greedy verify: gs[m] is the next feed token and is NOT in
            the cache; offset keeps exactly the verified prefix."""
            x = jnp.concatenate([token[:, None], drafts[:-1].T], axis=1)
            off0 = cache.offset
            logits, cache = model(params, x, cache)  # (B, K, V)

            def score(carry, i):
                recent = carry
                f = _dist_logits(logits[:, i], recent, sp)
                plp = jax.nn.log_softmax(f, axis=-1)
                # the consumed token at slot i+1 is drafts[i]; evolving with
                # it is exact on the accepted prefix (discarded past it)
                recent = update_recent_tokens(recent, drafts[i])
                return recent, plp

            _, plps = jax.lax.scan(score, recent, jnp.arange(K))  # (K, B, V)
            gs, m, count = rejection_round(key, drafts, qlps, plps)
            return finish_round(gs, m, count, off0, cache, recent)

        self._draft_block = jax.jit(draft_block_fn, donate_argnums=(2,))
        self._verify = jax.jit(verify_fn, donate_argnums=(3, 4))
        self._draft_sampled = jax.jit(draft_sampled_fn, donate_argnums=(2,))
        self._verify_sampled = jax.jit(
            verify_sampled_fn, donate_argnums=(4, 5)
        )
        self._rewind = jax.jit(
            lambda c, off: c._replace(offset=off), donate_argnums=(0,)
        )

    # ------------------------------------------------------------------
    def generate_step(
        self,
        prompt_tokens,
        *,
        temperature: float = 0.0,
        top_p: float = 1.0,
        repetition_penalty: Optional[float] = None,
        repetition_context_size: int = REPETITION_WINDOW,
        logit_bias: Optional[dict[int, float]] = None,
        seed: Optional[int] = None,
        max_tokens: int = 256,
        want_logprobs: bool = False,
    ) -> Iterator[tuple[int, Optional[TokenLogprobs]]]:
        if want_logprobs:
            # logprobs need per-token summaries the verify path doesn't
            # compute — take the exact normal path
            yield from self.target.generate_step(
                prompt_tokens, temperature=temperature, top_p=top_p,
                repetition_penalty=repetition_penalty,
                repetition_context_size=repetition_context_size,
                logit_bias=logit_bias, seed=seed, max_tokens=max_tokens,
                want_logprobs=want_logprobs,
            )
            return

        sampled = temperature > 0
        sp = make_sampler_params(
            temperature, top_p, repetition_penalty, logit_bias
        )
        prompt = np.asarray(prompt_tokens, np.int32).reshape(
            self.target.batch, -1
        )
        n_prompt = prompt.shape[1]
        if n_prompt + max_tokens > self.max_seq:
            raise ValueError(
                f"prompt ({n_prompt}) + max_tokens ({max_tokens}) exceeds KV "
                f"capacity {self.max_seq}"
            )

        import time as _time

        t = self.target
        cache = t.model.make_cache(t.batch, t.max_seq, t.cache_dtype)
        recent = init_recent_tokens(t.batch, repetition_context_size, prompt)
        key = jax.random.PRNGKey(
            int(_time.time_ns()) & 0x7FFFFFFF if seed is None else seed
        )

        last_logits, cache = t.run_prefill(prompt, cache)
        # draft prefills the same prompt into its own cache
        d = self.draft
        dcache = d.model.make_cache(d.batch, d.max_seq, d.cache_dtype)
        _, dcache = d.run_prefill(prompt, dcache)

        tok, logprobs, recent, key = t._sample(last_logits, recent, key, sp)
        yield int(tok[0]), None
        emitted = 1
        # the first emitted token's row is in NEITHER cache: both models
        # consume it as the next round's feed token, exactly like normal
        # decode. ``offset`` mirrors cache.offset on host for the capacity
        # check (it grows by the accepted count each round).
        offset = n_prompt
        K = self.spec_k
        while emitted < max_tokens:
            if offset + K > self.max_seq or max_tokens - emitted < 2:
                # tail (or capacity edge): plain blocked decode from here
                remaining = max_tokens - emitted

                def dispatch(carry):
                    outs, tk, ch, rc, kk = t._decode_block(
                        t.params, carry[0], carry[1], carry[2], carry[3],
                        sp, False,
                    )
                    return outs, (tk, ch, rc, kk)

                from mlx_sharding_tpu.generate import blocked_token_stream

                yield from blocked_token_stream(
                    dispatch, (tok, cache, recent, key), remaining,
                    t.decode_block, False,
                )
                return

            if sampled:
                key, kd, kv = jax.random.split(key, 3)
                drafts, qlps, dcache = self._draft_sampled(
                    d.params, tok, dcache, recent, jax.random.split(kd, K), sp
                )
                gs, count, tok, cache, recent = self._verify_sampled(
                    t.params, tok, drafts, qlps, cache, recent, kv, sp
                )
            else:
                drafts, dcache = self._draft_block(d.params, tok, dcache)
                gs, count, tok, cache, recent = self._verify(
                    t.params, tok, drafts, cache, recent, sp
                )
            n, gs_host = int(count[0]), np.asarray(gs)
            self.rounds += 1
            self.accepted_tokens += n
            # draft consumed [t0, d1..d_{K-1}] = K rows; keep the verified
            # prefix (the accepted tokens ARE the draft's inputs there)
            dcache = self._rewind(
                dcache, dcache.offset - K + jnp.asarray(n, jnp.int32)
            )
            for j in range(n):
                if emitted >= max_tokens:
                    break
                yield int(gs_host[j, 0]), None
                emitted += 1
            offset += n


class NgramSpeculativeGenerator:
    """``generate_step`` contract with prompt-lookup drafts — no draft
    model, no draft KV cache. Proposals come from :class:`NgramDraftProposer`
    over the stream's own prompt + produced history; the target scores them
    in one T=K forward exactly like the draft-engine path. The window
    adapts per round via :class:`AcceptanceTracker`; a disabled window runs
    K=1 rounds (verify-only decode — one token per forward, still exact)
    until the re-probe timer fires.

    Greedy streams are token-exact vs plain decode (acceptance-prefix
    argument, draft-agnostic); sampled streams are distribution-exact via
    rejection sampling against the proposer's one-hot q. The per-round
    window cap is applied INSIDE the verify program (m = min(m, wcap-1)):
    truncating to a prefix of properly-accepted positions before anything
    past it is committed is exactly window-wcap speculation."""

    def __init__(
        self,
        model,
        params,
        *,
        spec_window_max: int = 8,
        max_seq: int = 4096,
        cache_dtype=jnp.bfloat16,
        prefill_chunk: int = 256,
        decode_block: int = 16,
        max_ngram: int = 3,
        clock: Callable[[], float] = time.monotonic,
    ):
        if spec_window_max < 2:
            raise ValueError(
                f"spec_window_max must be >= 2, got {spec_window_max}"
            )
        if not (model.config.is_first_stage and model.config.is_last_stage):
            raise ValueError(
                "speculative decoding needs the FULL model on one program "
                "(no start/end-layer stage slice)"
            )
        self.target = Generator(
            model, params, max_seq=max_seq, cache_dtype=cache_dtype,
            prefill_chunk=prefill_chunk, decode_block=decode_block,
        )
        self.max_seq = self.target.max_seq
        self.proposer = NgramDraftProposer(max_ngram=max_ngram)
        self.tracker = AcceptanceTracker(1, w_max=spec_window_max, clock=clock)
        self.spec_window_max = spec_window_max
        self.rounds = 0
        self.accepted_tokens = 0
        self.draft_tokens = 0
        self._model = model
        self._verify_greedy: dict[int, Callable] = {}
        self._verify_sampled: dict[int, Callable] = {}

    def _greedy_prog(self, K: int):
        prog = self._verify_greedy.get(K)
        if prog is not None:
            return prog
        model = self._model

        def fn(params, token, drafts, wcap, cache, recent, sp):
            x = jnp.concatenate([token[:, None], drafts[:-1].T], axis=1)
            off0 = cache.offset
            logits, cache = model(params, x, cache)  # (B, K, V)
            zero_key = jax.random.PRNGKey(0)  # unused at temperature 0

            def score(carry, i):
                recent = carry
                g, _ = sample_token(zero_key, logits[:, i], sp, recent)
                recent = update_recent_tokens(recent, g)
                return recent, g

            _, gs = jax.lax.scan(score, recent, jnp.arange(K))  # (K, B)
            mism = gs != drafts
            any_mism = mism.any(axis=0)
            first = jnp.argmax(mism, axis=0)
            m = jnp.where(any_mism, first, K - 1)
            m = jnp.minimum(m, wcap - 1)  # per-round window cap
            count = (m + 1).astype(jnp.int32)
            return _round_epilogue(K, gs, m, count, off0, cache, recent)

        prog = jax.jit(fn, donate_argnums=(4, 5))
        self._verify_greedy[K] = prog
        return prog

    def _sampled_prog(self, K: int):
        prog = self._verify_sampled.get(K)
        if prog is not None:
            return prog
        model = self._model
        vocab = model.config.vocab_size

        def fn(params, token, drafts, wcap, cache, recent, key, sp):
            x = jnp.concatenate([token[:, None], drafts[:-1].T], axis=1)
            off0 = cache.offset
            logits, cache = model(params, x, cache)  # (B, K, V)

            def score(carry, i):
                recent = carry
                f = _dist_logits(logits[:, i], recent, sp)
                plp = jax.nn.log_softmax(f, axis=-1)
                recent = update_recent_tokens(recent, drafts[i])
                return recent, plp

            _, plps = jax.lax.scan(score, recent, jnp.arange(K))
            qlps = one_hot_draft_logprobs(drafts, vocab)
            gs, m, count = rejection_round(key, drafts, qlps, plps)
            m = jnp.minimum(m, wcap - 1)  # per-round window cap
            count = (m + 1).astype(jnp.int32)
            return _round_epilogue(K, gs, m, count, off0, cache, recent)

        prog = jax.jit(fn, donate_argnums=(4, 5))
        self._verify_sampled[K] = prog
        return prog

    # ------------------------------------------------------------------
    def generate_step(
        self,
        prompt_tokens,
        *,
        temperature: float = 0.0,
        top_p: float = 1.0,
        repetition_penalty: Optional[float] = None,
        repetition_context_size: int = REPETITION_WINDOW,
        logit_bias: Optional[dict[int, float]] = None,
        seed: Optional[int] = None,
        max_tokens: int = 256,
        want_logprobs: bool = False,
    ) -> Iterator[tuple[int, Optional[TokenLogprobs]]]:
        if want_logprobs:
            # logprobs need per-token summaries the verify path doesn't
            # compute — take the exact normal path
            yield from self.target.generate_step(
                prompt_tokens, temperature=temperature, top_p=top_p,
                repetition_penalty=repetition_penalty,
                repetition_context_size=repetition_context_size,
                logit_bias=logit_bias, seed=seed, max_tokens=max_tokens,
                want_logprobs=want_logprobs,
            )
            return

        sampled = temperature > 0
        sp = make_sampler_params(
            temperature, top_p, repetition_penalty, logit_bias
        )
        prompt = np.asarray(prompt_tokens, np.int32).reshape(
            self.target.batch, -1
        )
        n_prompt = prompt.shape[1]
        if n_prompt + max_tokens > self.max_seq:
            raise ValueError(
                f"prompt ({n_prompt}) + max_tokens ({max_tokens}) exceeds KV "
                f"capacity {self.max_seq}"
            )

        t = self.target
        cache = t.model.make_cache(t.batch, t.max_seq, t.cache_dtype)
        recent = init_recent_tokens(t.batch, repetition_context_size, prompt)
        key = jax.random.PRNGKey(
            int(time.time_ns()) & 0x7FFFFFFF if seed is None else seed
        )
        self.tracker.reset(0)

        last_logits, cache = t.run_prefill(prompt, cache)
        tok, logprobs, recent, key = t._sample(last_logits, recent, key, sp)
        history = list(prompt[0]) + [int(tok[0])]
        yield int(tok[0]), None
        emitted = 1
        offset = n_prompt
        while emitted < max_tokens:
            w = self.tracker.window(0)
            K = w if w > 0 else 1  # disabled: verify-only decode round
            if offset + K > self.max_seq or max_tokens - emitted < 2:
                remaining = max_tokens - emitted

                def dispatch(carry):
                    outs, tk, ch, rc, kk = t._decode_block(
                        t.params, carry[0], carry[1], carry[2], carry[3],
                        sp, False,
                    )
                    return outs, (tk, ch, rc, kk)

                from mlx_sharding_tpu.generate import blocked_token_stream

                yield from blocked_token_stream(
                    dispatch, (tok, cache, recent, key), remaining,
                    t.decode_block, False,
                )
                return

            drafts_np, n_valid = self.proposer.propose(history, K)
            wc = min(K, max(1, n_valid))
            wcap = jnp.asarray([wc], jnp.int32)
            drafts = jnp.asarray(drafts_np[:, None])  # (K, 1)
            if sampled:
                key, kv = jax.random.split(key)
                gs, count, tok, cache, recent = self._sampled_prog(K)(
                    t.params, tok, drafts, wcap, cache, recent, kv, sp
                )
            else:
                gs, count, tok, cache, recent = self._greedy_prog(K)(
                    t.params, tok, drafts, wcap, cache, recent, sp
                )
            n, gs_host = int(count[0]), np.asarray(gs)
            self.rounds += 1
            if w > 0:
                # disabled rounds are plain decode in disguise — counting
                # their single token as "accepted" with zero draft tokens
                # would push accept_rate past 1.0
                self.accepted_tokens += n
                self.draft_tokens += wc
                self.tracker.observe(0, w, n)
            for j in range(n):
                if emitted >= max_tokens:
                    break
                yield int(gs_host[j, 0]), None
                history.append(int(gs_host[j, 0]))
                emitted += 1
            offset += n

    def spec_stats(self) -> dict:
        """CLI/telemetry summary of this stream's speculation outcome."""
        return {
            "mode": "ngram",
            "window_max": self.spec_window_max,
            "rounds": self.rounds,
            "draft_tokens": self.draft_tokens,
            "accepted_tokens": self.accepted_tokens,
            "accept_rate": self.accepted_tokens / max(1, self.draft_tokens),
            **self.tracker.stats(),
        }
