"""Speculative decoding with a draft model — exact greedy acceleration.

ROADMAP item: the reference has no speculation of any kind. A small draft
model proposes ``spec_k`` tokens per round; the target model scores all of
them in ONE T=K forward (prefill-shaped — MXU-efficient, unlike K
sequential matvecs) and the longest prefix the target agrees with is
emitted, plus the target's own correction token at the first divergence.
Every emitted token is exactly what plain greedy decode would produce —
whatever the draft's quality, only throughput changes, never content
(tested token-exact in tests/test_speculative.py).

The TPU-shaped part is the rollback: this framework's caches derive
validity from the offset (rows past it are never attended and are
overwritten in place), so rejecting draft tokens costs ONE scalar — set
``offset = verified_prefix_end`` — no copying, no paging, no mask
rebuild. The draft model keeps its own cache and rewinds the same way.

Greedy requests (temperature == 0 — the serving default) use exact prefix
acceptance: every emitted token is what plain greedy decode would produce.
Sampled requests (temperature > 0) use REJECTION SAMPLING (Leviathan et
al.): the draft SAMPLES its proposals and records its distribution q_i;
the target's one T=K forward yields p_i; proposal d is accepted with
probability min(1, p_i(d)/q_i(d)), and the first rejection resamples from
the residual norm(max(p_i - q_i, 0)). The emitted stream is distributed
EXACTLY as plain sampling from the target (tested distributionally in
tests/test_speculative.py) — the draft only changes throughput, never the
distribution. Both p and q are the fully-transformed distributions
(logit_bias, repetition penalty over an exactly-evolved window,
temperature, top-p nucleus), so speculation composes with every sampler
knob; the token streams differ from non-speculative sampling for the same
seed (the PRNG is consumed differently), which is inherent to the method.
"""

from __future__ import annotations

from typing import Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np

from mlx_sharding_tpu.generate import (
    REPETITION_WINDOW,
    Generator,
    TokenLogprobs,
)
from mlx_sharding_tpu.sample import (
    init_recent_tokens,
    make_sampler_params,
    nucleus_logits,
    sample_token,
    transform_logits,
    update_recent_tokens,
)


def _dist_logits(logits, recent, sp):
    """The request's full sampling distribution in log domain (unnormalized),
    via the SAME pipeline sample_token samples from (sample.py
    transform_logits → nucleus_logits) — p and q below are both defined by
    it, which is what makes the acceptance ratio meaningful."""
    return nucleus_logits(transform_logits(logits, recent, sp), sp)


def rejection_round(key, drafts, q_logprobs, p_logprobs):
    """One round of speculative rejection sampling (pure math, jit-safe).

    drafts: (K, B) proposals; q_logprobs / p_logprobs: (K, B, V) draft and
    target log-distributions at each slot. Returns (gs, m, count):
    gs (K, B) — per-slot emitted token (draft token where accepted, the
    residual resample where rejected; only slots ≤ m are meaningful),
    m (B,) — last emitted slot, count (B,) = m + 1.

    Guarantee (the Leviathan et al. identity, unit-tested directly): the
    token emitted at a slot is distributed exactly as p at that slot."""
    K, B = drafts.shape
    k_u, k_res = jax.random.split(key)
    u = jax.random.uniform(k_u, (K, B))
    d_lp_q = jnp.take_along_axis(
        q_logprobs, drafts[..., None], axis=-1
    )[..., 0]  # (K, B)
    d_lp_p = jnp.take_along_axis(
        p_logprobs, drafts[..., None], axis=-1
    )[..., 0]
    # accept with prob min(1, p/q); exp of a clamped-to-0 log ratio avoids
    # overflow and u < 1 makes ratio >= 1 an unconditional accept
    accept = u < jnp.exp(jnp.minimum(d_lp_p - d_lp_q, 0.0))
    reject = ~accept

    # residual distribution per slot: norm(max(p - q, 0)); if its mass is
    # ~0 (p ≈ q everywhere) resampling from p is the correct limit
    p = jnp.exp(p_logprobs)
    q = jnp.exp(q_logprobs)
    res = jnp.maximum(p - q, 0.0)
    mass = res.sum(axis=-1, keepdims=True)
    res_logits = jnp.where(mass > 1e-9, jnp.log(res), p_logprobs)
    r = jax.vmap(jax.random.categorical)(
        jax.random.split(k_res, K), res_logits
    ).astype(jnp.int32)  # (K, B)

    gs = jnp.where(reject, r, drafts)
    any_rej = reject.any(axis=0)
    first = jnp.argmax(reject, axis=0)
    m = jnp.where(any_rej, first, K - 1)
    return gs, m, (m + 1).astype(jnp.int32)


class SpeculativeGenerator:
    """``generate_step`` contract over a (target, draft) model pair.

    Holds two plain Generators (their prefill/sample programs are reused
    verbatim) plus two speculation programs: the draft's K-step greedy
    scan and the target's fused verify (T=K forward + transform-aware
    acceptance)."""

    def __init__(
        self,
        model,
        params,
        draft_model,
        draft_params,
        *,
        spec_k: int = 4,
        max_seq: int = 4096,
        cache_dtype=jnp.bfloat16,
        prefill_chunk: int = 256,
        decode_block: int = 16,
    ):
        if spec_k < 1:
            raise ValueError(f"spec_k must be >= 1, got {spec_k}")
        tv = getattr(model.config, "vocab_size", None)
        dv = getattr(draft_model.config, "vocab_size", None)
        if tv != dv:
            # a mismatched pair would silently emit clamped-index garbage:
            # draft token ids index the target's embedding/logprob rows
            raise ValueError(
                f"draft vocab ({dv}) must match target vocab ({tv}) — "
                "speculation exchanges raw token ids between the models"
            )
        if not (model.config.is_first_stage and model.config.is_last_stage):
            raise ValueError(
                "speculative decoding needs the FULL model on one program "
                "(no start/end-layer stage slice)"
            )
        self.spec_k = spec_k
        # acceptance telemetry: tokens emitted per verify round averages
        # between 1 (draft never agrees) and K (always agrees)
        self.rounds = 0
        self.accepted_tokens = 0
        self.target = Generator(
            model, params, max_seq=max_seq, cache_dtype=cache_dtype,
            prefill_chunk=prefill_chunk, decode_block=decode_block,
        )
        self.draft = Generator(
            draft_model, draft_params, max_seq=max_seq,
            cache_dtype=cache_dtype, prefill_chunk=prefill_chunk,
        )
        self.max_seq = self.target.max_seq

        K = spec_k

        def draft_block_fn(dparams, token, dcache):
            """K greedy draft proposals (plain argmax — transforms live on
            the verify side where exactness is decided)."""

            def step(carry, _):
                tok, dcache = carry
                logits, dcache = draft_model(dparams, tok[:, None], dcache)
                tok = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
                return (tok, dcache), tok

            (_, dcache), drafts = jax.lax.scan(
                step, (token, dcache), None, length=K
            )
            return drafts, dcache  # drafts (K, B)

        def finish_round(gs, m, count, off0, cache, recent):
            """Shared verify epilogue (greedy and rejection-sampled rounds):
            replay ONLY the emitted tokens into the recent window, keep
            exactly the verified prefix in the cache (gs[m] is the next
            feed token and is NOT cached), return the round tuple."""

            def replay(carry, i):
                recent = carry
                upd = update_recent_tokens(recent, gs[i])
                return jnp.where((i <= m)[:, None], upd, recent), None

            recent, _ = jax.lax.scan(replay, recent, jnp.arange(K))
            cache = cache._replace(offset=off0 + count[0])
            return gs, count, gs[m[0]], cache, recent

        def verify_fn(params, token, drafts, cache, recent, sp):
            """One target forward over [t0, d1..d_{K-1}] scores every draft
            position; acceptance walks the agreement prefix. Returns the
            emitted tokens (K, B; rows past ``count`` are garbage), the
            count, the next feed token, and state rewound to the verified
            prefix."""
            b = token.shape[0]
            x = jnp.concatenate([token[:, None], drafts[:-1].T], axis=1)  # (B, K)
            off0 = cache.offset
            logits, cache = model(params, x, cache)  # (B, K, V)
            zero_key = jax.random.PRNGKey(0)  # unused at temperature 0

            def score(carry, i):
                recent = carry
                g, _ = sample_token(zero_key, logits[:, i], sp, recent)
                recent = update_recent_tokens(recent, g)
                return recent, g

            _, gs = jax.lax.scan(score, recent, jnp.arange(K))  # (K, B)

            mism = gs != drafts  # position i: target's g_i vs proposal d_{i+1}
            any_mism = mism.any(axis=0)  # (B,)
            first = jnp.argmax(mism, axis=0)  # first True (0 if none)
            m = jnp.where(any_mism, first, K - 1)
            count = (m + 1).astype(jnp.int32)  # tokens emitted this round
            return finish_round(gs, m, count, off0, cache, recent)

        def draft_sampled_fn(dparams, token, dcache, recent, keys, sp):
            """K sampled draft proposals + the exact distribution each was
            drawn from (q_i log rows — the acceptance denominator). The
            draft sees the target's true recent window and evolves a local
            copy with its own proposals."""

            def step(carry, key_i):
                tok, dcache, recent = carry
                logits, dcache = draft_model(dparams, tok[:, None], dcache)
                f = _dist_logits(logits[:, -1], recent, sp)
                qlp = jax.nn.log_softmax(f, axis=-1)
                tok = jax.random.categorical(key_i, f, axis=-1).astype(
                    jnp.int32
                )
                recent = update_recent_tokens(recent, tok)
                return (tok, dcache, recent), (tok, qlp)

            (_, dcache, _), (drafts, qlps) = jax.lax.scan(
                step, (token, dcache, recent), keys
            )
            return drafts, qlps, dcache  # (K, B), (K, B, V)

        def verify_sampled_fn(params, token, drafts, qlps, cache, recent,
                              key, sp):
            """Target T=K forward + rejection sampling. Same bookkeeping as
            the greedy verify: gs[m] is the next feed token and is NOT in
            the cache; offset keeps exactly the verified prefix."""
            x = jnp.concatenate([token[:, None], drafts[:-1].T], axis=1)
            off0 = cache.offset
            logits, cache = model(params, x, cache)  # (B, K, V)

            def score(carry, i):
                recent = carry
                f = _dist_logits(logits[:, i], recent, sp)
                plp = jax.nn.log_softmax(f, axis=-1)
                # the consumed token at slot i+1 is drafts[i]; evolving with
                # it is exact on the accepted prefix (discarded past it)
                recent = update_recent_tokens(recent, drafts[i])
                return recent, plp

            _, plps = jax.lax.scan(score, recent, jnp.arange(K))  # (K, B, V)
            gs, m, count = rejection_round(key, drafts, qlps, plps)
            return finish_round(gs, m, count, off0, cache, recent)

        self._draft_block = jax.jit(draft_block_fn, donate_argnums=(2,))
        self._verify = jax.jit(verify_fn, donate_argnums=(3, 4))
        self._draft_sampled = jax.jit(draft_sampled_fn, donate_argnums=(2,))
        self._verify_sampled = jax.jit(
            verify_sampled_fn, donate_argnums=(4, 5)
        )
        self._rewind = jax.jit(
            lambda c, off: c._replace(offset=off), donate_argnums=(0,)
        )

    # ------------------------------------------------------------------
    def generate_step(
        self,
        prompt_tokens,
        *,
        temperature: float = 0.0,
        top_p: float = 1.0,
        repetition_penalty: Optional[float] = None,
        repetition_context_size: int = REPETITION_WINDOW,
        logit_bias: Optional[dict[int, float]] = None,
        seed: Optional[int] = None,
        max_tokens: int = 256,
        want_logprobs: bool = False,
    ) -> Iterator[tuple[int, Optional[TokenLogprobs]]]:
        if want_logprobs:
            # logprobs need per-token summaries the verify path doesn't
            # compute — take the exact normal path
            yield from self.target.generate_step(
                prompt_tokens, temperature=temperature, top_p=top_p,
                repetition_penalty=repetition_penalty,
                repetition_context_size=repetition_context_size,
                logit_bias=logit_bias, seed=seed, max_tokens=max_tokens,
                want_logprobs=want_logprobs,
            )
            return

        sampled = temperature > 0
        sp = make_sampler_params(
            temperature, top_p, repetition_penalty, logit_bias
        )
        prompt = np.asarray(prompt_tokens, np.int32).reshape(
            self.target.batch, -1
        )
        n_prompt = prompt.shape[1]
        if n_prompt + max_tokens > self.max_seq:
            raise ValueError(
                f"prompt ({n_prompt}) + max_tokens ({max_tokens}) exceeds KV "
                f"capacity {self.max_seq}"
            )

        import time as _time

        t = self.target
        cache = t.model.make_cache(t.batch, t.max_seq, t.cache_dtype)
        recent = init_recent_tokens(t.batch, repetition_context_size, prompt)
        key = jax.random.PRNGKey(
            int(_time.time_ns()) & 0x7FFFFFFF if seed is None else seed
        )

        last_logits, cache = t.run_prefill(prompt, cache)
        # draft prefills the same prompt into its own cache
        d = self.draft
        dcache = d.model.make_cache(d.batch, d.max_seq, d.cache_dtype)
        _, dcache = d.run_prefill(prompt, dcache)

        tok, logprobs, recent, key = t._sample(last_logits, recent, key, sp)
        yield int(tok[0]), None
        emitted = 1
        # the first emitted token's row is in NEITHER cache: both models
        # consume it as the next round's feed token, exactly like normal
        # decode. ``offset`` mirrors cache.offset on host for the capacity
        # check (it grows by the accepted count each round).
        offset = n_prompt
        K = self.spec_k
        while emitted < max_tokens:
            if offset + K > self.max_seq or max_tokens - emitted < 2:
                # tail (or capacity edge): plain blocked decode from here
                remaining = max_tokens - emitted

                def dispatch(carry):
                    outs, tk, ch, rc, kk = t._decode_block(
                        t.params, carry[0], carry[1], carry[2], carry[3],
                        sp, False,
                    )
                    return outs, (tk, ch, rc, kk)

                from mlx_sharding_tpu.generate import blocked_token_stream

                yield from blocked_token_stream(
                    dispatch, (tok, cache, recent, key), remaining,
                    t.decode_block, False,
                )
                return

            if sampled:
                key, kd, kv = jax.random.split(key, 3)
                drafts, qlps, dcache = self._draft_sampled(
                    d.params, tok, dcache, recent, jax.random.split(kd, K), sp
                )
                gs, count, tok, cache, recent = self._verify_sampled(
                    t.params, tok, drafts, qlps, cache, recent, kv, sp
                )
            else:
                drafts, dcache = self._draft_block(d.params, tok, dcache)
                gs, count, tok, cache, recent = self._verify(
                    t.params, tok, drafts, cache, recent, sp
                )
            n, gs_host = int(count[0]), np.asarray(gs)
            self.rounds += 1
            self.accepted_tokens += n
            # draft consumed [t0, d1..d_{K-1}] = K rows; keep the verified
            # prefix (the accepted tokens ARE the draft's inputs there)
            dcache = self._rewind(
                dcache, dcache.offset - K + jnp.asarray(n, jnp.int32)
            )
            for j in range(n):
                if emitted >= max_tokens:
                    break
                yield int(gs_host[j, 0]), None
                emitted += 1
            offset += n
