"""Tracing / profiling / metrics.

The reference has none of this — ad-hoc prints on the shard server and a
tok/s printout in the CLI are its entire observability story (SURVEY §5
"Tracing/profiling: None"). Here:

- :func:`profile_trace` wraps the JAX profiler (TensorBoard-loadable traces
  of XLA execution, including per-op TPU timing) around any generation call;
- :class:`ServingMetrics` is a lock-guarded counter set the API server
  exposes at ``/metrics`` — request counts, token throughput, TTFT and
  decode-rate summaries (p50/p95 from a bounded reservoir).
"""

from __future__ import annotations

import bisect
import contextlib
import random
import threading
from dataclasses import dataclass, field

from mlx_sharding_tpu.analysis.runtime import make_lock

# Shared bucket boundaries. Chosen to straddle both the CPU smoke rig
# (ms-scale ticks) and real-chip serving points; the +Inf bucket is
# implicit (the histogram's last slot).
LATENCY_BUCKETS_S = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                     0.5, 1.0, 2.5, 5.0, 10.0, 30.0)
ITL_BUCKETS_S = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                 0.25, 0.5, 1.0)
HANDOFF_BUCKETS_MS = (0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                      500.0, 1000.0)


@contextlib.contextmanager
def profile_trace(log_dir: str | None):
    """JAX profiler trace context; no-op when log_dir is falsy."""
    if not log_dir:
        yield
        return
    import jax

    with jax.profiler.trace(log_dir):
        yield


class _Reservoir:
    """Bounded uniform sample for percentile summaries."""

    def __init__(self, capacity: int = 512, seed: int = 0):
        self.capacity = capacity
        self.values: list[float] = []
        self.count = 0
        self._rng = random.Random(seed)

    def add(self, value: float):
        self.count += 1
        if len(self.values) < self.capacity:
            self.values.append(value)
        else:
            j = self._rng.randrange(self.count)
            if j < self.capacity:
                self.values[j] = value

    def percentile(self, p: float) -> float:
        if not self.values:
            return 0.0
        s = sorted(self.values)
        idx = min(len(s) - 1, max(0, round(p / 100 * (len(s) - 1))))
        return s[idx]


class Histogram:
    """Cumulative bucketed histogram — the Prometheus ``_bucket{le=}`` /
    ``_sum`` / ``_count`` exposition shape. Unlike the reservoir summaries
    (whose quantiles cannot be combined), bucket counts aggregate exactly:
    merging replicas or successive scrapes is elementwise addition, which
    is why the latency families that matter (TTFT, ITL, queue wait,
    handoff) live here and not in :class:`_Reservoir`."""

    __slots__ = ("_bounds", "_counts", "_sum", "_count", "_lock")

    def __init__(self, bounds, lock_name: str = "Histogram._lock"):
        self._bounds = tuple(float(b) for b in bounds)
        self._counts = [0] * (len(self._bounds) + 1)  # last slot = +Inf
        self._sum = 0.0
        self._count = 0
        self._lock = make_lock(lock_name)

    def observe(self, value: float):
        v = float(value)
        with self._lock:
            # bisect_left: first bound >= v, i.e. the smallest le bucket
            # containing v; beyond every bound lands in the +Inf slot
            self._counts[bisect.bisect_left(self._bounds, v)] += 1
            self._sum += v
            self._count += 1

    def to_dict(self) -> dict:
        """Serializable snapshot — the cross-replica aggregation currency
        (``latency_stats()`` contracts pass these, never live objects)."""
        with self._lock:
            return {
                "bounds": list(self._bounds),
                "counts": list(self._counts),
                "sum": self._sum,
                "count": self._count,
            }

    @staticmethod
    def merge_dicts(dicts) -> dict | None:
        """Elementwise merge of :meth:`to_dict` snapshots. Snapshots with
        mismatched bounds are skipped (a mixed-version fleet must degrade,
        not crash a scrape)."""
        out = None
        for d in dicts:
            if not d or "counts" not in d:
                continue
            if out is None:
                out = {
                    "bounds": list(d["bounds"]),
                    "counts": list(d["counts"]),
                    "sum": float(d["sum"]),
                    "count": int(d["count"]),
                }
            elif list(d["bounds"]) == out["bounds"]:
                out["counts"] = [a + b for a, b in
                                 zip(out["counts"], d["counts"])]
                out["sum"] += float(d["sum"])
                out["count"] += int(d["count"])
        return out

    @staticmethod
    def render_into(lines: list, family: str, snap: dict | None,
                    help_text: str = ""):
        """Append one family's exposition block from a :meth:`to_dict`
        snapshot (no-op when the snapshot is absent/malformed)."""
        if not snap or "counts" not in snap:
            return
        if help_text:
            lines.append(f"# HELP {family} {help_text}")
        lines.append(f"# TYPE {family} histogram")
        acc = 0
        for bound, n in zip(snap["bounds"], snap["counts"]):
            acc += n
            lines.append(f'{family}_bucket{{le="{bound:g}"}} {acc}')
        acc += snap["counts"][-1]
        lines.append(f'{family}_bucket{{le="+Inf"}} {acc}')
        lines.append(f"{family}_sum {snap['sum']:.6f}")
        lines.append(f"{family}_count {snap['count']}")


def _render_spec_family(lines: list, spec: dict):
    """Append the adaptive-speculation gauge family from a ``spec_stats()``
    dict: whether this generator drafts at all, how wide, and whether it
    pays (accept_rate = accepted / drafted). Never rendered as zeros on a
    non-speculating host — callers gate on ``spec is not None``."""
    lines += [
        "# TYPE mst_spec_enabled gauge",
        f'mst_spec_enabled{{mode="{spec["mode"]}"}} 1',
        "# TYPE mst_spec_window gauge",
        f"mst_spec_window {spec.get('window_max', 0)}",
        "# TYPE mst_spec_accept_rate gauge",
        f"mst_spec_accept_rate "
        f"{spec.get('accept_rate', 0.0):.4f}",
        "# TYPE mst_spec_draft_tokens_total counter",
        f"mst_spec_draft_tokens_total "
        f"{spec.get('draft_tokens', 0)}",
        "# TYPE mst_spec_accepted_tokens_total counter",
        f"mst_spec_accepted_tokens_total "
        f"{spec.get('accepted_tokens', 0)}",
        "# TYPE mst_spec_rounds_total counter",
        f"mst_spec_rounds_total {spec.get('rounds', 0)}",
        "# TYPE mst_spec_fallback_ticks_total counter",
        f"mst_spec_fallback_ticks_total "
        f"{spec.get('fallback_ticks', 0)}",
        "# TYPE mst_spec_draft_faults_total counter",
        f"mst_spec_draft_faults_total "
        f"{spec.get('draft_faults', 0)}",
    ]
    if "disabled_slots" in spec:
        # per-slot adaptive control only (tracker-backed)
        lines += [
            "# TYPE mst_spec_disabled_slots gauge",
            f"mst_spec_disabled_slots "
            f"{spec['disabled_slots']}",
            "# TYPE mst_spec_shed_events_total counter",
            f"mst_spec_shed_events_total "
            f"{spec['shed_events']}",
        ]


@dataclass
class ServingMetrics:
    # named lock (ordering: ServingMetrics.lock is taken BEFORE any engine
    # lock — render() calls the engine's locked accessors while holding it)
    lock: threading.Lock = field(
        default_factory=lambda: make_lock("ServingMetrics.lock")
    )
    requests_total: int = 0
    requests_failed: int = 0
    prompt_tokens_total: int = 0
    generation_tokens_total: int = 0
    ttft_s: _Reservoir = field(default_factory=_Reservoir)
    decode_tps: _Reservoir = field(default_factory=_Reservoir)
    # bucketed TTFT (the reservoir stays for operator-facing quantiles in
    # logs; the histogram is what aggregates across replicas and scrapes)
    ttft_hist: Histogram = field(
        default_factory=lambda: Histogram(
            LATENCY_BUCKETS_S, "ServingMetrics.ttft_hist"
        )
    )
    # zero-arg callable returning the live ContinuousBatcher (or None) —
    # a callable so model hot-swaps can never leave a stale reference
    batcher_fn: object = None
    # zero-arg callable returning the live SpeculativeGenerator (or None)
    spec_fn: object = None
    # zero-arg callable returning the host's weights.WeightStore (or None);
    # defaults to the module singleton at render time so the shared-weights
    # gauges exist even for servers built without make_server
    weight_store_fn: object = None
    # zero-arg callable returning the live prefix_store.PrefixStore (or
    # None) — callable for the same hot-swap reason as batcher_fn
    prefix_store_fn: object = None
    # zero-arg callable returning pod.PodFleet.pod_stats() (or None) —
    # None on every single-host deployment, which keeps the single-host
    # exposition byte-identical (no host labels, no pod families)
    pod_stats_fn: object = None
    # zero-arg callable returning the layer-wise KV sharing summary
    # (kv_share.py; provider.kv_share_stats()) or None when no share map
    # is configured — unset keeps the exposition free of share families
    kv_share_fn: object = None
    # zero-arg callable returning the compressed-latent KV transport
    # summary (kv_compress.py; provider.kv_compress_stats()) or None when
    # no codec is active — unset keeps compress families absent
    kv_compress_fn: object = None

    def record_request(
        self,
        *,
        prompt_tokens: int,
        generation_tokens: int,
        ttft_s: float,
        decode_tps: float,
        failed: bool = False,
    ):
        with self.lock:
            self.requests_total += 1
            if failed:
                self.requests_failed += 1
            self.prompt_tokens_total += prompt_tokens
            self.generation_tokens_total += generation_tokens
            if ttft_s > 0:
                self.ttft_s.add(ttft_s)
                self.ttft_hist.observe(ttft_s)
            if decode_tps > 0:
                self.decode_tps.add(decode_tps)

    def record_failure(self):
        with self.lock:
            self.requests_total += 1
            self.requests_failed += 1

    def render(self) -> str:
        """Prometheus text exposition."""
        with self.lock:
            lines = [
                "# TYPE mst_requests_total counter",
                f"mst_requests_total {self.requests_total}",
                "# TYPE mst_requests_failed_total counter",
                f"mst_requests_failed_total {self.requests_failed}",
                "# TYPE mst_prompt_tokens_total counter",
                f"mst_prompt_tokens_total {self.prompt_tokens_total}",
                "# TYPE mst_generation_tokens_total counter",
                f"mst_generation_tokens_total {self.generation_tokens_total}",
                "# TYPE mst_decode_tokens_per_second summary",
                f'mst_decode_tokens_per_second{{quantile="0.5"}} {self.decode_tps.percentile(50):.3f}',
                f'mst_decode_tokens_per_second{{quantile="0.95"}} {self.decode_tps.percentile(95):.3f}',
            ]
            # TTFT as a cumulative histogram (was a two-point summary):
            # bucket counts sum across replicas; quantiles never did
            Histogram.render_into(
                lines, "mst_ttft_seconds", self.ttft_hist.to_dict()
            )
            # fault-harness visibility: a fault left ARMED in a live
            # deployment (forgotten MST_FAULTS, a chaos campaign that
            # didn't disarm) must show on every scrape, as must specs
            # dropped at parse time. Lazy import + never-500, same as the
            # engine sections below.
            fmark = len(lines)
            try:
                from mlx_sharding_tpu.testing import faults as _faults

                lines += [
                    "# TYPE mst_faults_malformed_total counter",
                    f"mst_faults_malformed_total {_faults.malformed_total()}",
                    "# TYPE mst_faults_armed gauge",
                ]
                armed = _faults.armed_sites()
                if armed:
                    lines += [
                        f'mst_faults_armed{{site="{site}"}} {n}'
                        for site, n in sorted(armed.items())
                    ]
                else:
                    # a bare # TYPE with no sample is invalid exposition —
                    # the disarmed steady state is an explicit zero
                    lines.append("mst_faults_armed 0")
            except Exception:  # noqa: BLE001 — scrape must not 500
                del lines[fmark:]
            # leak-ledger health: the bounded anomaly ring keeps only the
            # newest entries, this counter keeps the true total (zero when
            # no ledger is instrumented — the production steady state)
            lmark = len(lines)
            try:
                from mlx_sharding_tpu.analysis import runtime as _rt

                led = _rt._RESOURCES
                lines += [
                    "# TYPE mst_ledger_anomalies_total counter",
                    "mst_ledger_anomalies_total "
                    f"{led.anomalies_total if led is not None else 0}",
                ]
            except Exception:  # noqa: BLE001 — scrape must not 500
                del lines[lmark:]
            # any engine accessor can die mid-scrape (replica torn
            # down, pool closing); drop the whole engine section
            # cleanly rather than 500 or emit a half-rendered family
            mark = len(lines)
            spec_rendered = False
            try:
                b = self.batcher_fn() if self.batcher_fn is not None else None
                if b is not None:
                    slots, active, queued = b.stats()
                    lines += [
                        "# TYPE mst_batch_slots gauge",
                        f"mst_batch_slots {slots}",
                        "# TYPE mst_batch_slots_active gauge",
                        f"mst_batch_slots_active {active}",
                        "# TYPE mst_batch_queue_depth gauge",
                        f"mst_batch_queue_depth {queued}",
                    ]
                    pages = getattr(b, "page_stats", lambda: None)()
                    if pages is not None:
                        total, in_use, high = pages
                        lines += [
                            "# TYPE mst_kv_pool_pages gauge",
                            f"mst_kv_pool_pages {total}",
                            "# TYPE mst_kv_pool_pages_in_use gauge",
                            f"mst_kv_pool_pages_in_use {in_use}",
                            "# TYPE mst_kv_pool_pages_high_water gauge",
                            f"mst_kv_pool_pages_high_water {high}",
                        ]
                    if pages is not None and getattr(b, "overcommit", False):
                        lines += [
                            "# TYPE mst_preemptions_total counter",
                            f"mst_preemptions_total {b.preemptions}",
                        ]
                    spill = getattr(b, "spill_stats", lambda: None)()
                    if spill is not None:
                        # KV migration story: how often memory pressure / drain
                        # moved page blocks instead of discarding them, and how
                        # much host DRAM the spill tier is holding
                        lines += [
                            "# TYPE mst_kv_spill_enabled gauge",
                            f"mst_kv_spill_enabled {int(bool(spill['enabled']))}",
                            "# TYPE mst_kv_spill_total counter",
                            f"mst_kv_spill_total {spill['spills']}",
                            "# TYPE mst_kv_spill_hits_total counter",
                            f"mst_kv_spill_hits_total {spill['spill_hits']}",
                            "# TYPE mst_kv_spill_fallbacks_total counter",
                            f"mst_kv_spill_fallbacks_total "
                            f"{spill['spill_fallbacks']}",
                            "# TYPE mst_kv_spill_evictions_total counter",
                            f"mst_kv_spill_evictions_total {spill['evictions']}",
                            "# TYPE mst_kv_spill_bytes gauge",
                            f"mst_kv_spill_bytes {spill['bytes_in_use']}",
                            "# TYPE mst_kv_spill_budget_bytes gauge",
                            f"mst_kv_spill_budget_bytes {spill['budget_bytes']}",
                            "# TYPE mst_kv_migration_out_total counter",
                            f"mst_kv_migration_out_total "
                            f"{spill['migrations_out']}",
                            "# TYPE mst_kv_migration_in_total counter",
                            f"mst_kv_migration_in_total {spill['migrations_in']}",
                            "# TYPE mst_kv_reprefill_tokens_total counter",
                            f"mst_kv_reprefill_tokens_total "
                            f"{spill['reprefill_tokens']}",
                            # proactive residency: cold-policy activity, tier
                            # lookup quality, and the overlapped-vs-demand
                            # resume split (.get: ReplicaSet aggregation may
                            # predate these keys)
                            "# TYPE mst_kv_spill_cold_total counter",
                            f"mst_kv_spill_cold_total "
                            f"{spill.get('cold_spills', 0)}",
                            "# TYPE mst_kv_spill_wakes_total counter",
                            f"mst_kv_spill_wakes_total "
                            f"{spill.get('cold_wakes', 0)}",
                            "# TYPE mst_kv_spill_parked gauge",
                            f"mst_kv_spill_parked {spill.get('parked', 0)}",
                            "# TYPE mst_kv_spill_hit_rate gauge",
                            f"mst_kv_spill_hit_rate "
                            f"{spill.get('hit_rate', 0.0):.4f}",
                            "# TYPE mst_kv_spill_rejects_total counter",
                            f'mst_kv_spill_rejects_total{{reason="oversize"}} '
                            f"{spill.get('rejects_oversize', 0)}",
                            f'mst_kv_spill_rejects_total{{reason="closed"}} '
                            f"{spill.get('rejects_closed', 0)}",
                            "# TYPE mst_kv_prefetch_enabled gauge",
                            f"mst_kv_prefetch_enabled "
                            f"{int(bool(spill.get('prefetch_enabled', False)))}",
                            "# TYPE mst_kv_prefetch_total counter",
                            f"mst_kv_prefetch_total "
                            f"{spill.get('prefetches', 0)}",
                            "# TYPE mst_kv_prefetch_hits_total counter",
                            f"mst_kv_prefetch_hits_total "
                            f"{spill.get('prefetch_hits', 0)}",
                            "# TYPE mst_kv_prefetch_demand_total counter",
                            f"mst_kv_prefetch_demand_total "
                            f"{spill.get('demand_imports', 0)}",
                            "# TYPE mst_kv_prefetch_faults_total counter",
                            f"mst_kv_prefetch_faults_total "
                            f"{spill.get('prefetch_faults', 0)}",
                        ]
                        if "migrated_streams" in spill:
                            # ReplicaSet-level: streams re-placed across
                            # replicas after a drain or mid-stream crash
                            lines += [
                                "# TYPE mst_kv_migration_streams_total counter",
                                f"mst_kv_migration_streams_total "
                                f"{spill['migrated_streams']}",
                            ]
                    kv = getattr(b, "kv_read_stats", lambda: None)()
                    if kv is not None:
                        path, last_tick, total_bytes = kv
                        lines += [
                            # 1 = ragged in-place paged attention, 0 = the
                            # gather/scatter path — which kernel decode is on
                            "# TYPE mst_paged_attention_ragged gauge",
                            f"mst_paged_attention_ragged {int(path == 'ragged')}",
                            "# TYPE mst_kv_bytes_read_last_tick gauge",
                            f"mst_kv_bytes_read_last_tick {last_tick}",
                            "# TYPE mst_kv_bytes_read_total counter",
                            f"mst_kv_bytes_read_total {total_bytes}",
                        ]
                    hbm = getattr(b, "hbm_bytes_per_token_stats", lambda: None)()
                    if hbm is not None:
                        lines += [
                            "# TYPE mst_decode_hbm_bytes_per_token gauge",
                            'mst_decode_hbm_bytes_per_token{kind="weights"} '
                            f"{hbm['weights']:.1f}",
                            'mst_decode_hbm_bytes_per_token{kind="kv"} '
                            f"{hbm['kv']:.1f}",
                        ]
                    lat = getattr(b, "latency_stats", lambda: None)()
                    if lat is not None:
                        # scheduler-side per-token latency: inter-token gaps
                        # from the emit path, queue wait from submit→slot.
                        # Histograms so ReplicaSet/Disagg merges stay exact.
                        Histogram.render_into(
                            lines, "mst_itl_seconds", lat.get("itl")
                        )
                        Histogram.render_into(
                            lines, "mst_queue_wait_seconds",
                            lat.get("queue_wait")
                        )
                    tick = getattr(b, "tick_timing_stats", lambda: None)()
                    if tick is not None:
                        # which run-loop the batcher is on (1 = double-buffered
                        # async pipeline, 0 = classic dispatch-then-harvest) and
                        # where each tick's wall time went: blocked on the
                        # harvest device_get vs. doing host-side scheduling work
                        path = tick["path"]
                        lines += [
                            "# TYPE mst_sched_async gauge",
                            f"mst_sched_async {int(path == 'async')}",
                            "# TYPE mst_tick_host_ms gauge",
                            f'mst_tick_host_ms{{path="{path}"}} '
                            f"{tick['host_ms_last']:.3f}",
                            "# TYPE mst_tick_device_blocked_ms gauge",
                            f'mst_tick_device_blocked_ms{{path="{path}"}} '
                            f"{tick['device_blocked_ms_last']:.3f}",
                            # resume-path import stall: ~0 when prefetch staged
                            # the pages, the full host→device marshal on demand
                            f'mst_tick_device_blocked_ms{{path="kv_import"}} '
                            f"{tick.get('kv_import_ms_last', 0.0):.3f}",
                        ]
                    spec = getattr(b, "spec_stats", lambda: None)()
                    if spec is not None:
                        _render_spec_family(lines, spec)
                        spec_rendered = True
                    res = getattr(b, "resilience_stats", lambda: None)()
                    if res is not None:
                        lines += [
                            "# TYPE mst_requests_timeout_total counter",
                            f"mst_requests_timeout_total {res['timeouts']}",
                            # shed = rejected before any engine work was spent:
                            # queue_full at admission (429), deadline while queued
                            "# TYPE mst_requests_shed_total counter",
                            f'mst_requests_shed_total{{reason="queue_full"}} '
                            f"{res['shed_queue_full']}",
                            f'mst_requests_shed_total{{reason="deadline"}} '
                            f"{res['shed_deadline']}",
                            "# TYPE mst_scheduler_thread_live gauge",
                            "mst_scheduler_thread_live "
                            f"{int(bool(res['scheduler_thread_live']))}",
                        ]
                        if res.get("max_queue") is not None:
                            lines += [
                                "# TYPE mst_max_queue gauge",
                                f"mst_max_queue {res['max_queue']}",
                            ]
                    health = getattr(b, "health", lambda: None)()
                    if health is not None and "replicas_total" in health:
                        lines += [
                            "# TYPE mst_replicas_total gauge",
                            f"mst_replicas_total {health['replicas_total']}",
                            "# TYPE mst_replicas_live gauge",
                            f"mst_replicas_live {health['replicas_live']}",
                        ]
                        lines.append("# TYPE mst_replica_breaker_open gauge")
                        for rep in health["replicas"]:
                            lines += [
                                f'mst_replica_breaker_open{{replica="{rep["replica"]}"}} '
                                f"{int(rep['breaker'] != 'closed')}",
                            ]
                        lines.append("# TYPE mst_replica_failures_total counter")
                        for rep in health["replicas"]:
                            lines += [
                                f'mst_replica_failures_total{{replica="{rep["replica"]}"}} '
                                f"{rep['failures']}",
                            ]
                    # per-replica routing load + fleet elasticity (replicas.py /
                    # fleet.py); breaker_state: 0 closed, 1 half-open, 2 open
                    per_rep = getattr(b, "replica_stats", lambda: None)()
                    if per_rep is not None:
                        # disaggregated pools tag entries with a role; indices
                        # repeat across pools, so the role label is what keeps
                        # the gauge lines distinct (monolithic sets stay
                        # unlabeled — role is None there)
                        def _rl(rep):
                            role = rep.get("role")
                            return (
                                f'replica="{rep["replica"]}",role="{role}"'
                                if role else f'replica="{rep["replica"]}"'
                            )
                        lines.append("# TYPE mst_replica_inflight gauge")
                        for rep in per_rep:
                            lines.append(
                                f"mst_replica_inflight{{{_rl(rep)}}} "
                                f"{rep['inflight']}"
                            )
                        lines.append("# TYPE mst_replica_queue_depth gauge")
                        for rep in per_rep:
                            lines.append(
                                f"mst_replica_queue_depth{{{_rl(rep)}}} "
                                f"{rep['queue_depth']}"
                            )
                        lines.append("# TYPE mst_replica_breaker_state gauge")
                        for rep in per_rep:
                            lines.append(
                                f"mst_replica_breaker_state{{{_rl(rep)}}} "
                                f"{rep['breaker_state']}"
                            )
                        # 1 = this replica aliases the host's resident weight
                        # tree (weights.WeightStore), 0 = private upload
                        lines.append("# TYPE mst_replica_weights_shared gauge")
                        for rep in per_rep:
                            lines.append(
                                f"mst_replica_weights_shared{{{_rl(rep)}}} "
                                f"{int(bool(rep.get('weights_shared')))}"
                            )
                    fleet = getattr(b, "fleet_stats", lambda: None)()
                    if fleet is not None:
                        lines += [
                            "# TYPE mst_fleet_size gauge",
                            f"mst_fleet_size {fleet['size']}",
                        ]
                        for pool in fleet.get("pools", []):
                            # per-role pool sizes under the disagg coordinator
                            if pool.get("role"):
                                lines.append(
                                    f'mst_fleet_size{{role="{pool["role"]}"}} '
                                    f"{pool['size']}"
                                )
                        lines += [
                            "# TYPE mst_autoscale_events_total counter",
                        ]
                        for kind in sorted(fleet.get("autoscale_events", {})):
                            lines.append(
                                f'mst_autoscale_events_total{{kind="{kind}"}} '
                                f"{fleet['autoscale_events'][kind]}"
                            )
                        if "sticky_hits" in fleet:
                            lines += [
                                "# TYPE mst_route_sticky_hits_total counter",
                                f"mst_route_sticky_hits_total "
                                f"{fleet['sticky_hits']}",
                                "# TYPE mst_route_affinity_hits_total counter",
                                f"mst_route_affinity_hits_total "
                                f"{fleet['affinity_hits']}",
                            ]
                        if "store_hits" in fleet:
                            # routed to the replica already holding the prefix
                            # resident in the fleet-wide store
                            lines += [
                                "# TYPE mst_route_store_hits_total counter",
                                f"mst_route_store_hits_total "
                                f"{fleet['store_hits']}",
                            ]
                    hand = getattr(b, "handoff_stats", lambda: None)()
                    if hand is not None:
                        # disaggregated serving: prefill→decode KV handoffs —
                        # volume, shipped bytes, DMA+control latency, and how
                        # often the degradation ladder fired (by kind)
                        lines += [
                            "# TYPE mst_disagg_handoff_total counter",
                            f"mst_disagg_handoff_total {hand['handoffs']}",
                            "# TYPE mst_disagg_handoff_bytes_total counter",
                            f"mst_disagg_handoff_bytes_total "
                            f"{hand['bytes_total']}",
                        ]
                        if hand.get("ms_hist"):
                            # handoff latency as a histogram (bucket counts
                            # aggregate across coordinators and scrapes)
                            Histogram.render_into(
                                lines, "mst_disagg_handoff_ms", hand["ms_hist"]
                            )
                        else:
                            # a pre-histogram aggregation: keep the summary
                            lines += [
                                "# TYPE mst_disagg_handoff_ms summary",
                                'mst_disagg_handoff_ms{quantile="0.5"} '
                                f"{hand.get('ms_p50') or 0.0:.3f}",
                                'mst_disagg_handoff_ms{quantile="0.99"} '
                                f"{hand.get('ms_p99') or 0.0:.3f}",
                            ]
                        lines += [
                            "# TYPE mst_disagg_fallbacks_total counter",
                        ]
                        for kind in sorted(hand.get("fallbacks", {})):
                            lines.append(
                                f'mst_disagg_fallbacks_total{{kind="{kind}"}} '
                                f"{hand['fallbacks'][kind]}"
                            )
                        if "store_skips" in hand:
                            # full-prefix store hits that skipped the prefill
                            # pool entirely (no phase-1 dispatch, no handoff)
                            lines += [
                                "# TYPE mst_disagg_store_skips_total counter",
                                f"mst_disagg_store_skips_total "
                                f"{hand['store_skips']}",
                            ]
                    bro = getattr(b, "brownout", None)
                    if bro is not None:
                        lines += [
                            "# TYPE mst_brownout_level gauge",
                            f"mst_brownout_level {bro.level()}",
                        ]
                    prefix = getattr(b, "prefix_stats", lambda: None)()
                    if prefix is not None:
                        queries, hits, reused, evictions, cached = prefix
                        lines += [
                            "# TYPE mst_prefix_cache_queries_total counter",
                            f"mst_prefix_cache_queries_total {queries}",
                            "# TYPE mst_prefix_cache_hits_total counter",
                            f"mst_prefix_cache_hits_total {hits}",
                            "# TYPE mst_prefix_cache_tokens_reused_total counter",
                            f"mst_prefix_cache_tokens_reused_total {reused}",
                            "# TYPE mst_prefix_cache_evictions_total counter",
                            f"mst_prefix_cache_evictions_total {evictions}",
                            "# TYPE mst_prefix_cache_pages gauge",
                            f"mst_prefix_cache_pages {cached}",
                        ]
            except Exception:  # noqa: BLE001 — scrapes must never 500
                del lines[mark:]
            spec = (
                self.spec_fn()
                if self.spec_fn is not None and not spec_rendered
                else None
            )
            if spec is not None and hasattr(spec, "spec_stats"):
                # new-protocol generator (n-gram single-stream) hosted
                # without a batcher: same family, same never-500 contract
                smark = len(lines)
                try:
                    st = spec.spec_stats()
                    if st is not None:
                        _render_spec_family(lines, st)
                except Exception:  # noqa: BLE001 — scrapes must never 500
                    del lines[smark:]
            elif spec is not None:
                # accepted/round ∈ [1, spec_k]: the draft-quality dial the
                # operator watches to size --spec-k
                lines += [
                    "# TYPE mst_spec_rounds_total counter",
                    f"mst_spec_rounds_total {spec.rounds}",
                    "# TYPE mst_spec_tokens_accepted_total counter",
                    f"mst_spec_tokens_accepted_total {spec.accepted_tokens}",
                ]
                rounds = max(1, spec.rounds)
                lines += [
                    # accepted/rounds collapsing toward 1.0 with fallbacks
                    # climbing = the draft is stale or mismatched
                    "# TYPE mst_spec_acceptance_rate gauge",
                    f"mst_spec_acceptance_rate "
                    f"{spec.accepted_tokens / rounds:.4f}",
                    "# TYPE mst_spec_fallback_ticks_total counter",
                    f"mst_spec_fallback_ticks_total "
                    f"{getattr(spec, 'fallback_ticks', 0)}",
                    "# TYPE mst_spec_tokens_replayed_total counter",
                    f"mst_spec_tokens_replayed_total "
                    f"{getattr(spec, 'replayed_tokens', 0)}",
                ]
            # cross-replica shared weights (weights.WeightStore): resident
            # tree count, engine refs aliasing them, and resident bytes —
            # with sharing on, bytes stays ~W while refs tracks fleet size;
            # always emitted (zeros mean every replica owns a private copy)
            try:
                if self.weight_store_fn is not None:
                    ws = self.weight_store_fn()
                else:
                    from mlx_sharding_tpu.weights import weight_store

                    ws = weight_store()
                store = ws.stats() if ws is not None else None
            except Exception:  # noqa: BLE001 — scrapes must never 500
                store = None
            if store is not None:
                lines += [
                    "# TYPE mst_weight_store_trees gauge",
                    f"mst_weight_store_trees {store['trees']}",
                    "# TYPE mst_weight_store_refs gauge",
                    f"mst_weight_store_refs {store['refs']}",
                    "# TYPE mst_weight_store_bytes gauge",
                    f"mst_weight_store_bytes {store['bytes']}",
                ]
            # fleet-wide content-addressed prefix KV store (prefix_store.py):
            # residency by tier, lookup quality, COW fork volume, insertion
            # damping, and eviction churn by reason
            try:
                ps = (
                    self.prefix_store_fn()
                    if self.prefix_store_fn is not None
                    else None
                )
                pstats = ps.stats() if ps is not None else None
            except Exception:  # noqa: BLE001 — scrapes must never 500
                pstats = None
            if pstats is not None:
                lines += [
                    "# TYPE mst_prefix_store_blocks gauge",
                    f'mst_prefix_store_blocks{{tier="device"}} '
                    f"{pstats['device_blocks']}",
                    f'mst_prefix_store_blocks{{tier="host"}} '
                    f"{pstats['host_blocks']}",
                    "# TYPE mst_prefix_store_bytes gauge",
                    f'mst_prefix_store_bytes{{tier="device"}} '
                    f"{pstats['device_bytes']}",
                    f'mst_prefix_store_bytes{{tier="host"}} '
                    f"{pstats['host_bytes']}",
                    "# TYPE mst_prefix_store_budget_bytes gauge",
                    f"mst_prefix_store_budget_bytes "
                    f"{pstats['host_budget_bytes']}",
                    "# TYPE mst_prefix_store_hits_total counter",
                    f'mst_prefix_store_hits_total{{tier="device"}} '
                    f"{pstats['hits_device']}",
                    f'mst_prefix_store_hits_total{{tier="host"}} '
                    f"{pstats['hits_host']}",
                    "# TYPE mst_prefix_store_misses_total counter",
                    f"mst_prefix_store_misses_total {pstats['misses']}",
                    "# TYPE mst_prefix_store_hit_rate gauge",
                    f"mst_prefix_store_hit_rate {pstats['hit_rate']:.4f}",
                    "# TYPE mst_prefix_store_tokens_reused_total counter",
                    f"mst_prefix_store_tokens_reused_total "
                    f"{pstats['tokens_reused']}",
                    "# TYPE mst_prefix_store_cow_forks_total counter",
                    f"mst_prefix_store_cow_forks_total "
                    f"{pstats['cow_forks']}",
                    "# TYPE mst_prefix_store_inserts_total counter",
                    f"mst_prefix_store_inserts_total {pstats['inserts']}",
                    "# TYPE mst_prefix_store_inserts_damped_total counter",
                    f"mst_prefix_store_inserts_damped_total "
                    f"{pstats['inserts_damped']}",
                    # 1 while brownout level >= 1 holds insertion closed
                    "# TYPE mst_prefix_store_inserts_paused gauge",
                    f"mst_prefix_store_inserts_paused "
                    f"{int(bool(pstats['inserts_paused']))}",
                    "# TYPE mst_prefix_store_demotions_total counter",
                    f"mst_prefix_store_demotions_total "
                    f"{pstats['demotions']}",
                    "# TYPE mst_prefix_store_demote_drops_total counter",
                    f"mst_prefix_store_demote_drops_total "
                    f"{pstats['demote_drops']}",
                    "# TYPE mst_prefix_store_evictions_total counter",
                    f'mst_prefix_store_evictions_total{{reason="budget"}} '
                    f"{pstats['evictions_budget']}",
                    f'mst_prefix_store_evictions_total{{reason="oversize"}} '
                    f"{pstats['evictions_oversize']}",
                    f'mst_prefix_store_evictions_total{{reason="reset"}} '
                    f"{pstats['evictions_reset']}",
                    "# TYPE mst_prefix_store_imports_total counter",
                    f'mst_prefix_store_imports_total{{kind="staged"}} '
                    f"{pstats['imports_staged']}",
                    f'mst_prefix_store_imports_total{{kind="demand"}} '
                    f"{pstats['imports_demand']}",
                    "# TYPE mst_prefix_store_faults_total counter",
                    f'mst_prefix_store_faults_total{{kind="lookup"}} '
                    f"{pstats['lookup_faults']}",
                    f'mst_prefix_store_faults_total{{kind="import"}} '
                    f"{pstats['import_faults']}",
                ]
            # layer-wise KV sharing (kv_share.py, KVSharer): share-group
            # geometry and the pool bytes the calibrated map removed —
            # only when a share map is configured (kv_share_fn unset keeps
            # the exposition free of the families)
            try:
                share = (
                    self.kv_share_fn()
                    if self.kv_share_fn is not None
                    else None
                )
            except Exception:  # noqa: BLE001 — scrapes must never 500
                share = None
            if share is not None:
                lines += [
                    "# TYPE mst_kv_share_enabled gauge",
                    f"mst_kv_share_enabled "
                    f"{int(bool(share.get('enabled')))}",
                    "# TYPE mst_kv_share_groups gauge",
                    f"mst_kv_share_groups {share.get('groups', 0)}",
                    "# TYPE mst_kv_share_bytes_saved gauge",
                    f"mst_kv_share_bytes_saved "
                    f"{share.get('bytes_saved', 0)}",
                ]
            # compressed-latent KV transport (kv_compress.py): blocks and
            # bytes moved compressed vs raw plus the counted degradation
            # legs — only when a codec is active (MLA-native or a loaded
            # low-rank map; kv_compress_fn returning None keeps the
            # exposition free of the families)
            try:
                comp = (
                    self.kv_compress_fn()
                    if self.kv_compress_fn is not None
                    else None
                )
            except Exception:  # noqa: BLE001 — scrapes must never 500
                comp = None
            if comp is not None:
                mode = str(comp.get("mode", "latent"))
                lines += [
                    "# TYPE mst_kv_compress_enabled gauge",
                    f'mst_kv_compress_enabled{{mode="{mode}"}} 1',
                    "# TYPE mst_kv_compress_blocks_total counter",
                    f'mst_kv_compress_blocks_total{{op="compress"}} '
                    f"{comp.get('blocks_compressed', 0)}",
                    f'mst_kv_compress_blocks_total{{op="reconstruct"}} '
                    f"{comp.get('blocks_reconstructed', 0)}",
                    "# TYPE mst_kv_compress_faults_total counter",
                    f'mst_kv_compress_faults_total{{op="encode"}} '
                    f"{comp.get('compress_faults', 0)}",
                    f'mst_kv_compress_faults_total{{op="decode"}} '
                    f"{comp.get('reconstruct_faults', 0)}",
                    "# TYPE mst_kv_compress_bytes_total counter",
                    f'mst_kv_compress_bytes_total{{kind="raw"}} '
                    f"{comp.get('bytes_raw_total', 0)}",
                    f'mst_kv_compress_bytes_total{{kind="wire"}} '
                    f"{comp.get('bytes_wire_total', 0)}",
                    "# TYPE mst_kv_compress_bytes_saved gauge",
                    f"mst_kv_compress_bytes_saved "
                    f"{comp.get('bytes_saved_total', 0)}",
                ]
            # pod fleet (pod.py): host-labeled size/weights/heartbeat from
            # the gossip view plus handoff and autoscaler counters — only
            # on --pod deployments (pod_stats_fn unset keeps single-host
            # exposition label-free); the gossip snapshot can race a host
            # death mid-render, so the whole section drops on any error
            pmark = len(lines)
            try:
                pod = (
                    self.pod_stats_fn()
                    if self.pod_stats_fn is not None
                    else None
                )
                if pod is not None:
                    lines += [
                        "# TYPE mst_pod_hosts gauge",
                        f"mst_pod_hosts {len(pod['hosts'])}",
                        "# TYPE mst_pod_host_deaths_total counter",
                        f"mst_pod_host_deaths_total "
                        f"{pod['autoscaler']['deaths_detected']}",
                    ]
                    hosts = sorted(pod["hosts"])
                    # one # TYPE per family (invalid exposition otherwise),
                    # then every host's sample; mst_fleet_size and the
                    # mst_weight_store_* families were already declared by
                    # the single-host sections above, so the host-labeled
                    # samples ride the existing declarations
                    lines.append("# TYPE mst_pod_host_alive gauge")
                    lines += [
                        f'mst_pod_host_alive{{host="{h}"}} '
                        f"{int(bool(pod['hosts'][h].get('alive')))}"
                        for h in hosts
                    ]
                    ages = [
                        (h, pod["hosts"][h].get("heartbeat_age_s"))
                        for h in hosts
                    ]
                    if any(a is not None for _, a in ages):
                        lines.append(
                            "# TYPE mst_pod_heartbeat_age_seconds gauge"
                        )
                        lines += [
                            f'mst_pod_heartbeat_age_seconds{{host="{h}"}} '
                            f"{a:.3f}"
                            for h, a in ages if a is not None
                        ]
                    lines += [
                        f'mst_fleet_size{{host="{h}"}} '
                        f"{(pod['hosts'][h].get('fleet') or {}).get('live', 0)}"
                        for h in hosts if pod["hosts"][h].get("fleet")
                    ]
                    for fam, key in (("trees", "trees"), ("refs", "refs"),
                                     ("bytes", "bytes")):
                        lines += [
                            f'mst_weight_store_{fam}{{host="{h}"}} '
                            f"{(pod['hosts'][h].get('weights') or {}).get(key, 0)}"
                            for h in hosts if pod["hosts"][h].get("weights")
                        ]
                    ho = pod["handoff"]
                    lines += [
                        "# TYPE mst_pod_handoff_total counter",
                        f"mst_pod_handoff_total {ho['shipped']}",
                        "# TYPE mst_pod_handoff_bytes_total counter",
                        f"mst_pod_handoff_bytes_total {ho['bytes_shipped']}",
                        "# TYPE mst_pod_handoff_received_total counter",
                        f"mst_pod_handoff_received_total {ho['received']}",
                        "# TYPE mst_pod_handoff_fallbacks_total counter",
                    ]
                    fb = ho.get("fallbacks") or {}
                    if fb:
                        lines += [
                            f'mst_pod_handoff_fallbacks_total'
                            f'{{kind="{kind}"}} {fb[kind]}'
                            for kind in sorted(fb)
                        ]
                    else:
                        # a bare # TYPE with no samples is invalid
                        # exposition — emit the zero explicitly
                        lines.append("mst_pod_handoff_fallbacks_total 0")
                    if ho.get("ms_p50") is not None:
                        lines += [
                            "# TYPE mst_pod_handoff_ms summary",
                            f'mst_pod_handoff_ms{{quantile="0.5"}} '
                            f"{ho['ms_p50']:.3f}",
                            f'mst_pod_handoff_ms{{quantile="0.99"}} '
                            f"{ho['ms_p99']:.3f}",
                        ]
                    # pod-federated prefix store (PodPrefixFederation):
                    # gossiped inventory size, remote-hit fetch traffic,
                    # and the by-kind degradations to plain prefill — only
                    # when the pod federates a store
                    pp = pod.get("prefix")
                    if pp is not None:
                        lines += [
                            "# TYPE mst_prefix_pod_inventory_keys gauge",
                            f"mst_prefix_pod_inventory_keys "
                            f"{pp.get('inventory_keys', 0)}",
                            "# TYPE mst_prefix_pod_hits_total counter",
                            f"mst_prefix_pod_hits_total "
                            f"{pp.get('hits', 0)}",
                            "# TYPE mst_prefix_pod_fetches_total counter",
                            f"mst_prefix_pod_fetches_total "
                            f"{pp.get('fetches', 0)}",
                            "# TYPE mst_prefix_pod_fetch_bytes_total "
                            "counter",
                            f"mst_prefix_pod_fetch_bytes_total "
                            f"{pp.get('fetch_bytes', 0)}",
                            "# TYPE mst_prefix_pod_fallbacks_total counter",
                        ]
                        pfb = pp.get("fallbacks") or {}
                        if pfb:
                            lines += [
                                f'mst_prefix_pod_fallbacks_total'
                                f'{{kind="{kind}"}} {pfb[kind]}'
                                for kind in sorted(pfb)
                            ]
                        else:
                            # a bare # TYPE with no samples is invalid
                            # exposition — emit the zero explicitly
                            lines.append("mst_prefix_pod_fallbacks_total 0")
                        if pp.get("fetch_ms_p50") is not None:
                            lines += [
                                "# TYPE mst_prefix_pod_fetch_ms summary",
                                f'mst_prefix_pod_fetch_ms{{quantile="0.5"}} '
                                f"{pp['fetch_ms_p50']:.3f}",
                                f'mst_prefix_pod_fetch_ms{{quantile="0.99"}} '
                                f"{pp['fetch_ms_p99']:.3f}",
                            ]
            except Exception:  # noqa: BLE001 — scrapes must never 500
                del lines[pmark:]
        return "\n".join(_finalize(lines)) + "\n"


# explicit HELP strings for the families whose meaning is not readable off
# the name; everything else gets a generated one-liner (coverage contract:
# EVERY emitted family carries # HELP and # TYPE — test_metrics_help_type)
_HELP = {
    "mst_requests_total": "Requests served (including failures).",
    "mst_requests_failed_total": "Requests that ended in an error.",
    "mst_ttft_seconds": "Time to first token, seconds (histogram).",
    "mst_itl_seconds":
        "Inter-token latency from the scheduler emit path, seconds.",
    "mst_queue_wait_seconds":
        "Admission queue wait, submit to slot assignment, seconds.",
    "mst_disagg_handoff_ms":
        "Prefill-to-decode KV handoff latency, milliseconds.",
    "mst_decode_tokens_per_second": "Per-request decode rate summary.",
    "mst_tick_host_ms": "Host-side scheduler work per tick, ms.",
    "mst_tick_device_blocked_ms":
        "Per-tick wall time blocked on the device, ms.",
    "mst_faults_armed":
        "Currently armed fault-injection sites (should be 0 in prod).",
    "mst_faults_malformed_total":
        "MST_FAULTS entries dropped as malformed at parse time.",
    "mst_ledger_anomalies_total":
        "Resource-ledger anomalies (double acquire/release); the log is "
        "a bounded ring but this counter never loses an increment.",
}


def _help_text(family: str) -> str:
    return _HELP.get(
        family, family.removeprefix("mst_").replace("_", " ") + "."
    )


def _infer_type(family: str) -> str:
    return "counter" if family.endswith("_total") else "gauge"


def _family_of(sample: str, histograms: set) -> str:
    name = sample.split("{", 1)[0].split(" ", 1)[0]
    for suffix in ("_bucket", "_sum", "_count"):
        if name.endswith(suffix) and name[: -len(suffix)] in histograms:
            return name[: -len(suffix)]
    return name


def _finalize(lines: list) -> list:
    """Exposition post-pass: every family gets a ``# HELP`` ahead of its
    ``# TYPE``, and any sample whose family never declared a ``# TYPE``
    (ad-hoc gauges added over ten PRs) gets both synthesized in front of
    its first sample. Keeps the per-block rendering code append-only."""
    typed = set()
    histograms = set()
    for ln in lines:
        if ln.startswith("# TYPE "):
            parts = ln.split()
            typed.add(parts[2])
            if parts[3] == "histogram":
                histograms.add(parts[2])
    out: list = []
    helped: set = set()
    for ln in lines:
        if ln.startswith("# HELP "):
            helped.add(ln.split()[2])
            out.append(ln)
            continue
        if ln.startswith("# TYPE "):
            fam = ln.split()[2]
            if fam not in helped:
                out.append(f"# HELP {fam} {_help_text(fam)}")
                helped.add(fam)
            out.append(ln)
            continue
        if not ln or ln.startswith("#"):
            out.append(ln)
            continue
        fam = _family_of(ln, histograms)
        if fam not in typed:
            out.append(f"# HELP {fam} {_help_text(fam)}")
            out.append(f"# TYPE {fam} {_infer_type(fam)}")
            helped.add(fam)
            typed.add(fam)
        out.append(ln)
    return out
