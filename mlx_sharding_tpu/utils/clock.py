"""Injectable time sources — the one place serving code gets "now" from.

Every controller in the stack (breakers, brownout dwell, autoscaler
hysteresis, heartbeat staleness, request deadlines) does arithmetic on a
monotonic "now". Grabbing ``time.monotonic`` ad hoc works until something
needs to *test* that arithmetic — or, worse, to run a 100-host fleet
through hours of simulated traffic in milliseconds. The contract here:

- **Production** code takes ``clock: Clock = MONOTONIC`` (and, where it
  also waits, ``sleep: SleepFn = WALL_SLEEP``) and never calls
  ``time.monotonic()`` / ``time.sleep()`` directly on a deadline path.
  mstcheck MST107 enforces the read half: a raw ``time.monotonic()`` in
  deadline arithmetic inside a class that carries an injectable clock is
  flagged — it silently bypasses the injected time source, so virtual-time
  tests pass while the shipped binary runs on a different clock.
- **Tests** inject a hand-stepped fake (``VirtualClock`` here, or the
  per-suite ``FakeClock`` equivalents that predate it).
- **The fleet simulator** (``mlx_sharding_tpu.sim``) injects one shared
  :class:`VirtualClock` into every component and advances it from a
  discrete-event loop — zero wall-clock sleeps, bit-identical replays.
"""

from __future__ import annotations

import time
from typing import Callable, Protocol, runtime_checkable


@runtime_checkable
class Clock(Protocol):
    """A zero-arg callable returning monotonic seconds. ``time.monotonic``
    satisfies it; so does :class:`VirtualClock` and every test FakeClock."""

    def __call__(self) -> float: ...


# the production defaults, importable by name so call sites read as intent
# ("this is the injectable slot, wired to the real clock") rather than as
# one more ad-hoc time.monotonic reference
MONOTONIC: Callable[[], float] = time.monotonic
WALL_SLEEP: Callable[[float], None] = time.sleep

SleepFn = Callable[[float], None]


class VirtualClock:
    """A monotonic clock that only moves when told to.

    Callable (so it drops into any ``clock=`` slot) and explicitly
    steppable. ``advance``/``set`` enforce monotonicity — simulated time
    never runs backward, exactly like the clock it stands in for."""

    __slots__ = ("_now",)

    def __init__(self, start: float = 0.0):
        self._now = float(start)

    def __call__(self) -> float:
        return self._now

    @property
    def now(self) -> float:
        return self._now

    def advance(self, dt: float) -> float:
        if dt < 0:
            raise ValueError(f"cannot advance a monotonic clock by {dt!r}")
        self._now += dt
        return self._now

    def set(self, t: float) -> float:
        """Jump to absolute time ``t`` (no-op when ``t`` is in the past —
        the event loop may deliver same-timestamp events in sequence)."""
        if t > self._now:
            self._now = float(t)
        return self._now

    def __repr__(self) -> str:
        return f"VirtualClock(t={self._now:.6f})"
