"""Chained chunk digests over token prefixes: the ONE content-address.

Both the router's prefix-affinity map (``ReplicaSet._affinity_chunks``)
and the fleet-wide prefix KV store (``prefix_store.PrefixStore``) key on
the same scheme: the prompt is cut into fixed-size token chunks and each
chunk's blake2b digest is seeded with the previous chunk's digest, so the
k-th digest content-addresses the ENTIRE k-chunk prefix — matching one
digest means matching every token before it. Extracting the chain here is
what makes the two consumers structurally unable to disagree on chunk
size semantics or chain seed: a router affinity hit and a store lookup
hit describe the same shared prefix.

The digest text is the comma-joined decimal token ids (not raw bytes):
stable across int dtypes and platforms, and identical to what the router
has always hashed — extraction changes no digest value.
"""

from __future__ import annotations

import hashlib
from typing import Optional


def chunk_digests(tokens, page: int, max_chunks: Optional[int] = None) -> list:
    """Chained 16-byte blake2b digests over fixed ``page``-token chunks of
    ``tokens``; ``keys[i]`` addresses the whole ``(i+1) * page``-token
    prefix. A trailing partial chunk contributes nothing (prefix reuse is
    chunk-granular). ``max_chunks`` caps the walk (the router bounds its
    hashing work; the store caps at the last FULL page before the final
    prompt token). Raises ``TypeError``/``ValueError`` on non-int tokens —
    callers with untrusted prompts guard, exactly as the router did."""
    toks = list(tokens)
    if max_chunks is not None:
        toks = toks[: page * max_chunks]
    toks = [int(t) for t in toks]
    n = len(toks) // page
    keys, h = [], b""
    for c in range(n):
        m = hashlib.blake2b(h, digest_size=16)
        m.update(",".join(map(str, toks[c * page:(c + 1) * page])).encode())
        h = m.digest()
        keys.append(h)
    return keys
