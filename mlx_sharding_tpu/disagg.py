"""Disaggregated prefill/decode serving: role-split pools + KV handoff.

Monolithic replicas make one engine own a request for its whole lifetime,
so long prefills and steady decode ticks fight for the same device and
TTFT / decode-throughput SLOs cannot be tuned independently. This module
splits the lifetime in two, per TPLA (arXiv:2508.15881): a PREFILL pool
runs flash-prefill at high arithmetic intensity and emits the first token;
the request's KV then ships to a DECODE pool replica as the checksummed
``KVPageBlock`` built in ``kv_transfer.py``, and that replica owns the
stream through completion.

Topology::

      request ──> DisaggCoordinator
                    │ route (prefix affinity / stickiness still apply)
                    ▼
              [prefill pool]  — ContinuousBatchers, _prefill_only=True
                    │ first token ──────────────> client (TTFT met)
                    │ HandoffReadyError(ResumeState)
                    ▼
              block.to_host()  — consumer-thread DMA, overlapped with the
                    │            prefill replica's next ticks (PRESERVE,
                    │            arXiv:2501.08192)
                    ▼
              [decode pool]   — least-loaded replica imports the block
                    │            (one scatter, no re-prefill) and resumes
                    ▼            token-exactly from the delivered prefix
                  client  <──  tokens 2..n

The handoff never stalls either pool's ticks: the prefill scheduler
exports the block dispatch-only (``_handoff_out``, off the tick-hot path —
MST108 enforces this) and leaves the device→host copy to THIS module,
which runs it on the request's own consumer thread; the decode replica
imports at admission through the existing resume machinery.

Degradation contract — a stream, once started, is NEVER dropped while any
replica in either pool lives:

- ``disagg.handoff`` fault (or any handoff-control failure): serve in
  place — the prefill pool resumes the stream itself and decodes it to
  completion. Counted ``handoff_fault``.
- ``to_host`` / ``cache.export`` failure: the block is dropped and the
  handoff proceeds blockless — the decode replica folds the delivered
  history into the prompt and re-prefills, still token-exact (the sampler
  PRNG row and repetition window travel in the ``ResumeState``). Counted
  ``block_dropped``.
- ``cache.import`` failure on the decode replica: the scheduler's own
  import fallback re-prefills from the fold — no coordinator involvement.
- prefill pool unavailable before any token: the decode pool serves the
  request monolithically (prefill included). Counted
  ``prefill_unavailable``. Admission saturation (``QueueFullError``) is
  NOT remapped — 429 + ``Retry-After`` is the correct answer, and routing
  the overflow at the decode pool would break its SLO isolation.
- a pool dies mid-stream after its own retries are exhausted: the
  coordinator rebuilds a blockless ``ResumeState`` from its delivered-
  token record and resumes on the other pool (greedy streams token-exact;
  sampled streams reseed, as for crash failover).

Autoscaling stays per-pool: each role's ``ReplicaSet`` gets its own
``FleetAutoscaler`` over its own ``pool_pressure`` (see ``fleet.py``), so
a prefill storm scales the prefill pool and cannot trigger decode-pool
spawns (and vice versa).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Optional

from mlx_sharding_tpu import tracing
from mlx_sharding_tpu.analysis.runtime import make_lock
from mlx_sharding_tpu.resilience import (
    HandoffReadyError,
    QueueFullError,
    RequestTimeoutError,
    ResumeState,
)
from mlx_sharding_tpu.testing.faults import inject
from mlx_sharding_tpu.utils.clock import MONOTONIC, Clock
from mlx_sharding_tpu.utils.observability import HANDOFF_BUCKETS_MS, Histogram


def _pct(sorted_ms: list, q: float) -> Optional[float]:
    """Nearest-rank percentile over an already-sorted sample; None when
    empty (gauge-grade — the handoff window is a bounded deque)."""
    if not sorted_ms:
        return None
    k = min(len(sorted_ms) - 1, max(0, int(round(q / 100 * len(sorted_ms))) - 1))
    return sorted_ms[k]


class DisaggCoordinator:
    """Two-phase request ownership over role-tagged replica pools.

    ``generate_step`` has the same contract as ``ReplicaSet``'s — eager
    validation errors surface on first ``next()``, then a token stream —
    so the server drives it unchanged. Every prefill replica must speak
    the prefill-only protocol (``supports_prefill_only``) and every decode
    replica the resume protocol (``supports_resume``); both are checked at
    construction, not at the first handoff."""

    concurrent = True  # the server must not serialize requests around us
    supports_sessions = True  # stickiness applies to the prefill leg

    def __init__(self, prefill_pool, decode_pool, *,
                 handoff_window: int = 512, prefix_store=None,
                 clock: Clock = MONOTONIC):
        for rep in getattr(prefill_pool, "replicas", [prefill_pool]):
            if not getattr(rep, "supports_prefill_only", False):
                raise ValueError(
                    "every prefill-pool replica must support prefill-only "
                    "admission (ContinuousBatcher); got "
                    f"{type(rep).__name__}"
                )
        for rep in getattr(prefill_pool, "replicas", [prefill_pool]):
            if getattr(rep, "_spec_mode", "off") != "off":
                raise ValueError(
                    "prefill-pool replicas must not speculate: a prefill "
                    "replica emits one token per request before the "
                    "handoff, so draft windows there are pure ballast — "
                    "build the pool with draft='off' (decode replicas "
                    "keep theirs)"
                )
        for rep in getattr(decode_pool, "replicas", [decode_pool]):
            if not getattr(rep, "supports_resume", False):
                raise ValueError(
                    "every decode-pool replica must support the resume "
                    f"protocol; got {type(rep).__name__}"
                )
        self.prefill = prefill_pool
        self.decode = decode_pool
        self.clock = clock
        # pod-scale cross-host handoff (pod.PodHandoff), attached by the
        # pod fleet after construction: when set, phase 2 may ship the
        # block to a less-loaded REMOTE decode host instead of the local
        # decode pool, with the same never-drop degradation ladder
        self.pod = None
        # fleet-wide prefix store (optional): when the WHOLE prompt is
        # already covered — a device entry on some decode replica or a
        # host-tier block — phase 1 is pure overhead, so generate_step
        # skips the prefill pool entirely and the decode pool serves from
        # token 0 (its admission imports/leases the covered prefix)
        self.prefix_store = prefix_store
        self._lock = make_lock("DisaggCoordinator._lock")
        self.handoffs = 0          # completed prefill→decode handoffs
        self.handoff_bytes = 0     # sum of shipped block payloads
        self.handoffs_compressed = 0  # handoffs shipped as compressed latents
        self.store_skips = 0       # full store hits that skipped phase 1
        self.fallbacks: dict = {}  # degradation counts by kind
        self._ms: deque = deque(maxlen=handoff_window)  # DMA+control ms
        # cumulative handoff-latency histogram: unlike the windowed deque
        # above, never resets, so /metrics can render a Prometheus-grade
        # ``mst_disagg_handoff_ms_bucket`` family that survives scrapes
        self._ms_hist = Histogram(HANDOFF_BUCKETS_MS,
                                  "DisaggCoordinator._ms_hist")

    # ---------------------------------------------------------- serving
    @property
    def supports_trace(self) -> bool:
        """``_trace`` is forwarded verbatim to both pools, so one request
        timeline spans the prefill leg, the handoff, and the decode leg —
        advertise it only when every leg will honor it."""
        return (getattr(self.prefill, "supports_trace", False)
                and getattr(self.decode, "supports_trace", False))

    @property
    def supports_deadlines(self) -> bool:
        return (getattr(self.prefill, "supports_deadlines", False)
                and getattr(self.decode, "supports_deadlines", False))

    @property
    def brownout(self):
        """The decode pool's brownout governs generation caps (that is
        where decode saturation lives); prefill's is the fallback."""
        return (getattr(self.decode, "brownout", None)
                or getattr(self.prefill, "brownout", None))

    def _count(self, kind: str):
        with self._lock:
            self.fallbacks[kind] = self.fallbacks.get(kind, 0) + 1

    def attach_pod(self, pod_handoff) -> None:
        """Wire the cross-host leg in (pod.PodFleet calls this): phase 2
        consults ``pod_handoff.pick_remote()`` per handoff and may serve
        the decode leg on a remote host."""
        self.pod = pod_handoff

    def generate_step(self, prompt_tokens, **kw):
        emitted: list = []  # every token the client saw, both phases
        trackable = True    # ints only; else cross-pool resume is refused

        def _track(item) -> bool:
            tok = item[0] if isinstance(item, (tuple, list)) else item
            try:
                emitted.append(int(tok))
                return True
            except (TypeError, ValueError):
                return False

        def _serve(pool, resume, fwd):
            nonlocal trackable
            f = dict(fwd, _resume=resume) if resume is not None else fwd
            it = pool.generate_step(prompt_tokens, **f)
            try:
                for item in it:
                    if trackable:
                        trackable = _track(item)
                    yield item
            except GeneratorExit:
                it.close()
                raise

        # resume/fallback legs drop the routing + TTFT kwargs: the first
        # token was already delivered, so stickiness and the TTFT budget
        # belong to the prefill leg alone. The TTFT value stays alive as
        # the inter-token watchdog it would have defaulted to.
        resume_kw = dict(kw)
        resume_kw.pop("_session", None)
        ttft = resume_kw.pop("ttft_timeout", None)
        if ttft is not None and resume_kw.get("stall_timeout") is None:
            resume_kw["stall_timeout"] = ttft

        # ---- phase 0: fleet-store full-hit check — when the store already
        # covers the ENTIRE prompt (a decode replica's device entry or a
        # host-tier block), dispatching to the prefill pool would prefill
        # nothing: skip phase 1 outright and let the decode pool serve
        # from token 0, admission leasing/importing the covered prefix.
        # A sick store (injected ``cache.prefix_lookup``) degrades to the
        # normal two-phase path — never a wrong or dropped stream.
        state: Optional[ResumeState] = None
        monolithic = False
        skip_prefill = False
        if self.prefix_store is not None:
            try:
                skip_prefill = self.prefix_store.covers_full(prompt_tokens)
            except Exception:  # noqa: BLE001 — advisory check only
                skip_prefill = False
        if skip_prefill:
            with self._lock:
                self.store_skips += 1
            monolithic = True  # decode-pool-first, original kwargs

        # ---- phase 1: the prefill pool delivers the first token
        if not monolithic:
            it = self.prefill.generate_step(
                prompt_tokens, _prefill_only=True, **kw
            )
            try:
                for item in it:
                    if trackable:
                        trackable = _track(item)
                    yield item
                return  # max_tokens == 1: the stream completed during prefill
            except GeneratorExit:
                it.close()
                raise
            except HandoffReadyError as exc:
                state = exc.state  # the expected exit: run the handoff below
            except (ValueError, RequestTimeoutError):
                raise  # bad request / blown budget — not a placement problem
            except QueueFullError:
                if not emitted:
                    raise  # saturation: 429 + Retry-After, do not spill the
                    # overflow onto the decode pool (that is the SLO leak
                    # disaggregation exists to close)
                self._count("prefill_failed")  # mid-replacement full queues
            except Exception:
                if emitted and not trackable:
                    raise  # tokens delivered, no exact continuation possible
                if emitted:
                    self._count("prefill_failed")
                else:
                    # nothing delivered yet: the decode pool serves the whole
                    # request monolithically — degraded, never dropped
                    self._count("prefill_unavailable")
                    monolithic = True

        # ---- phase 2: handoff (or fallback re-placement)
        if state is not None:
            target = self.decode
            tr = kw.get("_trace")
            t0 = self.clock()
            tp0 = time.perf_counter()
            with tracing.bind(tr):
                try:
                    inject("disagg.handoff",
                           n_bytes=getattr(state.block, "nbytes", 0))
                except Exception:
                    # handoff-control failure: serve in place — the prefill
                    # pool finishes the stream it started
                    self._count("handoff_fault")
                    target = self.prefill
                if state.block is not None:
                    try:
                        # the export was dispatch-only on the prefill tick;
                        # THIS is the device→host DMA, on the request's own
                        # consumer thread so both pools keep ticking under it
                        state.block.to_host()
                    except Exception:
                        state.block = None  # fold re-prefill stays token-exact
                        self._count("block_dropped")
            if target is self.decode:
                # nbytes reads AFTER to_host: a compressed-latent block
                # (kv_compress) counts its wire size — what actually moved
                nbytes = getattr(state.block, "nbytes", 0) or 0
                compressed = (
                    getattr(state.block, "compress_kind", None) is not None
                )
                ms = (self.clock() - t0) * 1000.0
                with self._lock:
                    self.handoffs += 1
                    self.handoff_bytes += int(nbytes)
                    if compressed:
                        self.handoffs_compressed += 1
                    self._ms.append(ms)
                self._ms_hist.observe(ms)
                if tr is not None:
                    tr.add("handoff_transfer", tp0, time.perf_counter(),
                           bytes=int(nbytes), compressed=compressed)
            elif tr is not None:
                tr.point("handoff_fault")
            # ---- pod leg: a remote decode host may be less loaded than
            # the local decode pool. serve_remote ships the block through
            # the ``pod.handoff`` fault site and relays the remote tokens
            # back; ANY failure raises PodHandoffFallback (counted by the
            # handoff, by kind) and the request continues on the local
            # plan below — cross-host never weakens the never-drop ladder.
            if target is self.decode and self.pod is not None \
                    and self.pod.pick_remote() is not None:
                from mlx_sharding_tpu.pod import PodHandoffFallback

                it = self.pod.serve_remote(state, resume_kw)
                try:
                    for item in it:
                        if trackable:
                            trackable = _track(item)
                        yield item
                    return
                except GeneratorExit:
                    it.close()
                    raise
                except PodHandoffFallback as exc:
                    if not exc.keep_block or exc.tokens_relayed:
                        # the block is gone (shipped/corrupt) or the remote
                        # already advanced the stream: rebuild a blockless
                        # resume from the coordinator's own delivered-token
                        # record — the existing token-exact fold path
                        state = ResumeState(
                            prompt=prompt_tokens, history=list(emitted),
                            produced=len(emitted),
                        )
            plan = [target, self.decode if target is self.prefill
                    else self.prefill]
            fwd = resume_kw
        elif monolithic:
            # full serve (prefill included): original kwargs, TTFT intact
            plan, fwd = [self.decode, self.prefill], kw
        else:
            # prefill leg died after delivering tokens: blockless resume,
            # decode pool first (it is the decode phase anyway)
            state = ResumeState(prompt=prompt_tokens, history=list(emitted),
                                produced=len(emitted))
            plan, fwd = [self.decode, self.prefill], resume_kw

        last: Optional[BaseException] = None
        for k, pool in enumerate(plan):
            try:
                yield from _serve(pool, state, fwd)
                return
            except GeneratorExit:
                raise
            except (ValueError, RequestTimeoutError):
                raise
            except Exception as exc:
                last = exc
                if emitted and not trackable:
                    raise
                if k + 1 < len(plan):
                    self._count(
                        f"{getattr(pool, 'role', None) or 'pool'}_failed"
                    )
                    if emitted:
                        # carry the full delivered prefix to the next pool
                        state = ResumeState(
                            prompt=prompt_tokens, history=list(emitted),
                            produced=len(emitted),
                        )
                        fwd = resume_kw
        raise last

    # ---------------------------------------------------- observability
    def handoff_stats(self) -> dict:
        """Counters for ``mst_disagg_handoff_*`` and the /health handoff
        block: completed handoffs, shipped bytes, DMA+control latency
        percentiles over the last window, degradation counts by kind."""
        with self._lock:
            ms = sorted(self._ms)
            return {
                "handoffs": self.handoffs,
                "bytes_total": self.handoff_bytes,
                "handoffs_compressed": self.handoffs_compressed,
                "store_skips": self.store_skips,
                "fallbacks": dict(self.fallbacks),
                "ms_p50": _pct(ms, 50),
                "ms_p99": _pct(ms, 99),
                "window": len(ms),
                "ms_hist": self._ms_hist.to_dict(),
            }

    def latency_stats(self) -> Optional[dict]:
        """Pool batchers' cumulative latency histograms (ITL, queue-wait)
        merged across both roles — same shape as a single batcher's."""
        per = [s for s in (
            getattr(self.prefill, "latency_stats", lambda: None)(),
            getattr(self.decode, "latency_stats", lambda: None)(),
        ) if s]
        if not per:
            return None
        return {k: Histogram.merge_dicts([s[k] for s in per if k in s])
                for k in set().union(*per)}

    def stats(self):
        """(slots, active, queued) summed over both pools."""
        ps, pa, pq = self.prefill.stats()
        ds, da, dq = self.decode.stats()
        return ps + ds, pa + da, pq + dq

    def replica_stats(self) -> list:
        """Both pools' per-replica snapshots, role-tagged (indices repeat
        across pools; the role label disambiguates the gauge lines)."""
        return list(self.prefill.replica_stats()) \
            + list(self.decode.replica_stats())

    def fleet_stats(self) -> dict:
        """Aggregate fleet gauges plus per-role ``pools`` blocks (the
        /metrics renderer emits ``mst_fleet_size{role=...}`` from them)."""
        pf, df = self.prefill.fleet_stats(), self.decode.fleet_stats()
        events: dict = {}
        for src in (pf, df):
            for k, v in src.get("autoscale_events", {}).items():
                events[k] = events.get(k, 0) + v
        out = {"role": None, "pools": [pf, df], "autoscale_events": events}
        for k in ("size", "total", "retired", "draining", "sticky_sessions",
                  "affinity_entries", "affinity_hits", "sticky_hits",
                  "weights_shared"):
            out[k] = pf.get(k, 0) + df.get(k, 0)
        return out

    def resilience_stats(self) -> dict:
        """Both pools' aggregates summed, plus the coordinator's handoff
        counters — one dict shaped like a ReplicaSet's so /metrics code
        paths need no disagg special-casing."""
        pr, dr = self.prefill.resilience_stats(), self.decode.resilience_stats()
        agg: dict = {}
        for k in set(pr) | set(dr):
            a, b = pr.get(k), dr.get(k)
            if k == "max_queue":
                agg[k] = (None if a is None and b is None
                          else (a or 0) + (b or 0))
            elif k == "scheduler_thread_live":
                agg[k] = bool(a if a is not None else True) \
                    and bool(b if b is not None else True)
            else:
                agg[k] = (a or 0) + (b or 0)
        h = self.handoff_stats()
        agg["handoffs"] = h["handoffs"]
        agg["handoff_fallbacks"] = sum(h["fallbacks"].values())
        return agg

    def spill_stats(self) -> Optional[dict]:
        per = [s for s in (self.prefill.spill_stats(),
                           self.decode.spill_stats()) if s is not None]
        if not per:
            return None
        agg: dict = {"enabled": any(s.get("enabled") for s in per)}
        for k in set().union(*per) - {"enabled"}:
            vals = [s.get(k, 0) for s in per]
            agg[k] = sum(v or 0 for v in vals)
        return agg

    def spec_stats(self) -> Optional[dict]:
        """Decode-pool speculation telemetry only — prefill replicas never
        speculate (enforced at construction), so the decode pool IS the
        coordinator's whole speculation story."""
        fn = getattr(self.decode, "spec_stats", None)
        return fn() if fn is not None else None

    def page_stats(self):
        per = [t for t in (self.prefill.page_stats(),
                           self.decode.page_stats()) if t is not None]
        if not per:
            return None
        return tuple(sum(col) for col in zip(*per))

    def set_pressure(self, level: int):
        self.prefill.set_pressure(level)
        self.decode.set_pressure(level)

    def health(self) -> dict:
        """Role blocks from both pools. ``serving`` while EITHER pool has
        a live replica — the degradation ladder can run the whole request
        lifecycle on one pool; ``ok`` only when both report ok."""
        ph, dh = self.prefill.health(), self.decode.health()
        if ph["status"] == dh["status"] == "ok":
            status = "ok"
        elif "draining" in (ph["status"], dh["status"]):
            status = "draining"
        else:
            status = "degraded"
        return {
            "status": status,
            "serving": bool(ph["serving"] or dh["serving"]),
            "disagg": True,
            "pools": {"prefill": ph, "decode": dh},
            "handoff": self.handoff_stats(),
        }

    def close(self):
        self.prefill.close()
        self.decode.close()
