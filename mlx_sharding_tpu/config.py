"""Model configuration dataclasses.

TPU-native re-design of the reference's per-arch ``ModelArgs`` dataclasses
(ref: shard/server/model/llama.py:11-24, gemma2.py:9-21, deepseek_v2.py:11-28).
Like the reference, a model config is constructed from an HF-style
``config.json`` dict, and the pipeline-stage bounds ``start_layer`` /
``end_layer`` ride along inside the config (ref: shard/utils.py:36-39 injects
them; sharding_weight.py:48-60 bakes them into the shard's config.json).

Unlike the reference we keep one base dataclass with arch-specific
subclasses registered in ``CONFIG_REGISTRY`` — resolution replaces the
reference's importlib trick (shard/utils.py:20-30).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Optional


@dataclass
class BaseConfig:
    """Fields shared by every decoder-only architecture we support."""

    model_type: str = "llama"
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: Optional[int] = None
    head_dim: Optional[int] = None
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    rope_scaling: Optional[dict] = None
    max_position_embeddings: int = 8192
    tie_word_embeddings: bool = False
    # Pipeline-stage bounds, [start_layer, end_layer). Mirrors the reference's
    # dynamic-sharding config injection (shard/utils.py:36-39).
    start_layer: int = 0
    end_layer: Optional[int] = None
    # MLX-style grouped affine quantization descriptor, e.g.
    # {"group_size": 64, "bits": 4} (ref: shard/utils.py:54-65).
    quantization: Optional[dict] = None
    # KV-cache storage dtype for paged engines: "int8" stores per-row-per-
    # head-scaled codes ({d, s} pools, see cache.quantize_kv_rows); None/
    # "bf16" keeps the dense cache_dtype pool. Server/CLI --kv-dtype
    # overrides; checkpoints may pin it here.
    kv_cache_dtype: Optional[str] = None

    def __post_init__(self):
        if self.num_key_value_heads is None:
            self.num_key_value_heads = self.num_attention_heads
        if self.head_dim is None:
            self.head_dim = self.hidden_size // self.num_attention_heads
        if self.end_layer is None:
            self.end_layer = self.num_hidden_layers
        if not (0 <= self.start_layer < self.end_layer <= self.num_hidden_layers):
            raise ValueError(
                f"Invalid stage bounds [{self.start_layer}, {self.end_layer}) "
                f"for a {self.num_hidden_layers}-layer model."
            )

    # -- stage placement helpers (semantics of sharding_weight.py:16-24) ----
    @property
    def is_first_stage(self) -> bool:
        return self.start_layer == 0

    @property
    def is_last_stage(self) -> bool:
        return self.end_layer == self.num_hidden_layers

    @property
    def num_local_layers(self) -> int:
        return self.end_layer - self.start_layer

    # Whether this stage needs the token-embedding table. Gemma-2 overrides:
    # its lm_head is tied to the embedding, so the LAST stage needs it too
    # (ref: shard/server/model/gemma2.py:23-24).
    @property
    def needs_embed(self) -> bool:
        return self.is_first_stage or (self.tie_word_embeddings and self.is_last_stage)

    @property
    def needs_head(self) -> bool:
        return self.is_last_stage

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "BaseConfig":
        names = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in names})

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)


@dataclass
class LlamaConfig(BaseConfig):
    model_type: str = "llama"
    attention_bias: bool = False
    mlp_bias: bool = False


@dataclass
class Qwen3Config(LlamaConfig):
    model_type: str = "qwen3"


@dataclass
class Gemma2Config(BaseConfig):
    """Gemma-2: softcapped logits/attention, tied embeddings, alternating
    sliding/global attention (ref: shard/server/model/gemma2.py)."""

    model_type: str = "gemma2"
    head_dim: Optional[int] = 256
    rms_norm_eps: float = 1e-6
    final_logit_softcapping: float = 30.0
    attn_logit_softcapping: float = 50.0
    query_pre_attn_scalar: float = 256.0
    sliding_window: int = 4096
    tie_word_embeddings: bool = True


@dataclass
class DeepseekV2Config(BaseConfig):
    """DeepSeek-V2: MLA attention + fine-grained MoE with shared experts
    (ref: shard/server/model/deepseek_v2.py:11-28)."""

    model_type: str = "deepseek_v2"
    moe_intermediate_size: int = 1407
    n_shared_experts: Optional[int] = 2
    n_routed_experts: Optional[int] = 64
    routed_scaling_factor: float = 1.0
    kv_lora_rank: int = 512
    q_lora_rank: Optional[int] = None
    qk_rope_head_dim: int = 64
    qk_nope_head_dim: int = 128
    v_head_dim: int = 128
    topk_method: str = "greedy"
    n_group: int = 1
    topk_group: int = 1
    scoring_func: str = "softmax"
    norm_topk_prob: bool = False
    num_experts_per_tok: int = 6
    moe_layer_freq: int = 1
    first_k_dense_replace: int = 1
    attention_bias: bool = False
    max_position_embeddings: int = 163840
    rope_theta: float = 10000.0
    # "compressed": cache the shared KV latent (kv_lora_rank + rope dims per
    # token, independent of head count) and absorb kv_b into the query/output
    # sides at attention time — the MLA inference optimization. "full": cache
    # decompressed per-head K/V (the reference's layout, deepseek_v2.py:120-125).
    mla_cache_mode: str = "compressed"

    def __post_init__(self):
        super().__post_init__()
        # MLA: query/key dim differs from value dim.
        self.head_dim = self.qk_nope_head_dim + self.qk_rope_head_dim


@dataclass
class MixtralConfig(BaseConfig):
    """Mixtral 8x7B-style MoE (BASELINE.json config #4; experts stage-local)."""

    model_type: str = "mixtral"
    num_local_experts: int = 8
    num_experts_per_tok: int = 2
    sliding_window: Optional[int] = None


# Arch-name resolution. Mirrors the reference's MODEL_REMAPPING
# (shard/utils.py:14-17): mistral runs through the llama implementation.
MODEL_REMAPPING = {
    "mistral": "llama",
    "qwen2": "llama",
}

CONFIG_REGISTRY: dict[str, type] = {
    "llama": LlamaConfig,
    "qwen3": Qwen3Config,
    "gemma2": Gemma2Config,
    "deepseek_v2": DeepseekV2Config,
    "mixtral": MixtralConfig,
}


def resolve_model_type(model_type: str) -> str:
    return MODEL_REMAPPING.get(model_type, model_type)


def config_from_dict(d: dict[str, Any]):
    original_type = d.get("model_type", "llama")
    model_type = resolve_model_type(original_type)
    if model_type not in CONFIG_REGISTRY:
        raise ValueError(
            f"Model type {model_type!r} not supported. "
            f"Supported: {sorted(CONFIG_REGISTRY)}"
        )
    cls = CONFIG_REGISTRY[model_type]
    d = dict(d)
    d["model_type"] = model_type
    if original_type == "qwen2":
        # Qwen2 uses QKV biases unconditionally and its HF config carries no
        # attention_bias field.
        d.setdefault("attention_bias", True)
    return cls.from_dict(d)
