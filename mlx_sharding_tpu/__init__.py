"""mlx_sharding_tpu — a TPU-native pipeline-sharded LLM serving framework.

A ground-up JAX/XLA re-design of the capability set of mzbac/mlx_sharding
(pipeline-parallel LLM inference with an OpenAI-compatible front end):
stages are pjit/shard_map programs on a TPU mesh, inter-stage hand-off is a
compiled collective over ICI, and the KV cache is a functional HBM-resident
pytree — no RPC, no Python serialization inside the token loop.
"""

__version__ = "0.1.0"

from mlx_sharding_tpu.config import config_from_dict  # noqa: F401
from mlx_sharding_tpu.models import build_model, get_model_class  # noqa: F401
